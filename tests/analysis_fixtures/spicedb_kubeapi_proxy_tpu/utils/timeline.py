"""A004 near-misses (fixture mirrors utils/timeline.py, a
Timeline-gated module): every mutation is dominated by a gate check,
reached only from gated callers, or belongs to a declared
constructed-behind-gate class."""

_EVENTS = []


def enabled():
    return True


def record(stage):
    if not enabled():
        return
    _EVENTS.append(stage)                 # gated: early-return guard


def observe(hist, v):
    if enabled():
        hist.observe(v)                   # gated: if-wrapped


def flush():
    if not enabled():
        return
    _drain()


def _drain():
    # private helper: every same-module caller (flush) gate-checks
    # before calling, so the one-level closure clears it
    _EVENTS.append("drain")


# wrapper only constructed when its gate is on (see create_endpoint)
class GatedRecorder:  # noqa: A004(built behind gate)
    def tick(self, counter):
        counter.inc()
