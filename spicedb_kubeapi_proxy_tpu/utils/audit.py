"""Structured authorization decision audit log (kube audit-policy analog).

PR 1 made wall time attributable (utils/tracing.py); this module makes
*decisions* attributable: every authorization decision the proxy takes —
check pass/fail, prefilter object groups, watch grants/revocations,
post-checks, dual-write commit/rollback — emits a structured `AuditEvent`
carrying user, groups, verb, GVR, object name(s), the matched rule id,
the backend that evaluated it, the decision, the caveat context, the
trace id (correlating with the request trace), and latency.

Hot-path contract (the bench gate: <2% filter-throughput regression with
Metadata auditing on):

- `emit()` NEVER blocks and NEVER raises: the sink is a bounded deque +
  ring buffer; when the writer lags, the new event is dropped and
  `authz_audit_dropped_total{reason="backpressure"}` counts it.
- Level policy mirrors the kube audit stages: `None` (disabled — check
  `sink.enabled` before even building an event), `Metadata` (identity +
  decision, no relationship strings or caveat context), `Request`
  (full event incl. rel strings, caveat context, explain witness).
- Per-user+verb sampling: ALLOWED decisions are sampled 1-in-N per
  (user, verb) key; denials and errors always pass (an audit log that
  samples away denials cannot answer "why was this denied").
- Identities (usernames, object names) live in EVENTS, never in metric
  labels — scripts/lint.py's cardinality gate enforces the split.

The ring buffer backs the authenticated `/debug/decisions` endpoint; the
async writer task (started with the server) renders events as one JSON
line each through a pluggable writer (default: the audit logger).

Thread-safe: decisions are emitted from asyncio handlers and executor
threads concurrently.
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("spicedb_kubeapi_proxy_tpu.audit")

# -- decision outcome enum ---------------------------------------------------
# The single vocabulary shared by request-context (`authz_outcome`),
# metrics, trace attrs, and audit events, so the three surfaces join by
# trace id without value translation (previously `always_allow` vs
# `allowed` vs missing-on-error drifted per surface).

OUTCOME_ALLOWED = "allowed"
OUTCOME_DENIED = "denied"
OUTCOME_ALWAYS_ALLOW = "always_allow"
OUTCOME_CONDITIONAL = "conditional"
OUTCOME_ERROR = "error"
# admission control rejected the request before/while authorizing (429 +
# Retry-After; utils/admission.py) — distinct from `denied` (a policy
# decision) and `error` (a failure): the request was never evaluated
OUTCOME_SHED = "shed"

OUTCOMES = frozenset((OUTCOME_ALLOWED, OUTCOME_DENIED, OUTCOME_ALWAYS_ALLOW,
                      OUTCOME_CONDITIONAL, OUTCOME_ERROR, OUTCOME_SHED))


def normalize_outcome(raw: Optional[str]) -> str:
    """Collapse a context outcome value into the shared enum: unknown or
    missing values (error paths that never set one) become `error`."""
    return raw if raw in OUTCOMES else OUTCOME_ERROR


# -- audit levels ------------------------------------------------------------

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"
LEVEL_REQUEST = "Request"

_LEVELS = {LEVEL_NONE: 0, LEVEL_METADATA: 1, LEVEL_REQUEST: 2}


def parse_level(raw: str) -> str:
    """Case-insensitive level parse; raises ValueError on unknown names."""
    for name in _LEVELS:
        if raw.strip().lower() == name.lower():
            return name
    raise ValueError(
        f"unknown audit level {raw!r}; expected one of {sorted(_LEVELS)}")


# bound the per-event identity payload: one event per object-GROUP, with
# a name sample, never one event per object (a 10k-pod list emits 2)
MAX_NAMES_PER_EVENT = 8
# sampling state is keyed (user, verb); cap the key space so an attacker
# minting usernames cannot grow sink memory without bound
_SAMPLE_STATE_CAP = 8192


@dataclass
class AuditEvent:
    """One authorization decision (or one decision group)."""
    stage: str                    # resolve|match|check|postcheck|postfilter|
    #                               respfilter|watch|update|dualwrite
    decision: str                 # OUTCOME_* enum value
    user: str = ""
    groups: tuple = ()
    verb: str = ""
    api_group: str = ""
    api_version: str = ""
    resource: str = ""
    namespace: str = ""
    names: tuple = ()             # object name(s) the decision covers
    count: int = 0                # group size when > len(names) sampled
    rule: str = ""                # matched ProxyRule name
    backend: str = ""             # jax | embedded | grpc
    # which evaluator produced the decision: cache (decision cache hit) |
    # kernel (device) | oracle (host evaluator) | mixed; "" when the
    # backend doesn't attribute.  Keeps audit truthful when the decision
    # cache answers without touching the evaluator at all.
    decision_source: str = ""
    trace_id: str = ""
    # fleet tracing provenance: the tier chain the request walked to
    # reach this node ("router>leader", "follower>leader", ...) — a
    # forwarded decision names its full hop chain on any node's
    # /debug/decisions, joining the merged trace by trace_id
    tier_path: str = ""
    latency_ms: float = 0.0
    # Request-level payload (dropped at Metadata)
    rel: str = ""                 # the checked relationship string
    caveat_context: Optional[dict] = None
    explain: Optional[dict] = None  # witness dict (authz/explain.py)
    message: str = ""
    ts: float = field(default_factory=time.time)

    def to_dict(self, level: str = LEVEL_REQUEST) -> dict:
        d = {"ts": round(self.ts, 6), "stage": self.stage,
             "decision": self.decision, "user": self.user,
             "groups": list(self.groups), "verb": self.verb,
             "gvr": "/".join((self.api_group, self.api_version,
                              self.resource)),
             "namespace": self.namespace, "names": list(self.names),
             "count": self.count or len(self.names), "rule": self.rule,
             "backend": self.backend, "trace_id": self.trace_id,
             "latency_ms": round(self.latency_ms, 3)}
        if self.decision_source:
            d["decision_source"] = self.decision_source
        if self.tier_path:
            # provenance, not payload: rendered at any emitting level
            # (like decision_source) — it contains tier names only
            d["tier_path"] = self.tier_path
        if self.explain is not None:
            # witnesses are explicitly requested (--audit-explain or
            # ?explain=1): render them at any level that emits at all
            d["explain"] = self.explain
        if _LEVELS.get(level, 0) >= _LEVELS[LEVEL_REQUEST]:
            if self.rel:
                d["rel"] = self.rel
            if self.caveat_context is not None:
                d["caveat_context"] = self.caveat_context
            if self.message:
                d["message"] = self.message
        return d


def _log_writer(line: str) -> None:
    logger.info("%s", line)


class AuditSink:
    """Bounded, non-blocking decision sink.

    emit() appends to a ring buffer (served at /debug/decisions) and to a
    bounded writer deque; a writer task started with the server drains
    the deque into one-JSON-line-per-event output.  Backpressure NEVER
    propagates to the caller: a full deque drops the event and counts it.
    """

    def __init__(self, level: str = LEVEL_METADATA, capacity: int = 1024,
                 ring_capacity: int = 256, sample_every: int = 1,
                 explain: bool = False, backend: str = "",
                 writer: Optional[Callable[[str], None]] = None,
                 registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}")
        self.level = parse_level(level)
        self.capacity = capacity
        self.ring_capacity = ring_capacity
        self.sample_every = sample_every
        # explained denials: decision sites attach the relation-path
        # witness when this is on (or the request carries ?explain=1)
        self.explain = explain
        # default `backend` for events built from this sink (the
        # endpoint's URL-scheme label: jax | embedded | grpc)
        self.backend = backend
        self._writer = writer or _log_writer
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_capacity)
        self._queue: collections.deque = collections.deque()
        self._sample_counts: dict = {}
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if registry is None:
            from . import metrics as m
            registry = m.REGISTRY
        self.emitted_total = registry.counter(
            "authz_audit_events_total",
            "Authorization audit events emitted, by stage and decision",
            labels=("stage", "decision"))
        self.dropped_total = registry.counter(
            "authz_audit_dropped_total",
            "Audit events dropped before reaching the sink, by reason "
            "(level: auditing disabled; sampled: per-user+verb sampling; "
            "backpressure: writer deque full)",
            labels=("reason",))

    # -- hot path ------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        """False at level None: decision sites skip event construction
        entirely (the <2% bench budget is spent nowhere)."""
        return self.level != LEVEL_NONE

    def _sampled_out(self, event: AuditEvent) -> bool:
        """1-in-N per (user, verb) for ALLOWED decisions only; denials,
        errors, and conditionals always pass."""
        if self.sample_every <= 1 or event.decision != OUTCOME_ALLOWED:
            return False
        key = (event.user, event.verb)
        with self._lock:
            if len(self._sample_counts) >= _SAMPLE_STATE_CAP:
                # bounded sampling state: reset rather than grow (a reset
                # re-emits one event per key, never silences one)
                self._sample_counts.clear()
            n = self._sample_counts.get(key, 0)
            self._sample_counts[key] = n + 1
        return n % self.sample_every != 0

    def emit(self, event: AuditEvent) -> bool:
        """Record one decision; returns True when the event was accepted
        (ring + writer deque), False when dropped.  Never blocks, never
        raises."""
        try:
            if not self.enabled:
                self.dropped_total.inc(reason="level")
                return False
            if self._sampled_out(event):
                self.dropped_total.inc(reason="sampled")
                return False
            self.emitted_total.inc(stage=event.stage,
                                   decision=event.decision)
            with self._lock:
                self._ring.append(event)
                if len(self._queue) >= self.capacity:
                    self.dropped_total.inc(reason="backpressure")
                    return False
                self._queue.append(event)
            self._wakeup()
            return True
        except Exception:
            # an audit fault must never fail the request it describes
            logger.exception("audit emit failed")
            return False

    def _wakeup(self) -> None:
        loop, wake = self._loop, self._wake
        if loop is None or wake is None or loop.is_closed():
            return
        try:
            loop.call_soon_threadsafe(wake.set)
        except RuntimeError:
            pass  # loop shut down between the check and the call

    # -- introspection -------------------------------------------------------

    def recent(self, limit: int = 0) -> list:
        """Newest-first ring snapshot as dicts at the sink's level."""
        with self._lock:
            events = list(self._ring)
        events.reverse()
        if limit > 0:
            events = events[:limit]
        return [e.to_dict(self.level) for e in events]

    # -- writer lifecycle ----------------------------------------------------

    async def start(self) -> None:
        """Start the async writer (idempotent); requires a running loop."""
        if self._task is not None and not self._task.done():
            return
        self._loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        self._task = self._loop.create_task(self._drain())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._flush()
        self._loop = None
        self._wake = None

    def _flush(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    return
                event = self._queue.popleft()
            self._write_one(event)

    def _write_one(self, event: AuditEvent) -> None:
        try:
            self._writer(json.dumps(event.to_dict(self.level),
                                    sort_keys=True))
        except Exception:
            logger.exception("audit writer failed")

    async def _drain(self) -> None:
        while True:
            self._flush()
            self._wake.clear()
            with self._lock:
                pending = bool(self._queue)
            if pending:
                continue
            try:
                # the timeout is a liveness net for emits that raced the
                # clear(); the wake event is the fast path
                await asyncio.wait_for(self._wake.wait(), 0.5)
            except asyncio.TimeoutError:
                pass


class _NullSink(AuditSink):
    """Shared disabled sink: the default wiring when auditing is off, so
    decision sites can call `sink.enabled` unconditionally."""

    def __init__(self):
        super().__init__(level=LEVEL_NONE)

    def emit(self, event: AuditEvent) -> bool:  # pragma: no cover - trivial
        return False


NULL_SINK = _NullSink()
