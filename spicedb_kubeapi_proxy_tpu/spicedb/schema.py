"""SpiceDB schema DSL parser -> schema IR.

Parses the subset of the SpiceDB schema language the reference uses in its
bootstrap schemas (reference pkg/spicedb/bootstrap.yaml:1-41 and the e2e
schemas in e2e/proxy_test.go): `use` directives, `definition` blocks with
`relation` declarations (union types, subject relations `type#rel`, wildcards
`type:*`, `with expiration`) and `permission` expressions (union `+`,
intersection `&`, exclusion `-`, arrow `->`, `nil`, parentheses).

The IR doubles as the input to the TPU schema compiler (ops/graph_compile.py)
which lowers permission expressions onto the iterative boolean-SpMV program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .types import SchemaError

# ---------------------------------------------------------------------------
# IR
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TypeRef:
    """One allowed subject type of a relation: `user`, `group#member`,
    `user:*`, `activity with expiration`."""
    type: str
    relation: str = ""      # subject relation ("" = direct subject)
    wildcard: bool = False  # type:*
    traits: tuple = ()      # e.g. ("expiration",)


class Expr:
    __slots__ = ()


@dataclass(frozen=True)
class Nil(Expr):
    pass


@dataclass(frozen=True)
class RelRef(Expr):
    """Reference to a relation or permission on the same definition."""
    name: str


@dataclass(frozen=True)
class Arrow(Expr):
    """`left->target`: for each subject object of relation `left`, evaluate
    `target` on it."""
    left: str
    target: str


@dataclass(frozen=True)
class Union(Expr):
    children: tuple


@dataclass(frozen=True)
class Intersection(Expr):
    children: tuple


@dataclass(frozen=True)
class Exclusion(Expr):
    base: Expr
    subtract: Expr


@dataclass
class Definition:
    name: str
    relations: dict = field(default_factory=dict)    # name -> list[TypeRef]
    permissions: dict = field(default_factory=dict)  # name -> Expr

    def has_relation_or_permission(self, name: str) -> bool:
        return name in self.relations or name in self.permissions


@dataclass
class Caveat:
    """`caveat name(param type, ...) { cel-expression }`.  The body is a CEL
    expression evaluated against the merged tuple/request context; a tuple
    carrying this caveat grants CONDITIONAL permission until the context
    decides it (the reference proxy skips CONDITIONAL LookupResources
    results, pkg/authz/lookups.go:85-88)."""
    name: str
    params: tuple          # ((param name, type source text), ...)
    body_src: str          # raw CEL source between the braces

    def __post_init__(self):
        self._prog = None

    def evaluate(self, context: dict) -> Optional[bool]:
        """True/False when decidable with `context`; None (CONDITIONAL)
        when required parameters are missing."""
        missing = [n for (n, _) in self.params if n not in context]
        if missing:
            return None
        if self._prog is None:
            from ..rules import cel  # lazy: schema is imported by rules
            self._prog = cel.compile_expression(self.body_src)
        out = self._prog.eval(dict(context))
        if not isinstance(out, bool):
            from .types import SchemaError as _SE
            raise _SE(f"caveat {self.name!r} returned {type(out).__name__},"
                      f" expected bool")
        return out


@dataclass
class Schema:
    definitions: dict = field(default_factory=dict)  # name -> Definition
    caveats: dict = field(default_factory=dict)      # name -> Caveat
    uses: tuple = ()

    def definition(self, type_name: str) -> Definition:
        d = self.definitions.get(type_name)
        if d is None:
            raise SchemaError(f"object definition `{type_name}` not found")
        return d

    def max_rewrite_depth(self) -> int:
        """Upper bound on acyclic rewrite nesting: used by the TPU compiler
        to size the `lax.scan` iteration count.  Recursive schemas (e.g.
        group#member in group membership) contribute via tuple-graph depth,
        not rewrite depth; see ops/graph_compile.py."""
        depths: dict[tuple, int] = {}

        def expr_depth(def_name: str, e: Expr, stack: frozenset) -> int:
            if isinstance(e, Nil):
                return 0
            if isinstance(e, RelRef):
                return ref_depth(def_name, e.name, stack)
            if isinstance(e, Arrow):
                # target evaluated on other definitions; bound separately
                best = 0
                for d in self.definitions.values():
                    if e.target in d.permissions or e.target in d.relations:
                        best = max(best, ref_depth(d.name, e.target, stack))
                return 1 + best
            if isinstance(e, (Union, Intersection)):
                return max((expr_depth(def_name, c, stack) for c in e.children),
                           default=0)
            if isinstance(e, Exclusion):
                return max(expr_depth(def_name, e.base, stack),
                           expr_depth(def_name, e.subtract, stack))
            raise SchemaError(f"unknown expr {e!r}")

        def ref_depth(def_name: str, name: str, stack: frozenset) -> int:
            key = (def_name, name)
            if key in stack:
                return 0  # recursive cycle; handled by iteration count
            if key in depths:
                return depths[key]
            d = self.definitions.get(def_name)
            if d is None:
                return 0
            if name in d.permissions:
                v = 1 + expr_depth(def_name, d.permissions[name], stack | {key})
            else:
                v = 1
            depths[key] = v
            return v

        best = 0
        for d in self.definitions.values():
            for p in d.permissions:
                best = max(best, ref_depth(d.name, p, frozenset()))
        return best


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_PUNCT = ["->", "{", "}", "(", ")", ":", "#", "|", "+", "&", "-", "=", ";",
          ",", "*", "/", "<", ">"]


def _tokenize(src: str) -> list:
    toks = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
            continue
        if src.startswith("/*", i):
            end = src.find("*/", i + 2)
            if end < 0:
                raise SchemaError(f"unterminated block comment at {i}")
            i = end + 2
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            toks.append(("ident", src[i:j], i))
            i = j
            continue
        if c in "\"'":
            # string literals only occur inside caveat bodies, which are
            # skipped; tokenize so the skipper can walk over them
            j = i + 1
            while j < n and src[j] != c:
                j += 2 if src[j] == "\\" else 1
            if j >= n:
                raise SchemaError(f"unterminated string at offset {i}")
            toks.append(("str", src[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit():
            j = i
            while j < n and (src[j].isdigit() or src[j] == "."):
                j += 1
            toks.append(("num", src[i:j], i))
            i = j
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(("punct", p, i))
                i += len(p)
                break
        else:
            raise SchemaError(f"unexpected character {c!r} at offset {i}")
    toks.append(("eof", "", n))
    return toks


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class _P:
    def __init__(self, toks: list, src: str = ""):
        self.toks = toks
        self.src = src
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def at(self, val: str) -> bool:
        k, v, _ = self.peek()
        return k == "punct" and v == val

    def eat(self, val: str) -> bool:
        if self.at(val):
            self.next()
            return True
        return False

    def expect_punct(self, val: str):
        k, v, pos = self.next()
        if k != "punct" or v != val:
            raise SchemaError(f"expected {val!r} at offset {pos}, got {v!r}")

    def expect_ident(self, what: str = "identifier") -> str:
        k, v, pos = self.next()
        if k != "ident":
            raise SchemaError(f"expected {what} at offset {pos}, got {v!r}")
        return v

    def qualified_name(self) -> str:
        """`name` or `prefix/name` (SpiceDB permits namespaced definitions)."""
        name = self.expect_ident("definition name")
        while self.eat("/"):
            name += "/" + self.expect_ident("name component")
        return name

    # -- grammar ------------------------------------------------------------

    def parse_schema(self) -> Schema:
        schema = Schema()
        uses = []
        while True:
            k, v, pos = self.peek()
            if k == "eof":
                break
            if k == "ident" and v == "use":
                self.next()
                uses.append(self.expect_ident("feature name"))
                continue
            if k == "ident" and v == "definition":
                d = self.parse_definition()
                if d.name in schema.definitions:
                    raise SchemaError(f"duplicate definition {d.name!r}")
                schema.definitions[d.name] = d
                continue
            if k == "ident" and v == "caveat":
                c = self.parse_caveat()
                if c.name in schema.caveats:
                    raise SchemaError(f"duplicate caveat {c.name!r}")
                schema.caveats[c.name] = c
                continue
            raise SchemaError(f"unexpected token {v!r} at offset {pos}")
        schema.uses = tuple(uses)
        _validate(schema)
        return schema

    def parse_caveat(self) -> Caveat:
        """`caveat name(param type, ...) { cel-expression }`."""
        self.next()  # 'caveat'
        name = self.expect_ident("caveat name")
        self.expect_punct("(")
        params = []
        while not self.eat(")"):
            pname = self.expect_ident("caveat parameter name")
            # the type is a free-form token run (`int`, `list<string>`,
            # `map<any>`, ...) up to `,` or `)`
            type_parts = []
            depth = 0
            while True:
                k, v, pos = self.peek()
                if k == "eof":
                    raise SchemaError("unterminated caveat parameter list")
                if depth == 0 and k == "punct" and v in (",", ")"):
                    break
                if k == "punct" and v == "<":
                    depth += 1
                elif k == "punct" and v == ">":
                    depth -= 1
                type_parts.append(v)
                self.next()
            if not type_parts:
                raise SchemaError(
                    f"caveat parameter {pname!r} missing a type")
            params.append((pname, "".join(type_parts)))
            self.eat(",")
        k, v, start = self.peek()
        self.expect_punct("{")
        depth = 1
        end = start
        while depth:
            k, v, end = self.next()
            if k == "eof":
                raise SchemaError("unterminated caveat body")
            if v == "{":
                depth += 1
            elif v == "}":
                depth -= 1
        body = self.src[start + 1: end].strip() if self.src else ""
        if not body:
            raise SchemaError(f"caveat {name!r} has an empty body")
        return Caveat(name=name, params=tuple(params), body_src=body)

    def parse_definition(self) -> Definition:
        self.next()  # 'definition'
        d = Definition(name=self.qualified_name())
        self.expect_punct("{")
        while not self.eat("}"):
            k, v, pos = self.peek()
            if k == "ident" and v == "relation":
                self.next()
                name = self.expect_ident("relation name")
                self.expect_punct(":")
                refs = [self.parse_type_ref()]
                while self.eat("|"):
                    refs.append(self.parse_type_ref())
                self.eat(";")
                if d.has_relation_or_permission(name):
                    raise SchemaError(
                        f"duplicate relation/permission {name!r} on {d.name}")
                d.relations[name] = refs
            elif k == "ident" and v == "permission":
                self.next()
                name = self.expect_ident("permission name")
                self.expect_punct("=")
                expr = self.parse_perm_expr()
                self.eat(";")
                if d.has_relation_or_permission(name):
                    raise SchemaError(
                        f"duplicate relation/permission {name!r} on {d.name}")
                d.permissions[name] = expr
            else:
                raise SchemaError(
                    f"expected relation or permission at offset {pos}, got {v!r}")
        return d

    def parse_type_ref(self) -> TypeRef:
        t = self.qualified_name()
        relation = ""
        wildcard = False
        if self.eat(":"):
            self.expect_punct("*")
            wildcard = True
        elif self.eat("#"):
            relation = self.expect_ident("subject relation")
        traits = []
        while True:
            k, v, _ = self.peek()
            if k == "ident" and v == "with":
                self.next()
                traits.append(self.expect_ident("trait name"))
                # `with caveat_name and expiration` continuation
                while True:
                    k2, v2, _ = self.peek()
                    if k2 == "ident" and v2 == "and":
                        self.next()
                        traits.append(self.expect_ident("trait name"))
                    else:
                        break
            else:
                break
        return TypeRef(type=t, relation=relation, wildcard=wildcard,
                       traits=tuple(traits))

    # precedence: `+` (lowest) < `&` < `-` (tightest), all left-assoc,
    # matching the SpiceDB schema DSL
    def parse_perm_expr(self) -> Expr:
        return self.parse_union()

    def parse_union(self) -> Expr:
        left = self.parse_intersection()
        children = [left]
        while self.eat("+"):
            children.append(self.parse_intersection())
        if len(children) == 1:
            return left
        return Union(tuple(children))

    def parse_intersection(self) -> Expr:
        left = self.parse_exclusion()
        children = [left]
        while self.eat("&"):
            children.append(self.parse_exclusion())
        if len(children) == 1:
            return left
        return Intersection(tuple(children))

    def parse_exclusion(self) -> Expr:
        left = self.parse_base()
        while self.eat("-"):
            left = Exclusion(left, self.parse_base())
        return left

    def parse_base(self) -> Expr:
        if self.eat("("):
            e = self.parse_perm_expr()
            self.expect_punct(")")
            return e
        k, v, pos = self.next()
        if k != "ident":
            raise SchemaError(f"expected expression at offset {pos}, got {v!r}")
        if v == "nil":
            return Nil()
        name = v
        if self.at("->"):
            self.next()
            target = self.expect_ident("arrow target")
            return Arrow(name, target)
        return RelRef(name)


def _validate(schema: Schema) -> None:
    for d in schema.definitions.values():
        for rel_name, refs in d.relations.items():
            for ref in refs:
                target = schema.definitions.get(ref.type)
                if target is None:
                    raise SchemaError(
                        f"{d.name}#{rel_name}: unknown subject type {ref.type!r}")
                if ref.relation and not target.has_relation_or_permission(ref.relation):
                    raise SchemaError(
                        f"{d.name}#{rel_name}: {ref.type!r} has no relation"
                        f" or permission {ref.relation!r}")
                for trait in ref.traits:
                    if trait != "expiration" and trait not in schema.caveats:
                        raise SchemaError(
                            f"{d.name}#{rel_name}: unknown trait/caveat"
                            f" {trait!r}")
        for perm_name, expr in d.permissions.items():
            _validate_expr(schema, d, perm_name, expr)


def _validate_expr(schema: Schema, d: Definition, perm: str, e: Expr) -> None:
    if isinstance(e, Nil):
        return
    if isinstance(e, RelRef):
        if not d.has_relation_or_permission(e.name):
            raise SchemaError(
                f"{d.name}#{perm}: references unknown relation/permission {e.name!r}")
        return
    if isinstance(e, Arrow):
        if e.left not in d.relations:
            raise SchemaError(
                f"{d.name}#{perm}: arrow left side {e.left!r} must be a relation"
                f" on {d.name}")
        return
    if isinstance(e, (Union, Intersection)):
        for c in e.children:
            _validate_expr(schema, d, perm, c)
        return
    if isinstance(e, Exclusion):
        _validate_expr(schema, d, perm, e.base)
        _validate_expr(schema, d, perm, e.subtract)
        return
    raise SchemaError(f"unknown expression node {e!r}")


def parse_schema(src: str) -> Schema:
    return _P(_tokenize(src), src).parse_schema()


def validate_relationship(schema: Schema, rel) -> None:
    """Reject writes the schema does not permit — the behavior of SpiceDB's
    WriteRelationships validation behind the reference's embedded server:
    undefined resource/subject types, writes to permissions or undeclared
    relations, subject types/sub-relations a relation does not accept,
    wildcard subjects without a `type:*` annotation, and caveats that are
    not declared in the schema.  Raises SchemaError."""
    d = schema.definition(rel.resource.type)
    relation = rel.relation
    if relation in d.permissions:
        raise SchemaError(
            f"cannot write relationship to permission "
            f"`{rel.resource.type}#{relation}`")
    refs = d.relations.get(relation)
    if refs is None:
        raise SchemaError(
            f"relation `{relation}` not found on definition "
            f"`{rel.resource.type}`")
    schema.definition(rel.subject.type)  # subject type must exist
    caveat = getattr(rel, "caveat", None)
    if caveat is not None and caveat.name not in schema.caveats:
        raise SchemaError(f"caveat `{caveat.name}` not found in schema")
    sub_rel = rel.subject.relation or ""
    wildcard = rel.subject.id == "*"
    # the traits the written tuple carries must be exactly what a matching
    # type annotation requires: `user with c` accepts only c-caveated
    # tuples, plain `user` only trait-free ones (SpiceDB semantics —
    # permit both by declaring `user | user with c`)
    tuple_traits = set()
    if caveat is not None:
        tuple_traits.add(caveat.name)
    if getattr(rel, "expires_at", None) is not None:
        tuple_traits.add("expiration")
    for ref in refs:
        if ref.type != rel.subject.type:
            continue
        if set(ref.traits) != tuple_traits:
            continue
        if wildcard:
            if ref.wildcard:
                break
            continue
        if not ref.wildcard and (ref.relation or "") == sub_rel:
            break
    else:
        want = (f"{rel.subject.type}:*" if wildcard
                else rel.subject.type + (f"#{sub_rel}" if sub_rel else ""))
        if tuple_traits:
            want += " with " + " and ".join(sorted(tuple_traits))
        raise SchemaError(
            f"subject `{want}` is not allowed on relation "
            f"`{rel.resource.type}#{relation}`")
