"""Seeded differential fuzzing harness (docs/fuzzing.md).

Three parts, one contract: a seed integer fully determines a
(schema, delta-stream, query-stream) triple, the triple replays
identically against the `jax://` device kernels and the host oracle at
pinned revisions, and any answer mismatch anywhere in the gate matrix
(DecisionCache x DevicePipeline x AsyncRebuild) or the replication role
matrix (leader / 2-hop follower chain / promoted leader) surfaces as a
one-line reproducible seed that shrinks to a self-contained artifact.

- `schema_gen`   random schemas (bounded-depth rewrites, arrows,
  intersections/exclusions, wildcards, CEL caveats, expiring
  relations), biased toward deep/entangled closures via
  `relation_footprint`
- `delta_gen`    random delta streams (writes, deletes,
  delete_by_filter, bulk loads, TTL churn against a FAKE clock,
  wildcard flips, plane-less caveats that force quarantine/rebuild)
- `driver`       the differential replay across gates x roles
- `shrink`       delta-stream minimizer + repro artifacts
- `scenarios`    the three first-class bench scenario workloads
  (caveat-heavy / wildcard-public / ephemeral-grants) + fuzz biases
- `metrics`      `authz_fuzz_*` counters (FuzzTelemetry gate)
"""

from .driver import (  # noqa: F401
    GATE_COMBOS,
    ROLES,
    Divergence,
    FuzzCase,
    build_case,
    run_case,
    smoke_cell_for,
)
from .schema_gen import generate_schema  # noqa: F401
from .shrink import (  # noqa: F401
    load_artifact,
    replay_artifact,
    shrink_case,
    write_artifact,
)
