"""Unit tests for the k8s protobuf envelope codec (proxy/k8sproto.py).

Covers every public function, hostile/truncated input handling, and —
crucially — cross-validation against the REAL protobuf runtime
(google.protobuf with dynamically-built descriptors mirroring
k8s.io/apimachinery runtime.Unknown + meta/v1 ObjectMeta), so the
hand-rolled wire splicing can't drift into a private dialect.

Reference behavior: pkg/authz/responsefilterer.go:241-301 (decode /
re-encode negotiated protobuf bodies; reject unrecognized).
"""

import pytest
from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from spicedb_kubeapi_proxy_tpu.proxy import k8sproto
from spicedb_kubeapi_proxy_tpu.proxy.k8sproto import (
    K8S_MAGIC,
    K8sProtoError,
    decode_unknown,
    encode_list,
    encode_object,
    encode_object_meta,
    encode_table,
    encode_unknown,
    field_bytes,
    filter_list_raw,
    filter_table_raw,
    is_k8s_proto,
    iter_list_items,
    object_meta,
    records,
)


# -- dynamic descriptors mirroring the k8s proto layout -----------------------

def _build_k8s_messages():
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "k8s_mirror.proto"
    fdp.package = "k8smirror"
    fdp.syntax = "proto2"

    def msg(name, fields):
        m = fdp.message_type.add()
        m.name = name
        for num, fname, ftype, extra in fields:
            f = m.field.add()
            f.name = fname
            f.number = num
            f.label = (descriptor_pb2.FieldDescriptorProto.LABEL_REPEATED
                       if extra.get("repeated")
                       else descriptor_pb2.FieldDescriptorProto.LABEL_OPTIONAL)
            f.type = ftype
            if "type_name" in extra:
                f.type_name = ".k8smirror." + extra["type_name"]

    T = descriptor_pb2.FieldDescriptorProto
    msg("TypeMeta", [(1, "apiVersion", T.TYPE_STRING, {}),
                     (2, "kind", T.TYPE_STRING, {})])
    msg("Unknown", [(1, "typeMeta", T.TYPE_MESSAGE, {"type_name": "TypeMeta"}),
                    (2, "raw", T.TYPE_BYTES, {}),
                    (3, "contentEncoding", T.TYPE_STRING, {}),
                    (4, "contentType", T.TYPE_STRING, {})])
    # meta/v1 ObjectMeta prefix: name=1, generateName=2, namespace=3,
    # plus a high-numbered field to prove unknown fields survive splicing
    msg("ObjectMeta", [(1, "name", T.TYPE_STRING, {}),
                       (2, "generateName", T.TYPE_STRING, {}),
                       (3, "namespace", T.TYPE_STRING, {}),
                       (11, "labels_blob", T.TYPE_BYTES, {})])
    msg("Object", [(1, "metadata", T.TYPE_MESSAGE, {"type_name": "ObjectMeta"}),
                   (2, "spec_blob", T.TYPE_BYTES, {})])
    msg("ListMeta", [(2, "resourceVersion", T.TYPE_STRING, {})])
    msg("List", [(1, "metadata", T.TYPE_MESSAGE, {"type_name": "ListMeta"}),
                 (2, "items", T.TYPE_MESSAGE,
                  {"type_name": "Object", "repeated": True})])
    msg("RawExtension", [(1, "raw", T.TYPE_BYTES, {})])
    msg("TableRow", [(1, "cells", T.TYPE_MESSAGE,
                      {"type_name": "RawExtension", "repeated": True}),
                     (3, "object", T.TYPE_MESSAGE,
                      {"type_name": "RawExtension"})])
    msg("Table", [(1, "metadata", T.TYPE_MESSAGE, {"type_name": "ListMeta"}),
                  (3, "rows", T.TYPE_MESSAGE,
                   {"type_name": "TableRow", "repeated": True})])

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    return {name: message_factory.GetMessageClass(
        pool.FindMessageTypeByName(f"k8smirror.{name}"))
        for name in ("TypeMeta", "Unknown", "ObjectMeta", "Object",
                     "ListMeta", "List", "RawExtension", "TableRow", "Table")}


M = _build_k8s_messages()


def real_object(name, namespace="", extra=b""):
    o = M["Object"]()
    o.metadata.name = name
    if namespace:
        o.metadata.namespace = namespace
    if extra:
        o.metadata.labels_blob = extra
    return o


def real_envelope(api_version, kind, raw, content_type=""):
    u = M["Unknown"]()
    u.typeMeta.apiVersion = api_version
    u.typeMeta.kind = kind
    u.raw = raw
    if content_type:
        u.contentType = content_type
    return K8S_MAGIC + u.SerializeToString()


# -- wire primitives ----------------------------------------------------------

class TestRecords:
    def test_all_wire_types(self):
        # field1 varint, field2 LD, field3 fixed64, field4 fixed32
        buf = (b"\x08\x96\x01"              # 1: varint 150
               b"\x12\x03abc"               # 2: LD "abc"
               b"\x19" + b"\x11" * 8 +      # 3: fixed64
               b"\x25" + b"\x22" * 4)       # 4: fixed32
        recs = list(records(buf))
        assert [(f, wt) for f, wt, _, _, _ in recs] == [
            (1, 0), (2, 2), (3, 1), (4, 5)]
        assert recs[0][4] == 150
        assert recs[1][4] == b"abc"
        assert recs[2][4] == b"\x11" * 8
        assert recs[3][4] == b"\x22" * 4
        # start/end offsets tile the buffer exactly
        assert recs[0][2] == 0
        assert all(recs[i][3] == recs[i + 1][2] for i in range(3))
        assert recs[-1][3] == len(buf)

    def test_matches_real_protobuf_offsets(self):
        o = real_object("p0", "team-a", extra=b"\x00\xffblob")
        raw = o.SerializeToString()
        # re-concatenating every record reproduces the buffer byte-exactly
        out = b"".join(raw[s:e] for _, _, s, e, _ in records(raw))
        assert out == raw

    @pytest.mark.parametrize("buf,err", [
        (b"\x08", "truncated varint"),                 # key then nothing
        (b"\x12\x05ab", "truncated length-delimited"),  # LD len 5, 2 bytes
        (b"\x19\x00", "truncated fixed64"),
        (b"\x25\x00", "truncated fixed32"),
        (b"\x0b", "unsupported wire type"),             # wt=3 group start
        (b"\x0c", "unsupported wire type"),             # wt=4 group end
        (b"\x08" + b"\xff" * 10 + b"\x01", "varint too long"),
    ])
    def test_hostile_input(self, buf, err):
        with pytest.raises(K8sProtoError, match=err):
            list(records(buf))

    def test_field_bytes_last_occurrence(self):
        buf = b"\x12\x01a" + b"\x12\x01b" + b"\x08\x01"
        assert field_bytes(buf, 2) == b"b"
        assert field_bytes(buf, 1) is None  # varint, not LD
        assert field_bytes(buf, 9) is None


# -- envelope -----------------------------------------------------------------

class TestEnvelope:
    def test_is_k8s_proto(self):
        assert is_k8s_proto(K8S_MAGIC + b"anything")
        assert not is_k8s_proto(b'{"kind":"Pod"}')
        assert not is_k8s_proto(b"")

    def test_decode_real_unknown(self):
        body = real_envelope("v1", "PodList", b"rawbytes",
                             "application/vnd.kubernetes.protobuf")
        av, kind, raw, ct = decode_unknown(body)
        assert (av, kind, raw, ct) == (
            "v1", "PodList", b"rawbytes",
            "application/vnd.kubernetes.protobuf")

    def test_encode_parsed_by_real_protobuf(self):
        body = encode_unknown("apps/v1", "DeploymentList", b"\x01\x02",
                              "application/vnd.kubernetes.protobuf")
        u = M["Unknown"]()
        u.ParseFromString(body[len(K8S_MAGIC):])
        assert u.typeMeta.apiVersion == "apps/v1"
        assert u.typeMeta.kind == "DeploymentList"
        assert u.raw == b"\x01\x02"
        assert u.contentType == "application/vnd.kubernetes.protobuf"

    def test_round_trip(self):
        body = encode_unknown("v1", "Pod", b"payload")
        assert decode_unknown(body) == ("v1", "Pod", b"payload", "")

    def test_missing_magic(self):
        with pytest.raises(K8sProtoError, match="magic"):
            decode_unknown(b"\x0a\x04")

    def test_truncated_envelope(self):
        good = real_envelope("v1", "Pod", b"x" * 50)
        with pytest.raises(K8sProtoError):
            decode_unknown(good[:-10])


# -- object meta --------------------------------------------------------------

class TestObjectMeta:
    def test_real_object(self):
        raw = real_object("p1", "team-b").SerializeToString()
        assert object_meta(raw) == ("team-b", "p1")

    def test_cluster_scoped(self):
        raw = real_object("node-1").SerializeToString()
        assert object_meta(raw) == ("", "node-1")

    def test_no_metadata(self):
        assert object_meta(b"") == ("", "")

    def test_encode_object_meta_parsed_by_real(self):
        raw = encode_object_meta("p0", "ns0")
        om = M["ObjectMeta"]()
        om.ParseFromString(raw)
        assert (om.name, om.namespace) == ("p0", "ns0")

    def test_encode_object_round_trip(self):
        raw = encode_object("v1", "Pod", "p0", "ns0")
        assert object_meta(raw) == ("ns0", "p0")


# -- list filtering -----------------------------------------------------------

class TestListFilter:
    def _real_list(self, specs):
        lst = M["List"]()
        lst.metadata.resourceVersion = "42"
        for name, ns in specs:
            lst.items.append(real_object(name, ns, extra=b"\xde\xad" * 8))
        return lst.SerializeToString()

    def test_filter_drops_disallowed(self):
        raw = self._real_list([("p0", "a"), ("p1", "b"), ("p2", "a")])
        out = filter_list_raw(raw, lambda ns, n: ns == "a")
        lst = M["List"]()
        lst.ParseFromString(out)
        assert [i.metadata.name for i in lst.items] == ["p0", "p2"]
        assert lst.metadata.resourceVersion == "42"  # ListMeta preserved

    def test_allowed_items_byte_exact(self):
        raw = self._real_list([("p0", "a"), ("p1", "b")])
        out = filter_list_raw(raw, lambda ns, n: True)
        assert out == raw  # nothing re-encoded, verbatim copy

    def test_filter_all_gone(self):
        raw = self._real_list([("p0", "a")])
        out = filter_list_raw(raw, lambda ns, n: False)
        lst = M["List"]()
        lst.ParseFromString(out)
        assert len(lst.items) == 0
        assert lst.metadata.resourceVersion == "42"

    def test_iter_list_items(self):
        raw = self._real_list([("p0", "a"), ("p1", "b")])
        items = list(iter_list_items(raw))
        assert [object_meta(i) for i in items] == [("a", "p0"), ("b", "p1")]

    def test_encode_list_round_trip(self):
        body = encode_list("v1", "PodList", [
            encode_object("v1", "Pod", "p0", "a"),
            encode_object("v1", "Pod", "p1", "b")])
        av, kind, raw, ct = decode_unknown(body)
        assert (av, kind) == ("v1", "PodList")
        assert [object_meta(i) for i in iter_list_items(raw)] == [
            ("a", "p0"), ("b", "p1")]

    def test_truncated_list_raises(self):
        raw = self._real_list([("p0", "a")])
        with pytest.raises(K8sProtoError):
            filter_list_raw(raw[:-3], lambda ns, n: True)


# -- table filtering ----------------------------------------------------------

class TestTableFilter:
    def _real_table(self, specs, enveloped=True):
        t = M["Table"]()
        t.metadata.resourceVersion = "7"
        for name, ns in specs:
            row = t.rows.add()
            obj_raw = real_object(name, ns).SerializeToString()
            if enveloped:
                obj_raw = real_envelope("meta.k8s.io/v1",
                                        "PartialObjectMetadata", obj_raw)
            row.object.raw = obj_raw
        return t.SerializeToString()

    @pytest.mark.parametrize("enveloped", [True, False])
    def test_filter_rows(self, enveloped):
        raw = self._real_table([("p0", "a"), ("p1", "b")], enveloped)
        out = filter_table_raw(raw, lambda ns, n: ns == "a")
        t = M["Table"]()
        t.ParseFromString(out)
        assert len(t.rows) == 1
        assert t.metadata.resourceVersion == "7"

    def test_rows_without_object_kept(self):
        t = M["Table"]()
        t.rows.add()  # no object at all -> ("", "")
        out = filter_table_raw(t.SerializeToString(),
                               lambda ns, n: (ns, n) == ("", ""))
        t2 = M["Table"]()
        t2.ParseFromString(out)
        assert len(t2.rows) == 1

    def test_encode_table_round_trip(self):
        body = encode_table([
            real_envelope("meta.k8s.io/v1", "PartialObjectMetadata",
                          real_object("p0", "a").SerializeToString()),
            real_object("p1", "b").SerializeToString()])
        av, kind, raw, ct = decode_unknown(body)
        assert kind == "Table"
        out = filter_table_raw(raw, lambda ns, n: n == "p1")
        t = M["Table"]()
        t.ParseFromString(out)
        assert len(t.rows) == 1
