"""Regression tests for the event-loop-hygiene fixes the static
analyzer (docs/static-analysis.md) drove in this round:

- A001 follower._bootstrap/_apply_record: checkpoint / bulk-sidecar
  bytes are spooled + npz-parsed OFF the serving loop (_spool_npz);
- A001 class, leader._serve_file: segment/checkpoint bytes are read off
  the loop (one disk read per follower fetch used to park the leader);
- A001 class, write path: store.write / delete_by_filter — which
  journal through the WAL (append + fsync) BEFORE becoming visible —
  run on an executor for both embedded:// and jax://, so a durable
  store's disk barrier never stalls the loop;
- embedded bulk checks snapshot under the store lock (writes now land
  from executor threads, and a bulk must never span two revisions);
- A004 admission.note_rejected: inert when the AdmissionControl
  killswitch is off.
"""

import asyncio
import threading

from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""


def _seed():
    return [parse_relationship(f"doc:d{i}#viewer@user:u{i % 3}")
            for i in range(12)]


class TestWritesOffLoop:
    """store.write/delete_by_filter carry the WAL fsync; they must run
    on an executor thread for every store-backed endpoint scheme."""

    def _assert_write_thread(self, url):
        ep = create_endpoint(url, Bootstrap(schema_text=SCHEMA))
        ep.store.bulk_load(_seed())
        inner_write = ep.store.write
        seen = []

        def spy(updates, preconditions=()):
            seen.append(threading.current_thread())
            return inner_write(updates, preconditions)

        ep.store.write = spy
        try:
            async def go():
                loop_thread = threading.current_thread()
                rev = await ep.write_relationships([RelationshipUpdate(
                    UpdateOp.TOUCH,
                    parse_relationship("doc:d0#viewer@user:w"))])
                assert rev == ep.store.revision
                assert seen and all(t is not loop_thread for t in seen), (
                    "store.write (WAL append + fsync) ran ON the event "
                    "loop")
                # read-your-writes still holds through the hop
                res = await ep.check_permission(CheckRequest(
                    ObjectRef("doc", "d0"), "view",
                    SubjectRef("user", "w")))
                assert res.allowed

            asyncio.run(go())
        finally:
            ep.store.write = inner_write

    def test_embedded_write_off_loop(self):
        self._assert_write_thread("embedded://")

    def test_jax_write_off_loop(self):
        self._assert_write_thread("jax://")

    def test_embedded_delete_off_loop(self):
        ep = create_endpoint("embedded://", Bootstrap(schema_text=SCHEMA))
        ep.store.bulk_load(_seed())
        inner = ep.store.delete_by_filter
        seen = []

        def spy(flt, preconditions=()):
            seen.append(threading.current_thread())
            return inner(flt, preconditions)

        ep.store.delete_by_filter = spy
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipFilter,
        )

        async def go():
            loop_thread = threading.current_thread()
            await ep.delete_relationships(
                RelationshipFilter(resource_type="doc", resource_id="d1"))
            assert seen and seen[0] is not loop_thread

        asyncio.run(go())

    def test_embedded_eval_holds_store_lock(self):
        """With writes committing from executor threads, the single
        check (evaluation + checked_at read) and the lookup enumeration
        must each run UNDER the store lock — an unlocked revision read
        could stamp a verdict with a revision the evaluation never saw,
        and a mid-enumeration write yields a lookup correct at no
        single revision."""
        ep = create_endpoint("embedded://", Bootstrap(schema_text=SCHEMA))
        ep.store.bulk_load(_seed())
        seen = {}
        real_check3 = ep.evaluator.check3
        real_lookup = ep.evaluator.lookup_resources

        def spy_check(*a, **k):
            seen["check_locked"] = ep.store.lock._is_owned()
            return real_check3(*a, **k)

        def spy_lookup(*a, **k):
            seen["lookup_locked"] = ep.store.lock._is_owned()
            return real_lookup(*a, **k)

        ep.evaluator.check3 = spy_check
        ep.evaluator.lookup_resources = spy_lookup

        async def go():
            res = await ep.check_permission(CheckRequest(
                ObjectRef("doc", "d0"), "view",
                SubjectRef("user", "u0")))
            assert res.checked_at == ep.store.revision
            ids = await ep.lookup_resources(
                "doc", "view", SubjectRef("user", "u0"))
            assert "d0" in set(ids)

        asyncio.run(go())
        assert seen["check_locked"], (
            "check3 + checked_at read ran without the store lock")
        assert seen["lookup_locked"], (
            "oracle lookup enumeration ran without the store lock")

    def test_embedded_bulk_check_never_spans_revisions(self):
        """Writes land from executor threads now; a bulk check must
        still answer at ONE revision (the store-lock snapshot)."""
        ep = create_endpoint("embedded://", Bootstrap(schema_text=SCHEMA))
        ep.store.bulk_load(_seed())
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                ep.store.write([RelationshipUpdate(
                    UpdateOp.TOUCH,
                    parse_relationship(f"doc:d{i % 12}#viewer@user:c"))])
                i += 1

        t = threading.Thread(target=churn)
        t.start()
        try:
            async def go():
                for _ in range(50):
                    res = await ep.check_bulk_permissions([
                        CheckRequest(ObjectRef("doc", f"d{k}"), "view",
                                     SubjectRef("user", f"u{k % 3}"))
                        for k in range(8)])
                    revs = {r.checked_at for r in res}
                    assert len(revs) == 1, (
                        f"torn bulk check across revisions {revs}")
                    await asyncio.sleep(0)

            asyncio.run(go())
        finally:
            stop.set()
            t.join()


class TestReplicationOffLoop:
    def test_follower_spools_npz_off_loop(self, monkeypatch, tmp_path):
        """_spool_npz (checkpoint bootstrap + bulk-sidecar apply) must
        write and parse the artifact on an executor thread, hand back
        the parse result, and leave no temp file behind."""
        import glob
        import tempfile

        from spicedb_kubeapi_proxy_tpu.spicedb.persist import (
            checkpoint as ckpt,
        )
        from spicedb_kubeapi_proxy_tpu.spicedb.replication.follower import (
            ReplicaFollower,
        )
        from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
        from spicedb_kubeapi_proxy_tpu.utils import metrics as m

        monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
        seen = {}

        def fake_load(path):
            seen["thread"] = threading.current_thread()
            with open(path, "rb") as f:
                seen["bytes"] = f.read()
            return "SNAP", "OVERLAY", {"revision": 7}

        monkeypatch.setattr(ckpt, "load_columnar_file", fake_load)
        follower = ReplicaFollower(TupleStore(), transport=None,
                                   registry=m.Registry())

        async def go():
            loop_thread = threading.current_thread()
            out = await follower._spool_npz(b"artifact-bytes", "t-")
            assert out == ("SNAP", "OVERLAY", {"revision": 7})
            assert seen["bytes"] == b"artifact-bytes"
            assert seen["thread"] is not loop_thread, (
                "checkpoint spool+parse ran ON the replica's serving "
                "loop")

        asyncio.run(go())
        assert glob.glob(str(tmp_path / "t-*")) == [], (
            "temp spool file leaked")

    def test_leader_serves_artifact_bytes_off_loop(self, monkeypatch,
                                                   tmp_path):
        import os

        from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
            Headers,
            Request,
        )
        from spicedb_kubeapi_proxy_tpu.spicedb.replication.leader import (
            ReplicationHub,
            serve_artifact_file,
        )
        from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
        from spicedb_kubeapi_proxy_tpu.utils import metrics as m

        seg = tmp_path / "seg-00000001.wal"
        seg.write_bytes(b"0123456789abcdef")
        seen = {}
        real_getsize = os.path.getsize

        def spy_getsize(path):
            if str(path) == str(seg):
                seen["thread"] = threading.current_thread()
            return real_getsize(path)

        monkeypatch.setattr(os.path, "getsize", spy_getsize)
        hub = ReplicationHub(TupleStore(), persistence=None,
                             registry=m.Registry())

        async def go():
            loop_thread = threading.current_thread()
            req = Request(method="GET",
                          target="/replication/segment/seg-00000001.wal",
                          headers=Headers())
            # serve_artifact_file is the ONE byte-serving path (leader
            # hub and fan-out hub both route through it)
            resp = await serve_artifact_file(req, str(seg), "segment",
                                             hub._shipped, hub.stats)
            assert resp.status == 200
            assert resp.body == b"0123456789abcdef"
            assert seen["thread"] is not loop_thread, (
                "artifact disk read ran ON the leader's serving loop")
            # offset serving still works through the executor hop
            req2 = Request(
                method="GET",
                target="/replication/segment/seg-00000001.wal?offset=10",
                headers=Headers())
            resp2 = await serve_artifact_file(req2, str(seg), "segment",
                                              hub._shipped, hub.stats)
            assert resp2.status == 206
            assert resp2.body == b"abcdef"

        asyncio.run(go())


class TestAdmissionGateHygiene:
    def test_note_rejected_inert_when_gate_off(self):
        from spicedb_kubeapi_proxy_tpu.utils import admission
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES

        before = admission._REJECTED.value(reason="queue_limit")
        GATES.set("AdmissionControl", False)
        try:
            admission.note_rejected("queue_limit")
            assert admission._REJECTED.value(
                reason="queue_limit") == before, (
                "killswitch off must mean inert: no rejection counter "
                "ticks (analyzer A004)")
        finally:
            GATES.set("AdmissionControl", True)
        admission.note_rejected("queue_limit")
        assert admission._REJECTED.value(
            reason="queue_limit") == before + 1
