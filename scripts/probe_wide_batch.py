"""Probe: production multitenant-1m graph, W=8 vs W=128 packed batches.

Measures lookup_resources_batch wall time for
  (a) 256 distinct subjects at today's W=8 bucket,
  (b) the same 256 subjects with SPICEDB_TPU_MIN_BATCH_WORDS=128 (padded
      columns — does widening cost anything?),
  (c) 4096 distinct subjects at W=128 (real demand filling the columns).

Run on the real TPU:  python scripts/probe_wide_batch.py
"""

import asyncio
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spicedb_kubeapi_proxy_tpu.models import workloads as wl
from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

ROUNDS = 4


def timed(ep, workload, subjects, label):
    async def go():
        t0 = time.time()
        out = await ep.lookup_resources_batch(
            workload.resource_type, workload.permission, subjects)
        warm = time.time() - t0
        times = []
        for _ in range(ROUNDS):
            t0 = time.time()
            await ep.lookup_resources_batch(
                workload.resource_type, workload.permission, subjects)
            times.append(time.time() - t0)
        med = statistics.median(times)
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        print(f"{label}: warm {warm:.1f}s, median {med*1000:.1f} ms, "
              f"{len(subjects)*n_obj/med/1e6:.1f}M checks/s "
              f"(sizes sample {[len(x) for x in out[:3]]})")
        return med

    return asyncio.run(go())


def main():
    workload = wl.multitenant_1m()
    schema = sch.parse_schema(workload.schema_text)
    ep = JaxEndpoint(schema)
    t0 = time.time()
    ep.store.bulk_load_text("\n".join(workload.relationships))
    print(f"loaded {len(workload.relationships)} rels in "
          f"{time.time()-t0:.1f}s")

    subs256 = [SubjectRef("user", s) for s in workload.subjects[:256]]
    subs4096 = [SubjectRef("user", s) for s in workload.subjects[:4096]]
    assert len({s.id for s in subs4096}) == 4096, "need distinct subjects"

    os.environ["SPICEDB_TPU_MIN_BATCH_WORDS"] = "1"
    t8 = timed(ep, workload, subs256, "W=8   batch=256 ")

    os.environ["SPICEDB_TPU_MIN_BATCH_WORDS"] = "128"
    t128p = timed(ep, workload, subs256, "W=128 batch=256 (padded)")
    t128f = timed(ep, workload, subs4096, "W=128 batch=4096")

    os.environ["SPICEDB_TPU_MIN_BATCH_WORDS"] = "32"
    t32 = timed(ep, workload, subs256, "W=32  batch=256 (padded)")
    t32f = timed(ep, workload,
                 [SubjectRef("user", s) for s in workload.subjects[:1024]],
                 "W=32  batch=1024")

    print("\nwiden-penalty (W=128 padded / W=8):", round(t128p / t8, 2))
    print("throughput ratio (4096@W128 vs 256@W8):",
          round((t8 / t128f) * 16, 1), "x")
    print("throughput ratio (1024@W32 vs 256@W8):",
          round((t8 / t32f) * 4, 1), "x")
    print("stats:", ep.stats)


if __name__ == "__main__":
    main()
