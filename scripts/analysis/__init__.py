"""Concurrency & hot-path static analyzer (docs/static-analysis.md).

One rule engine, one driver (`scripts/analyze.py`), one suppression
story for every static gate the repo has grown:

  A-rules  (this package)        concurrency-shape + jit-purity rules,
                                 each motivated by a bug this repo
                                 actually shipped a fix for
  M-rules  (legacy_lint.py)      the scripts/lint.py AST/lexical rules
                                 (F401/E722/...  + M001/M002/M003)
  SL-rules (spicedb/schema_lint) Cedar-style schema/rule lint, bridged
                                 as a subprocess so the analyzer itself
                                 never imports jax

Suppressions: `# noqa: AXXX(reason)` on the finding line — the reason
is REQUIRED (a bare `# noqa: AXXX` is itself finding A000).  Findings
that predate the rule live in the checked-in baseline
(scripts/analysis/baseline.json, `--update-baseline`); the gate fails
only on NEW findings.
"""

from .core import Finding, SourceFile, Baseline, load_sources  # noqa: F401

ANALYZER_VERSION = 1
