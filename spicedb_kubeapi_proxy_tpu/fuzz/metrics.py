"""`authz_fuzz_*` counters (FuzzTelemetry gate; docs/observability.md).

The harness is an offline tool, but its runs ride the same metrics
registry the server exports so a long `--budget-seconds` campaign can
be scraped/snapshotted like any other workload.  The `FuzzTelemetry`
gate is the killswitch: off, every recording helper is inert (analyzer
rule A004 enforces the dominating check — this module is registered in
scripts/analysis/rules_gates.py)."""

from __future__ import annotations

from ..utils import metrics as m
from ..utils.features import GATES

_cases = m.REGISTRY.counter(
    "authz_fuzz_cases_total",
    "Differential fuzz (case, gate-combo, role) cells replayed")
_divergences = m.REGISTRY.counter(
    "authz_fuzz_divergences_total",
    "Fuzz replays that produced >=1 jax-vs-oracle divergence")
_shrink_probes = m.REGISTRY.counter(
    "authz_fuzz_shrink_probes_total",
    "Replay probes spent minimizing failing delta streams")


def fuzz_telemetry_enabled() -> bool:
    return GATES.enabled("FuzzTelemetry")


def note_case(diverged: bool) -> None:
    if not fuzz_telemetry_enabled():
        return
    _cases.inc()
    if diverged:
        _divergences.inc()


def note_shrink_probe() -> None:
    if fuzz_telemetry_enabled():
        _shrink_probes.inc()
