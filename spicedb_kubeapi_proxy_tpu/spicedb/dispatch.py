"""Cross-request batched dispatch (SURVEY.md §2, parallelism table).

The reference fans each HTTP request's checks into one
`CheckBulkPermissions` RPC (pkg/authz/check.go:23-48) but batches only
*within* a request.  On TPU the batch IS the kernel invocation, so this
wrapper also coalesces across concurrent requests: concurrent
check/LookupResources callers enqueue work, and a drain loop issues fused
calls to the inner endpoint.

Policy ("natural batching"): when no inner call is in flight, the queue
flushes immediately — single-caller latency is one kernel call, same as
direct dispatch.  While a call is in flight, new arrivals accumulate and go
out together on the next drain, so high concurrency (BASELINE config 5: 256
simultaneous list requests) produces device-sized batches without a tuning
knob.  `max_batch` caps one drain's fused size.

Failure isolation: if a fused inner call raises, each member request is
retried individually so one malformed query (e.g. unknown definition, which
the endpoint surfaces as an error like the reference does) cannot poison
unrelated co-batched callers.
"""

from __future__ import annotations

import asyncio
from typing import Iterable, Optional

from .endpoints import PermissionsEndpoint
from .store import Watcher
from .types import (
    CheckRequest,
    Precondition,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
)


class BatchingEndpoint(PermissionsEndpoint):
    def __init__(self, inner: PermissionsEndpoint, max_batch: int = 4096):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.inner = inner
        self.max_batch = max_batch
        self._check_queue: list = []   # (CheckRequest, Future)
        self._lr_queue: dict = {}      # (type, perm) -> list[(SubjectRef, Future)]
        self._drain_task: Optional[asyncio.Task] = None
        self._stats = {"drains": 0, "fused_checks": 0, "fused_lookups": 0,
                       "max_fused_batch": 0}

    @property
    def stats(self) -> dict:
        """Own dispatch counters merged over the inner backend's stats."""
        inner_stats = getattr(self.inner, "stats", None)
        out = dict(inner_stats) if isinstance(inner_stats, dict) else {}
        out.update(self._stats)
        return out

    # -- queue plumbing ------------------------------------------------------

    def _kick(self) -> None:
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain())

    async def _drain(self) -> None:
        # Double-buffered lookups: when the inner endpoint exposes the
        # two-phase start/finish pair (jax://), batch N+1's kernel is
        # DISPATCHED (start) before batch N's transfer+extraction
        # (finish) blocks, so the device computes N+1 while N's result
        # streams to the host — the transfer is no longer serialized
        # behind an idle device (VERDICT r4 item 2).  `pending` holds at
        # most one started batch, bounding snapshot retention.
        pending = None  # (waiters, ctx) started but not finished
        two_phase = (hasattr(self.inner, "lookup_resources_batch_start")
                     and hasattr(self.inner, "lookup_resources_batch_finish"))
        while self._check_queue or self._lr_queue or pending:
            self._stats["drains"] += 1
            if self._check_queue:
                batch = self._check_queue[: self.max_batch]
                del self._check_queue[: len(batch)]
                await self._run_checks(batch)
            if self._lr_queue:
                key, waiters = next(iter(self._lr_queue.items()))
                del self._lr_queue[key]
                rest = waiters[self.max_batch:]
                waiters = waiters[: self.max_batch]
                if rest:
                    self._lr_queue.setdefault(key, []).extend(rest)
                if two_phase:
                    started = await self._start_lookups(key, waiters)
                    if pending:
                        await self._finish_lookups(*pending)
                    pending = started  # None if start failed (handled)
                else:
                    await self._run_lookups(key, waiters)
            elif pending:
                await self._finish_lookups(*pending)
                pending = None

    async def _retry_individually(self, waiters: list, single_call) -> None:
        """Per-member fallback after a fused call failed (concurrently —
        a poison request must not serialize the drain loop) so one
        malformed query can't fail unrelated co-batched callers."""
        async def retry_one(item, fut):
            if fut.done():
                return
            try:
                res = await single_call(item)
            except Exception as e:
                if not fut.done():  # caller may cancel during the await
                    fut.set_exception(e)
            else:
                if not fut.done():
                    fut.set_result(res)

        await asyncio.gather(*[retry_one(it, f) for it, f in waiters])

    @staticmethod
    def _resolve(waiters: list, results: list) -> None:
        for (_, fut), res in zip(waiters, results):
            if not fut.done():
                fut.set_result(res)

    async def _run_fused(self, waiters: list, stat: str, fused_call,
                         single_call) -> None:
        """One fused inner call for `waiters` ([(item, Future)]); on
        failure, retry members individually."""
        items = [it for it, _ in waiters]
        self._stats[stat] += 1
        self._stats["max_fused_batch"] = max(self._stats["max_fused_batch"],
                                            len(items))
        try:
            results = await fused_call(items)
        except Exception:
            await self._retry_individually(waiters, single_call)
            return
        self._resolve(waiters, results)

    async def _run_checks(self, batch: list) -> None:
        await self._run_fused(
            batch, "fused_checks",
            self.inner.check_bulk_permissions,
            self.inner.check_permission)

    async def _run_lookups(self, key: tuple, waiters: list) -> None:
        resource_type, permission = key
        await self._run_fused(
            waiters, "fused_lookups",
            lambda subjects: self.inner.lookup_resources_batch(
                resource_type, permission, subjects),
            lambda subject: self.inner.lookup_resources(
                resource_type, permission, subject))

    async def _start_lookups(self, key: tuple, waiters: list):
        """Phase 1 of a double-buffered fused lookup: dispatch the
        kernel + async D2H.  On failure, degrade to the classic fused
        call with per-member retry; returns None so the drain loop has
        nothing to finish."""
        resource_type, permission = key
        self._stats["fused_lookups"] += 1
        self._stats["max_fused_batch"] = max(self._stats["max_fused_batch"],
                                            len(waiters))
        try:
            ctx = await self.inner.lookup_resources_batch_start(
                resource_type, permission, [s for s, _ in waiters])
        except Exception:
            self._stats["fused_lookups"] -= 1  # _run_fused recounts
            await self._run_lookups(key, waiters)
            return None
        return (waiters, (key, ctx))

    async def _finish_lookups(self, waiters: list, started) -> None:
        """Phase 2: blocking transfer + extraction; per-member retry on
        failure (same isolation contract as _run_fused)."""
        key, ctx = started
        resource_type, permission = key
        try:
            results = await self.inner.lookup_resources_batch_finish(ctx)
        except Exception:
            await self._retry_individually(
                waiters, lambda s: self.inner.lookup_resources(
                    resource_type, permission, s))
            return
        self._resolve(waiters, results)

    # -- batched verbs -------------------------------------------------------

    async def check_permission(self, req: CheckRequest):
        fut = asyncio.get_running_loop().create_future()
        self._check_queue.append((req, fut))
        self._kick()
        return await fut

    async def check_bulk_permissions(self, reqs: list) -> list:
        if not reqs:
            return []
        loop = asyncio.get_running_loop()
        futs = []
        for r in reqs:
            fut = loop.create_future()
            self._check_queue.append((r, fut))
            futs.append(fut)
        self._kick()
        return list(await asyncio.gather(*futs))

    async def lookup_resources(self, resource_type: str, permission: str,
                               subject: SubjectRef) -> list:
        fut = asyncio.get_running_loop().create_future()
        self._lr_queue.setdefault((resource_type, permission), []).append(
            (subject, fut))
        self._kick()
        return await fut

    async def lookup_resources_batch(self, resource_type: str, permission: str,
                                     subjects: list) -> list:
        if not subjects:
            return []
        loop = asyncio.get_running_loop()
        futs = []
        bucket = self._lr_queue.setdefault((resource_type, permission), [])
        for s in subjects:
            fut = loop.create_future()
            bucket.append((s, fut))
            futs.append(fut)
        self._kick()
        return list(await asyncio.gather(*futs))

    # -- passthrough verbs ---------------------------------------------------

    async def read_relationships(self, flt: RelationshipFilter) -> list:
        return await self.inner.read_relationships(flt)

    async def write_relationships(self, updates: Iterable[RelationshipUpdate],
                                  preconditions: Iterable[Precondition] = ()) -> int:
        return await self.inner.write_relationships(updates, preconditions)

    async def delete_relationships(self, flt: RelationshipFilter,
                                   preconditions: Iterable[Precondition] = ()) -> int:
        return await self.inner.delete_relationships(flt, preconditions)

    def watch(self, object_types=None) -> Watcher:
        return self.inner.watch(object_types)

    async def close(self) -> None:
        task = self._drain_task
        if task is not None and not task.done():
            await task
        await self.inner.close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
