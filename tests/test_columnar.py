"""Columnar data plane: native/Python parser parity, store base-layer
semantics vs the object path, and the vectorized graph compiler vs the
per-tuple compiler (differential, SURVEY.md §4 oracle pattern)."""

import random
import time

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.ops.graph_compile import (
    compile_graph,
    compile_graph_columnar,
)
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.columnar import ColumnarSnapshot
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    ObjectRef,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectFilter,
    UpdateOp,
    parse_relationship,
)

CORPUS = [
    "namespace:ns1#viewer@user:alice",
    "group:eng#member@group:sub#member",
    "doc:d1#viewer@user:*",
    "pod:ns/with:colon#namespace@namespace:ns",
    "a:b#c@d:e#...",
    "x:y#z@w:v[expiration:1234.5]",
    "tail:id#rel@u:last",
]

BAD = [
    "noseparator",
    "a:#r@u:x",
    "a:b#r@u:",
    "{{x}}:b#r@u:x",
    "x:y#z@w:v[expiration:zzz]",
    "x:y#z@w:v[expiration:0x10]",   # float() rejects hex
    "x:y#z@w:v[expiration:]",
]

TEXT = "\n".join(CORPUS + ["# comment", "", "   "])


def parsers():
    out = [("python", ColumnarSnapshot._from_text_py)]
    from spicedb_kubeapi_proxy_tpu import native

    if native.load() is not None:
        out.append(("native", ColumnarSnapshot.from_text))
    return out


class TestParserParity:
    @pytest.mark.parametrize("name,parse", parsers())
    def test_corpus_matches_parse_relationship(self, name, parse):
        snap = parse(TEXT)
        assert len(snap) == len(CORPUS)
        for i, line in enumerate(CORPUS):
            assert snap.relationship(i) == parse_relationship(line), (name, line)

    @pytest.mark.parametrize("name,parse", parsers())
    @pytest.mark.parametrize("bad", BAD)
    def test_bad_lines_raise(self, name, parse, bad):
        with pytest.raises(ValueError):
            parse(bad)

    @pytest.mark.parametrize("name,parse", parsers())
    def test_expiration_whitespace_tolerated(self, name, parse):
        # float() strips surrounding whitespace; both parsers must agree
        snap = parse("x:y#z@w:v[expiration: 7.5 ]")
        assert snap.relationship(0).expires_at == 7.5

    def test_native_python_identical_pools(self):
        ps = parsers()
        if len(ps) < 2:
            pytest.skip("native extension unavailable")
        a = ps[0][1](TEXT)
        b = ps[1][1](TEXT)
        assert a.pool == b.pool
        for col in ("rtype", "rid", "rel", "stype", "sid", "srel"):
            assert np.array_equal(getattr(a, col), getattr(b, col)), col


def canon(store, flt=None):
    return sorted(r.rel_string() for r in store.read(flt))


def make_stores(rels):
    s_obj = TupleStore()
    s_obj.bulk_load([parse_relationship(r) for r in rels])
    s_col = TupleStore()
    s_col.bulk_load_text("\n".join(rels))
    return s_obj, s_col


class TestBaseLayerDifferential:
    def test_reads_and_writes_match_object_path(self):
        rng = random.Random(3)
        rels = sorted({
            f"ns:n{rng.randrange(20)}#viewer@user:u{rng.randrange(40)}"
            for _ in range(500)})
        s_obj, s_col = make_stores(rels)
        assert canon(s_obj) == canon(s_col)
        flt = RelationshipFilter(resource_type="ns", relation="viewer",
                                 subject=SubjectFilter(type="user"))
        assert canon(s_obj, flt) == canon(s_col, flt)
        assert s_obj.object_ids_of_type("ns") == s_col.object_ids_of_type("ns")
        r0 = parse_relationship(rels[7])
        for st in (s_obj, s_col):
            st.write([RelationshipUpdate(UpdateOp.DELETE, r0)])
            st.write([RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship("ns:new#viewer@user:z"))])
        assert canon(s_obj) == canon(s_col)
        assert not s_col.has_exact(r0)

    def test_duplicate_lines_upsert_like_bulk_load(self):
        dup = "doc:1#viewer@user:a"
        s = TupleStore()
        s.bulk_load_text(f"{dup}\n{dup}\n{dup}[expiration:99999999999]")
        # dict-upsert semantics: one copy, last occurrence wins
        assert s.count() == 1
        assert s.read()[0].expires_at == 99999999999
        s.write([RelationshipUpdate(UpdateOp.DELETE, parse_relationship(dup))])
        assert s.count() == 0
        assert not s.has_exact(parse_relationship(dup))

    def test_touch_shadow_of_duplicated_base_row(self):
        dup = "doc:1#viewer@user:a"
        s = TupleStore()
        s.bulk_load_text(f"{dup}\n{dup}")
        s.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(dup))])
        assert s.count() == 1

    def test_base_expiration(self):
        now = time.time()
        s = TupleStore()
        s.bulk_load_text(f"a:b#r@u:x[expiration:{now + 0.15}]\na:c#r@u:y")
        assert s.count() == 2
        time.sleep(0.2)
        assert [r.rel_string() for r in s.read()] == ["a:c#r@u:y"]
        assert s.object_ids_of_type("a") == ["c"]

    def test_subjects_for_combines_base_and_overlay(self):
        s = TupleStore()
        s.bulk_load_text("ns:n1#viewer@user:a\nns:n1#viewer@user:b")
        s.write([RelationshipUpdate(
            UpdateOp.TOUCH, parse_relationship("ns:n1#viewer@user:c"))])
        got = sorted(str(x) for x in s.subjects_for(ObjectRef("ns", "n1"),
                                                    "viewer"))
        assert got == ["user:a", "user:b", "user:c"]


SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition tenant { relation admin: user
  relation member: user | group#member
  permission access = admin + member }
definition namespace { relation tenant: tenant
  relation viewer: user | group#member | user:*
  permission view = viewer + tenant->access }
definition pod { relation namespace: namespace
  relation creator: user
  relation banned: user
  permission view = creator + namespace->view - banned }
"""


def random_rels(rng, n):
    rels = set()
    for _ in range(n):
        k = rng.randrange(8)
        if k == 0:
            rels.add(f"group:g{rng.randrange(8)}#member@user:u{rng.randrange(30)}")
        elif k == 1:
            rels.add(f"group:g{rng.randrange(8)}#member@group:g{rng.randrange(8)}#member")
        elif k == 2:
            rels.add(f"tenant:t{rng.randrange(3)}#member@group:g{rng.randrange(8)}#member")
        elif k == 3:
            rels.add(f"namespace:n{rng.randrange(6)}#tenant@tenant:t{rng.randrange(3)}")
        elif k == 4:
            rels.add(f"pod:n{rng.randrange(6)}/p{rng.randrange(40)}"
                     f"#namespace@namespace:n{rng.randrange(6)}")
        elif k == 5:
            rels.add(f"namespace:n{rng.randrange(6)}#viewer@user:*")
        elif k == 6:
            rels.add(f"pod:n{rng.randrange(6)}/p{rng.randrange(40)}"
                     f"#banned@user:u{rng.randrange(30)}")
        else:
            rels.add(f"pod:n{rng.randrange(6)}/p{rng.randrange(40)}"
                     f"#creator@user:u{rng.randrange(30)}")
    rels.add("alien:x#zap@user:u1")            # type not in schema
    rels.add("pod:n0/p0#unknownrel@user:u1")   # relation not in schema
    return sorted(rels)


def assert_programs_equal(p1, p2):
    assert p1.state_size == p2.state_size
    assert p1.slot_offsets == p2.slot_offsets
    assert p1.object_ids == p2.object_ids
    e1 = sorted(zip(p1.edge_dst.tolist(), p1.edge_src.tolist()))
    e2 = sorted(zip(p2.edge_dst.tolist(), p2.edge_src.tolist()))
    assert e1 == e2
    assert p1.wildcard_terms == p2.wildcard_terms
    assert p1.perm_ops == p2.perm_ops
    assert p1.arrow_specs == p2.arrow_specs


class TestColumnarCompilerDifferential:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_graphs(self, seed):
        schema = sch.parse_schema(SCHEMA)
        rng = random.Random(seed)
        rels = random_rels(rng, rng.randrange(80, 300))
        tuples = [parse_relationship(r) for r in rels]
        snap = ColumnarSnapshot.from_text("\n".join(rels))
        p1 = compile_graph(schema, tuples)
        p2 = compile_graph_columnar(schema, snap, np.arange(len(snap)), [])
        assert_programs_equal(p1, p2)

    def test_extras_and_overlay(self):
        schema = sch.parse_schema(SCHEMA)
        rels = random_rels(random.Random(9), 120)
        tuples = [parse_relationship(r) for r in rels]
        overlay = [parse_relationship("pod:n0/extra#creator@user:brandnew"),
                   parse_relationship("namespace:nX#viewer@user:u1")]
        extra = {"user": {"ghost1", "ghost2"}, "pod": {"n9/phantom"}}
        p1 = compile_graph(schema, tuples + overlay, extra_subject_ids=extra)
        snap = ColumnarSnapshot.from_text("\n".join(rels))
        p2 = compile_graph_columnar(schema, snap, np.arange(len(snap)),
                                    overlay, extra_subject_ids=extra)
        assert_programs_equal(p1, p2)

    def test_dead_rows_excluded(self):
        schema = sch.parse_schema(SCHEMA)
        rels = random_rels(random.Random(4), 100)
        snap = ColumnarSnapshot.from_text("\n".join(rels))
        keep = np.arange(len(snap))[::2]
        tuples = [snap.relationship(int(i)) for i in keep]
        p1 = compile_graph(schema, tuples)
        p2 = compile_graph_columnar(schema, snap, keep, [])
        assert_programs_equal(p1, p2)


class TestAsciiStrictParity:
    """The bulk-text grammar is ASCII-strict so native/Python agree
    bit-for-bit on exotic inputs (underscored floats, unicode whitespace,
    unicode line separators)."""

    @pytest.mark.parametrize("name,parse", parsers())
    def test_underscored_float_rejected(self, name, parse):
        with pytest.raises(ValueError):
            parse("x:y#z@w:v[expiration:1_5]")

    @pytest.mark.parametrize("name,parse", parsers())
    def test_unicode_whitespace_not_stripped(self, name, parse):
        # U+00A0 is not ASCII whitespace: it stays part of the type field
        snap = parse(" x:y#z@w:v")
        assert snap.relationship(0).resource.type == " x"

    @pytest.mark.parametrize("name,parse", parsers())
    def test_unicode_line_separator_not_a_newline(self, name, parse):
        # U+2028 does not split lines in the bulk grammar -> one tuple with
        # the separator embedded in the subject id
        snap = parse("a:b#r@u:one more")
        assert len(snap) == 1
        assert snap.relationship(0).subject.id == "one more"
