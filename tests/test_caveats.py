"""Caveat (conditional permission) support end-to-end.

SURVEY.md hard part (c): the reference's embedded SpiceDB supports caveated
tuples and the proxy skips CONDITIONAL LookupResources results
(/root/reference/pkg/authz/lookups.go:85-88).  Coverage: schema DSL caveat
blocks, caveated tuples in the store, tri-state (Kleene) evaluation in the
oracle, CONDITIONAL in bulk-check results, LR skipping, and the jax://
residual routing (differential vs the oracle, incl. deltas).
"""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CaveatRef,
    CheckRequest,
    ObjectRef,
    Permissionship,
    RelationshipUpdate,
    SchemaError,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

CAVEAT_SCHEMA = """
caveat on_tuesday(day string) {
  day == "tuesday"
}
caveat ip_allowlist(allowed list<string>, ip string) {
  ip in allowed
}
definition user {}
definition document {
  relation viewer: user | user with on_tuesday
  relation editor: user with ip_allowlist
  relation banned: user | user with on_tuesday
  permission view = viewer + editor
  permission edit = editor - banned
}
"""


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def delete(*rels):
    return [RelationshipUpdate(UpdateOp.DELETE, parse_relationship(r))
            for r in rels]


class TestSchemaCaveats:
    def test_parse_caveat_blocks(self):
        s = sch.parse_schema(CAVEAT_SCHEMA)
        assert set(s.caveats) == {"on_tuesday", "ip_allowlist"}
        c = s.caveats["on_tuesday"]
        assert c.params == (("day", "string"),)
        assert c.body_src == 'day == "tuesday"'
        assert s.caveats["ip_allowlist"].params == (
            ("allowed", "list<string>"), ("ip", "string"))

    def test_caveat_evaluate(self):
        s = sch.parse_schema(CAVEAT_SCHEMA)
        c = s.caveats["on_tuesday"]
        assert c.evaluate({"day": "tuesday"}) is True
        assert c.evaluate({"day": "monday"}) is False
        assert c.evaluate({}) is None  # missing param -> CONDITIONAL

    def test_unknown_trait_rejected(self):
        with pytest.raises(SchemaError, match="unknown trait"):
            sch.parse_schema("""
definition user {}
definition doc { relation viewer: user with nonexistent }
""")

    def test_with_and_expiration(self):
        s = sch.parse_schema("""
caveat c(x int) { x > 0 }
definition user {}
definition doc { relation viewer: user with c and expiration }
""")
        assert s.definitions["doc"].relations["viewer"][0].traits == \
            ("c", "expiration")


class TestRelStringCaveats:
    def test_round_trip(self):
        r = parse_relationship(
            'document:d1#viewer@user:alice[caveat:on_tuesday:{"day": "tuesday"}]')
        assert r.caveat == CaveatRef("on_tuesday", '{"day": "tuesday"}')
        assert parse_relationship(r.rel_string()) == r

    def test_caveat_without_context(self):
        r = parse_relationship("document:d1#viewer@user:alice[caveat:on_tuesday]")
        assert r.caveat == CaveatRef("on_tuesday")
        assert r.caveat.context() == {}

    def test_caveat_plus_expiration(self):
        r = parse_relationship(
            "document:d1#viewer@user:a[caveat:on_tuesday][expiration:99.5]")
        assert r.caveat.name == "on_tuesday" and r.expires_at == 99.5
        assert parse_relationship(r.rel_string()) == r


def make_embedded(rels):
    ep = EmbeddedEndpoint(sch.parse_schema(CAVEAT_SCHEMA))
    if rels:
        ep.store.write(touch(*rels))
    return ep


class TestOracleTristate:
    def test_decided_true(self):
        ep = make_embedded(
            ['document:d#viewer@user:a[caveat:on_tuesday:{"day": "tuesday"}]'])
        assert ep.evaluator.check3(ObjectRef("document", "d"), "view",
                                   SubjectRef("user", "a")) == 2

    def test_decided_false(self):
        ep = make_embedded(
            ['document:d#viewer@user:a[caveat:on_tuesday:{"day": "monday"}]'])
        assert ep.evaluator.check3(ObjectRef("document", "d"), "view",
                                   SubjectRef("user", "a")) == 0

    def test_undecided_conditional(self):
        ep = make_embedded(["document:d#viewer@user:a[caveat:on_tuesday]"])
        assert ep.evaluator.check3(ObjectRef("document", "d"), "view",
                                   SubjectRef("user", "a")) == 1

    def test_definite_tuple_wins_union(self):
        ep = make_embedded([
            "document:d#viewer@user:a[caveat:on_tuesday]",
            "document:d#viewer@user:a",
        ])
        assert ep.evaluator.check3(ObjectRef("document", "d"), "view",
                                   SubjectRef("user", "a")) == 2

    def test_exclusion_with_conditional_subtract(self):
        # edit = editor - banned; banned is undecided -> MAYBE
        ep = make_embedded([
            'document:d#editor@user:a[caveat:ip_allowlist:'
            '{"allowed": ["1.2.3.4"], "ip": "1.2.3.4"}]',
            "document:d#banned@user:a[caveat:on_tuesday]",
        ])
        assert ep.evaluator.check3(ObjectRef("document", "d"), "edit",
                                   SubjectRef("user", "a")) == 1

    def test_bulk_check_conditional_permissionship(self):
        ep = make_embedded(["document:d#viewer@user:a[caveat:on_tuesday]"])

        async def run():
            out = await ep.check_bulk_permissions([
                CheckRequest(ObjectRef("document", "d"), "view",
                             SubjectRef("user", "a")),
                CheckRequest(ObjectRef("document", "d"), "view",
                             SubjectRef("user", "b")),
            ])
            assert out[0].permissionship == \
                Permissionship.CONDITIONAL_PERMISSION
            assert not out[0].allowed  # conditional is NOT a pass
            assert out[1].permissionship == Permissionship.NO_PERMISSION
        asyncio.run(run())

    def test_lr_skips_conditional(self):
        # reference lookups.go:85-88: conditional results are skipped
        ep = make_embedded([
            "document:c#viewer@user:a[caveat:on_tuesday]",
            'document:y#viewer@user:a[caveat:on_tuesday:{"day": "tuesday"}]',
            "document:p#viewer@user:a",
        ])

        async def run():
            ids = await ep.lookup_resources("document", "view",
                                            SubjectRef("user", "a"))
            assert sorted(ids) == ["p", "y"]
        asyncio.run(run())


def make_jax_pair(rels):
    ep = JaxEndpoint(sch.parse_schema(CAVEAT_SCHEMA))
    if rels:
        ep.store.write(touch(*rels))
    return ep, Evaluator(ep.schema, ep.store)


def assert_jax_matches_oracle(ep, oracle, object_ids, subjects,
                              permissions=("view", "edit")):
    async def run():
        for perm in permissions:
            for s in subjects:
                want_lr = sorted(oracle.lookup_resources("document", perm, s))
                got_lr = sorted(await ep.lookup_resources("document", perm, s))
                assert got_lr == want_lr, (perm, s, got_lr, want_lr)
                reqs = [CheckRequest(ObjectRef("document", oid), perm, s)
                        for oid in object_ids]
                got = await ep.check_bulk_permissions(reqs)
                for oid, res in zip(object_ids, got):
                    want = oracle.check3(ObjectRef("document", oid), perm, s)
                    got3 = {Permissionship.NO_PERMISSION: 0,
                            Permissionship.CONDITIONAL_PERMISSION: 1,
                            Permissionship.HAS_PERMISSION: 2}[res.permissionship]
                    assert got3 == want, (perm, oid, s, got3, want)
    asyncio.run(run())


class TestJaxCaveatResiduals:
    SUBJECTS = [SubjectRef("user", u) for u in ("a", "b", "nobody")]

    def test_differential_with_caveats(self):
        ep, oracle = make_jax_pair([
            "document:d1#viewer@user:a[caveat:on_tuesday]",
            'document:d2#viewer@user:a[caveat:on_tuesday:{"day": "tuesday"}]',
            "document:d3#viewer@user:b",
            'document:d3#editor@user:a[caveat:ip_allowlist:'
            '{"allowed": [], "ip": "9.9.9.9"}]',
        ])
        assert_jax_matches_oracle(ep, oracle, ["d1", "d2", "d3"],
                                  self.SUBJECTS)
        # round-4: caveat-affected queries stay ON the kernel (tri-state
        # definite/maybe bitplanes) — no host-oracle residual routing
        assert ep.stats["oracle_residual_checks"] == 0
        assert ep.stats["kernel_calls"] > 0

    def test_no_caveats_no_residual(self):
        ep, oracle = make_jax_pair(["document:d#viewer@user:a"])
        assert_jax_matches_oracle(ep, oracle, ["d"], self.SUBJECTS)
        assert ep.stats["oracle_residual_checks"] == 0
        assert ep.stats["kernel_calls"] > 0

    def test_delta_add_then_remove_caveat(self):
        ep, oracle = make_jax_pair(["document:d#viewer@user:a"])
        assert_jax_matches_oracle(ep, oracle, ["d"], self.SUBJECTS)
        # first caveated tuple forces a rebuild + residual routing
        ep.store.write(touch("document:d#viewer@user:b[caveat:on_tuesday]"))
        assert_jax_matches_oracle(ep, oracle, ["d"], self.SUBJECTS)
        # replacing the caveated tuple with a definite one
        ep.store.write(touch("document:d#viewer@user:b"))
        assert_jax_matches_oracle(ep, oracle, ["d"], self.SUBJECTS)
        # deleting it
        ep.store.write(delete("document:d#viewer@user:b"))
        assert_jax_matches_oracle(ep, oracle, ["d"], self.SUBJECTS)

    def test_replace_definite_with_caveated(self):
        ep, oracle = make_jax_pair([
            "document:d#viewer@user:a",
            "document:x#viewer@user:b[caveat:on_tuesday]",
        ])
        assert_jax_matches_oracle(ep, oracle, ["d", "x"], self.SUBJECTS)
        # same key flips definite -> caveated: device edge must disappear
        ep.store.write(touch("document:d#viewer@user:a[caveat:on_tuesday]"))
        assert_jax_matches_oracle(ep, oracle, ["d", "x"], self.SUBJECTS)

    def test_bulk_load_text_with_caveats(self):
        ep = JaxEndpoint(sch.parse_schema(CAVEAT_SCHEMA))
        ep.store.bulk_load_text("\n".join([
            "document:p#viewer@user:a",
            "document:c#viewer@user:a[caveat:on_tuesday]",
        ]))
        oracle = Evaluator(ep.schema, ep.store)
        assert_jax_matches_oracle(ep, oracle, ["p", "c"], self.SUBJECTS)
