"""Device-resident query pipeline tests (docs/performance.md
"Device-resident pipeline"): on-device bitplane pack + word transpose
must be bit-exact against the host path across random batches and bucket
widths, donated state arenas must account correctly in the HBM ledger,
pipelined dispatch must stay parity-correct under store churn with
rebuilds mid-flight pinned to their capture generation, the
DevicePipeline gate off must reproduce the serial path, and the CPU
end-to-end pipeline must actually overlap transfer with compute."""

import asyncio

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import devtel, timeline
from spicedb_kubeapi_proxy_tpu.utils.features import GATES

SCHEMA = """
definition user {}
definition group {
  relation member: user
  permission m = member
}
definition doc {
  relation viewer: user | group#member
  relation editor: user
  permission view = viewer + editor
  permission edit = editor
}
"""


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def make_pair(n_docs=24, n_users=6, n_groups=2, seed=3):
    """(jax endpoint, oracle) over a randomized doc/group graph."""
    rng = np.random.default_rng(seed)
    schema = sch.parse_schema(SCHEMA)
    # these tests exercise the device-pipeline machinery (arenas,
    # overlapped dispatch): keep the Leopard index out so batch lookups
    # actually launch kernels instead of serving from the closure plane
    prev = GATES.enabled("LeopardIndex")
    GATES.set("LeopardIndex", False)
    try:
        jx = JaxEndpoint(schema)
    finally:
        GATES.set("LeopardIndex", prev)
    rels = []
    for g in range(n_groups):
        for u in range(n_users):
            if rng.random() < 0.5:
                rels.append(f"group:g{g}#member@user:u{u}")
    for d in range(n_docs):
        u = rng.integers(0, n_users)
        rels.append(f"doc:d{d}#viewer@user:u{u}")
        if rng.random() < 0.3:
            rels.append(f"doc:d{d}#editor@user:u{rng.integers(0, n_users)}")
        if rng.random() < 0.3:
            rels.append(f"doc:d{d}#viewer@group:g{rng.integers(0, n_groups)}#member")
    jx.store.write(touch(*rels))
    return jx, Evaluator(schema, jx.store)


@pytest.fixture(params=["ell", "segment"])
def kernel_kind(request, monkeypatch):
    monkeypatch.setenv("SPICEDB_TPU_KERNEL", request.param)
    return request.param


# -- on-device pack + transpose parity (host oracle path) ---------------------


class TestDevicePackParity:
    def test_pack_transpose_parity_fuzz(self, kernel_kind):
        """Property test: the pipelined entry points (device-side
        bitplane pack, fused word transpose, donated arena) are
        bit-exact against the serial host-pack path across random query
        batches and every pow-2 bucket width the dispatcher produces."""
        jx, _ = make_pair()
        jx.warm_start()
        g = jx._graph
        prog = g.prog
        rng = np.random.default_rng(11)
        off, length = prog.slot_range("doc", "view")
        packed = hasattr(g, "run_lookup_packed")
        for lanes_req in (1, 7, 32, 33, 64, 100, 128):
            lanes = g.batch_bucket(lanes_req)
            q = np.full(lanes, prog.dead_index, np.int32)
            n_real = min(lanes_req, lanes)
            q[:n_real] = rng.integers(0, prog.state_size - 1, n_real,
                                      dtype=np.int32)
            snap = g.snapshot()
            # lookup: serial [L, W/B] then host .T  vs  device-transposed
            if packed:
                host = g.run_lookup_packed(off, length, q, snap=snap)
                dev, _, _ = g.run_lookup_packed_T_device(off, length, q,
                                                         snap=snap)
            else:
                host = g.run_lookup(off, length, q, snap=snap)
                dev, _, _ = g.run_lookup_T_device(off, length, q, snap=snap)
            np.testing.assert_array_equal(np.asarray(dev), host.T,
                                          err_msg=f"lanes={lanes}")
            # checks: serial host split of col -> (word, bit) vs on-device
            n_gather = int(rng.integers(1, lanes + 1))
            gidx = rng.integers(0, prog.state_size - 1, n_gather,
                                dtype=np.int32)
            gcol = rng.integers(0, lanes, n_gather, dtype=np.int32)
            serial = g.run_checks3(q, gidx, gcol, snap=snap)
            dev, _, _ = g.run_checks3_device(q, gidx, gcol, snap=snap)
            np.testing.assert_array_equal(
                np.asarray(dev)[: len(serial)].astype(np.int64),
                np.asarray(serial).astype(np.int64),
                err_msg=f"lanes={lanes}")

    def test_endpoint_parity_vs_oracle(self, kernel_kind):
        """End-to-end: fused checks + lookups through the pipelined
        endpoint agree with the host oracle."""
        jx, oracle = make_pair(seed=5)
        subs = [SubjectRef("user", f"u{i}") for i in range(6)]

        async def go():
            got_lr = await jx.lookup_resources_batch("doc", "view", subs)
            reqs = [CheckRequest(ObjectRef("doc", f"d{d}"), "view", s)
                    for d in range(8) for s in subs]
            got_ck = await jx.check_bulk_permissions(reqs)
            return got_lr, reqs, got_ck

        got_lr, reqs, got_ck = asyncio.run(go())
        for s, ids in zip(subs, got_lr):
            assert sorted(ids) == sorted(
                oracle.lookup_resources("doc", "view", s))
        for r, res in zip(reqs, got_ck):
            assert res.allowed == oracle.check(
                r.resource, r.permission, r.subject)


# -- generation pinning: rebuild mid-flight must not mix generations ----------


class TestGenerationPinning:
    def test_lookup_finish_pinned_across_rebuild(self, kernel_kind):
        jx, oracle = make_pair(seed=7)
        subs = [SubjectRef("user", f"u{i}") for i in range(4)]

        async def go():
            ctx = await jx.lookup_resources_batch_start("doc", "view", subs)
            # expected answers at the PINNED revision, before the delta
            expected = [sorted(oracle.lookup_resources("doc", "view", s))
                        for s in subs]
            jx.store.write(touch(*[f"doc:d{d}#viewer@user:u{i}"
                                   for d in range(8) for i in range(4)]))
            jx.force_rebuild()  # rebuild mid-flight
            got = await jx.lookup_resources_batch_finish(ctx)
            for want, ids in zip(expected, got):
                assert sorted(ids) == want
            # a fresh batch sees the post-delta graph
            fresh = await jx.lookup_resources_batch("doc", "view", subs)
            for s, ids in zip(subs, fresh):
                assert sorted(ids) == sorted(
                    oracle.lookup_resources("doc", "view", s))

        asyncio.run(go())

    def test_check_finish_pinned_across_rebuild(self, kernel_kind):
        jx, oracle = make_pair(n_docs=8, seed=9)
        reqs = [CheckRequest(ObjectRef("doc", f"d{d}"), "view",
                             SubjectRef("user", "u0")) for d in range(8)]

        async def go():
            ctx = await jx.check_bulk_permissions_start(reqs)
            expected = [oracle.check(r.resource, r.permission, r.subject)
                        for r in reqs]
            # flip every answer for u0, then rebuild mid-flight
            jx.store.write(touch(*[f"doc:d{d}#editor@user:u0"
                                   for d in range(8)]))
            jx.force_rebuild()
            got = await jx.check_bulk_permissions_finish(ctx)
            assert [r.allowed for r in got] == expected
            fresh = await jx.check_bulk_permissions(reqs)
            assert all(r.allowed for r in fresh)

        asyncio.run(go())


# -- pipelined vs serial dispatch parity under churn --------------------------


class TestDispatchParityUnderChurn:
    def _workload(self, depth: int, seed: int = 17):
        """Run a deterministic churn workload (writes interleaved with
        waves of concurrent fused checks+lookups) at the given pipeline
        depth; returns the collected answers."""
        jx, oracle = make_pair(n_docs=16, seed=seed)
        ep = BatchingEndpoint(jx, max_batch=4, pipeline_depth=depth)
        subs = [SubjectRef("user", f"u{i}") for i in range(6)]
        out = []

        async def go():
            for rnd in range(4):
                jx.store.write(touch(f"doc:d{rnd}#viewer@user:u{rnd % 6}"))
                # max_batch=4 splits these waves into several fused
                # batches per drain, so depth>1 pipelines inside a wave
                tasks = [ep.lookup_resources("doc", "view", s) for s in subs]
                tasks += [ep.check_permission(CheckRequest(
                    ObjectRef("doc", f"d{d}"), "view", subs[d % 6]))
                    for d in range(10)]
                res = await asyncio.gather(*tasks)
                out.append([sorted(r) if isinstance(r, list) else r.allowed
                            for r in res])
            return ep.stats

        stats = asyncio.run(go())
        # quiesced end state agrees with the oracle
        for s in subs:
            want = sorted(oracle.lookup_resources("doc", "view", s))
            got = sorted(asyncio.run(jx.lookup_resources("doc", "view", s)))
            assert got == want
        return out, stats

    def test_depths_agree_under_churn(self):
        serial, _ = self._workload(depth=1)
        piped, stats = self._workload(depth=3)
        assert serial == piped
        assert stats["fused_lookups"] >= 4
        assert stats["fused_checks"] >= 4

    def test_rejects_bad_depth(self):
        jx, _ = make_pair(n_docs=2)
        with pytest.raises(ValueError, match="pipeline_depth"):
            BatchingEndpoint(jx, pipeline_depth=0)


# -- feature-gate killswitch: off reproduces the serial path ------------------


class TestGateOff:
    def test_gate_off_uses_serial_entry_points(self, monkeypatch):
        GATES.set("DevicePipeline", False)
        try:
            jx, oracle = make_pair(seed=21)
            jx.warm_start()
            g = jx._graph

            def boom(*a, **k):
                raise AssertionError("pipelined entry used with gate off")

            # tripwires: the gate-off path must never touch the
            # pipelined entry points or the async readback pool
            monkeypatch.setattr(g, "run_checks3_device", boom,
                                raising=False)
            monkeypatch.setattr(g, "run_lookup_packed_T_device", boom,
                                raising=False)
            monkeypatch.setattr(g, "run_lookup_T_device", boom,
                                raising=False)
            from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
            monkeypatch.setattr(je, "_start_readback", boom)
            subs = [SubjectRef("user", f"u{i}") for i in range(4)]

            async def go():
                lr = await jx.lookup_resources_batch("doc", "view", subs)
                ck = await jx.check_bulk_permissions(
                    [CheckRequest(ObjectRef("doc", "d0"), "view", s)
                     for s in subs])
                return lr, ck

            lr, ck = asyncio.run(go())
            for s, ids in zip(subs, lr):
                assert sorted(ids) == sorted(
                    oracle.lookup_resources("doc", "view", s))
            for s, res in zip(subs, ck):
                assert res.allowed == oracle.check(
                    ObjectRef("doc", "d0"), "view", s)
        finally:
            GATES.set("DevicePipeline", True)

    def test_gate_off_dispatcher_never_two_phases_checks(self, monkeypatch):
        GATES.set("DevicePipeline", False)
        try:
            jx, _ = make_pair(n_docs=6, seed=23)
            ep = BatchingEndpoint(jx, pipeline_depth=4)

            def boom(*a, **k):
                raise AssertionError("two-phase checks used with gate off")

            monkeypatch.setattr(jx, "check_bulk_permissions_start", boom,
                                raising=False)

            async def go():
                tasks = [ep.check_permission(CheckRequest(
                    ObjectRef("doc", f"d{d}"), "view",
                    SubjectRef("user", "u0"))) for d in range(6)]
                return await asyncio.gather(*tasks)

            res = asyncio.run(go())
            assert len(res) == 6
        finally:
            GATES.set("DevicePipeline", True)


# -- donated state arenas: HBM ledger accounting ------------------------------


class TestArenaLedger:
    def test_arena_registers_once_and_retires_with_generation(self,
                                                              kernel_kind):
        jx, _ = make_pair(seed=25)
        subs = [SubjectRef("user", f"u{i}") for i in range(4)]

        async def wave():
            await jx.lookup_resources_batch("doc", "view", subs)
            await jx.check_bulk_permissions(
                [CheckRequest(ObjectRef("doc", "d0"), "view", s)
                 for s in subs])

        asyncio.run(wave())
        gen = jx._devtel_gen
        # generation-scoped: the ledger is process-global, and earlier
        # tests' graphs retire asynchronously (weakref.finalize + the
        # deferred-retirement queue), so totals() would be noisy here
        arena = devtel.LEDGER.generation_bytes(gen, kind="state_arena")
        assert arena > 0
        # donation updates in place: repeat calls of the same buckets
        # neither allocate nor free (registered bytes constant)
        for _ in range(3):
            asyncio.run(wave())
        assert devtel.LEDGER.generation_bytes(gen,
                                              kind="state_arena") == arena
        # a rebuild retires the outgoing generation wholesale, arenas
        # included; the next wave re-registers under the new generation
        jx.force_rebuild()
        assert devtel.LEDGER.generation_bytes(gen) == 0
        asyncio.run(wave())
        gen2 = jx._devtel_gen
        assert devtel.LEDGER.generation_bytes(gen2) > 0
        assert devtel.LEDGER.generation_bytes(gen2,
                                              kind="state_arena") == arena

    def test_discard_arena_unregisters(self, kernel_kind):
        jx, _ = make_pair(seed=27)
        asyncio.run(jx.lookup_resources_batch(
            "doc", "view", [SubjectRef("user", "u0")]))
        g = jx._graph
        kern = getattr(g, "kernel", None) or g._kernel()
        keys = list(kern._arenas)
        assert keys
        before = devtel.LEDGER.totals().get("state_arena", 0)
        kern.discard_arena(keys[0])
        assert devtel.LEDGER.totals().get("state_arena", 0) < before


# -- compile prewarm ----------------------------------------------------------


class TestPrewarm:
    def test_prewarm_records_compile_events_and_absorbs_stall(
            self, kernel_kind):
        jx, _ = make_pair(seed=29)
        mark = timeline.now()
        jx.warm_start(prewarm=True)
        evs = [e for e in timeline.TIMELINE.events(since=mark)
               if e.stage == "compile" and e.track == "rebuild"]
        assert any(e.attrs and e.attrs.get("prewarm") == "checks"
                   for e in evs)
        assert any(e.attrs and str(e.attrs.get("prewarm", "")).startswith(
            "lookup:") for e in evs)
        # the warmed bucket ladder means a first real request compiles
        # nothing new: no device-track compile slice after warm start
        mark2 = timeline.now()

        async def go():
            await jx.check_bulk_permissions(
                [CheckRequest(ObjectRef("doc", "d0"), "view",
                              SubjectRef("user", "u0"))])
            await jx.lookup_resources_batch(
                "doc", "view", [SubjectRef("user", "u0")])

        asyncio.run(go())
        compiles = [e for e in timeline.TIMELINE.events(since=mark2)
                    if e.stage == "compile" and e.track == "device"]
        assert compiles == []

    def test_off_diagonal_bulk_check_compiles_nothing(self, kernel_kind):
        """A bulk check whose request count and distinct-subject count
        land in DIFFERENT pow-2 buckets must still hit a prewarmed jit
        key: the gather bucket is floored at the lane width, because an
        independent gather ladder put the first real fused check on an
        off-diagonal (lanes, gather) shape — a multi-second lazy
        compile on the hot path that the churn soak flagged (the shape
        retrace is attributed as a device-track compile slice, so this
        asserts on the timeline, not wall time)."""
        jx, _ = make_pair(seed=31)
        jx.warm_start(prewarm=True)
        mark = timeline.now()

        async def go():
            # 16 requests from 3 distinct subjects: gather bucket 16,
            # subject lanes bucket 8/32 — off-diagonal before the floor
            await jx.check_bulk_permissions([
                CheckRequest(ObjectRef("doc", f"d{i}"), "view",
                             SubjectRef("user", f"u{i % 3}"))
                for i in range(16)])

        asyncio.run(go())
        compiles = [e for e in timeline.TIMELINE.events(since=mark)
                    if e.stage == "compile" and e.track == "device"]
        assert compiles == []

    def test_prewarm_covers_flush_scatter_ladder(self, kernel_kind):
        """warm_start(prewarm=True) pre-compiles the delta-flush
        scatter ladder (pad_scatter buckets 16..512): each novel
        `.at[rows].set` shape was a lazy XLA scatter compile under the
        endpoint lock on the first drain of that size.  The prewarm
        scatters are idempotent — device tables must be bit-identical
        after — and recorded as `prewarm="flush"` compile events."""
        jx, oracle = make_pair(seed=33)
        jx.warm_start()  # build the graph without prewarm
        g = jx._graph
        before = {}
        for name in ("dev_main", "dev_aux", "dev_cav",
                     "edge_src", "edge_dst"):
            arr = getattr(g, name, None)
            if arr is not None and getattr(arr, "size", 0):
                before[name] = np.asarray(arr).copy()
        mark = timeline.now()
        warmed = g.prewarm_flush()
        assert warmed > 0
        evs = [e for e in timeline.TIMELINE.events(since=mark)
               if e.stage == "compile" and e.track == "rebuild"
               and e.attrs and e.attrs.get("prewarm") == "flush"]
        assert {e.bucket for e in evs} >= {16, 64, 512}
        for name, arr in before.items():
            np.testing.assert_array_equal(np.asarray(getattr(g, name)),
                                          arr, err_msg=name)
        # and the graph still answers correctly after the rewrite
        async def go():
            got = await jx.lookup_resources_batch(
                "doc", "view", [SubjectRef("user", "u0")])
            want = oracle.lookup_resources("doc", "view",
                                           SubjectRef("user", "u0"))
            assert sorted(got[0]) == sorted(want)

        asyncio.run(go())


# -- CPU e2e: the pipeline overlaps transfer with compute ---------------------


class TestOverlapE2E:
    def test_pipelined_dispatch_overlaps(self):
        """Sustained fused batches through the pipelined dispatcher:
        batch N's readback/transfer must overlap another batch's kernel
        window (overlap ratio >= 0.5 — the ROADMAP item 1 acceptance
        number; the serial seed measured ~0).

        Workload shape matters on the CPU backend: the graph is large
        enough (150k docs) that the per-batch kernel window exceeds the
        per-batch host encode, and depth 3 keeps a second started batch
        in flight so the host extraction of batch N-1 (which on CPU
        outweighs the kernel) doesn't drain the pipeline between
        dispatches — see docs/performance.md "pipeline depth".  One
        retry absorbs scheduler-noise flakes (precedent:
        test_device_batches_do_not_block_event_loop)."""
        # plane-served lookups would leave no kernel windows to overlap
        prev = GATES.enabled("LeopardIndex")
        GATES.set("LeopardIndex", False)
        try:
            ep = create_endpoint("jax://?max_batch=8&pipeline_depth=3",
                                 Bootstrap(schema_text=SCHEMA))
        finally:
            GATES.set("LeopardIndex", prev)
        n_users = 96
        ep.store.bulk_load(
            [parse_relationship(f"doc:d{d}#viewer@user:u{d % n_users}")
             for d in range(150_000)])
        subs = [SubjectRef("user", f"u{i}") for i in range(n_users)]

        async def go():
            # 96 subjects at max_batch=8 -> 12 fused batches queued at
            # once; the drain keeps up to 2 started batches in flight
            # while finishing the oldest (pipeline_depth=3)
            await asyncio.gather(*[
                ep.lookup_resources("doc", "view", s) for s in subs])

        asyncio.run(go())  # warm-up: jit compiles + arena allocation
        for attempt in range(2):
            mark = timeline.now()
            asyncio.run(go())
            evs = timeline.TIMELINE.events(since=mark)
            st = timeline.overlap_stats(evs)
            assert st is not None
            if st["ratio"] >= 0.5 or attempt == 1:
                assert st["ratio"] >= 0.5, st
                break
        # pipelined device packing leaves (almost) nothing attributable
        # to host pack/transpose stalls vs the dominant kernel time
        s = timeline.summary(since=mark)
        kernel_s = s["stage_ms"].get("kernel", 0.0)
        assert kernel_s > 0
        assert s["stall_s"].get("transpose", 0.0) <= 0.2 * kernel_s / 1e3
