"""Response filtering (reference pkg/authz/responsefilterer.go).

- StandardResponseFilterer: waits (≤10s) for the concurrently-running
  prefilter LookupResources, then filters list/object/Table response bodies
  against the allowed NamespacedName set.  Filter-denied single objects
  surface as 401 Unauthorized with a kube Status body; an empty filtered
  body becomes 404 (reference responsefilterer.go:716-735).
- WatchResponseFilterer: wraps the upstream watch stream; raw frames are
  replayed byte-exactly when allowed, buffered per NamespacedName until
  allowed, and dropped + unbuffered on revocation; Status events pass
  through (reference responsefilterer.go:423-714).
- EmptyResponseFilterer: pass-through for alwaysAllow requests.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from ..proxy.httpcore import Request, Response
from ..proxy.kube import RequestInfo
from ..proxy.restmapper import CachingRESTMapper, NoKindMatchError
from ..utils.admission import AdmissionRejectedError
from ..rules.engine import (
    ResolveInput,
    ResolvedPreFilter,
    RunnableRule,
    resolve_rel,
)
from ..spicedb.endpoints import PermissionsEndpoint
from ..utils.audit import (
    MAX_NAMES_PER_EVENT,
    NULL_SINK,
    OUTCOME_ALLOWED,
    OUTCOME_DENIED,
)
from ..utils.tracing import span
from .lookups import PrefilterResult, run_lookup_resources
from .rulesel import single_pre_filter_rule
from .watch import WATCH_FILTERED_TOTAL, WatchTracker, run_watch

PREFILTER_TIMEOUT = 10.0
# max not-yet-authorized frames buffered per watch (overflow drops oldest)
WATCH_BUFFER_CAP = 1024
# explained hidden objects per filtered list: one witness per hidden name
# up to this bound (a 10k-pod list must not trigger 10k oracle walks)
MAX_EXPLAINED_DENIALS = MAX_NAMES_PER_EVENT


class FilterError(Exception):
    pass


def _unauthorized_status(message: str) -> dict:
    return {
        "kind": "Status", "apiVersion": "v1", "metadata": {},
        "status": "Failure", "message": message, "reason": "Unauthorized",
        "code": 401,
    }


class _RecordingResult:
    """PrefilterResult wrapper recording each membership decision so the
    batched filter pass fans ONE audit event per object-group (allowed /
    denied), never one per object.  Bounded: counters plus a fixed-size
    name sample — a 10k-pod list must not allocate 10k tuples on the hot
    filter path just to feed an 8-name audit sample."""

    _SAMPLE = max(MAX_NAMES_PER_EVENT, MAX_EXPLAINED_DENIALS)

    def __init__(self, inner: PrefilterResult):
        self.inner = inner
        self.allowed_count = 0
        self.denied_count = 0
        self.allowed_names: list = []  # first _SAMPLE (namespace, name)
        self.denied_names: list = []

    @property
    def all_allowed(self) -> bool:
        return self.inner.all_allowed

    def is_allowed(self, namespace: str, name: str) -> bool:
        ok = self.inner.is_allowed(namespace, name)
        if ok:
            self.allowed_count += 1
            if len(self.allowed_names) < self._SAMPLE:
                self.allowed_names.append((namespace, name))
        else:
            self.denied_count += 1
            if len(self.denied_names) < self._SAMPLE:
                self.denied_names.append((namespace, name))
        return ok


class ResponseFilterer:
    async def filter_resp(self, resp: Response, req: Request) -> None:
        raise NotImplementedError


class EmptyResponseFilterer(ResponseFilterer):
    async def filter_resp(self, resp: Response, req: Request) -> None:
        return None


class StandardResponseFilterer(ResponseFilterer):
    def __init__(self, rest_mapper: CachingRESTMapper, input: ResolveInput,
                 filtered_rules: list, endpoint: Optional[PermissionsEndpoint]):
        self.rest_mapper = rest_mapper
        self.input = input
        self.filtered_rules = filtered_rules
        self.endpoint = endpoint
        self._prefilter_started = False
        self._prefilter_future: Optional[asyncio.Future] = None
        # strong ref: the loop holds tasks weakly; an unreferenced LR
        # task is collectable by the cyclic gc mid-flight (same latent
        # bug as the workflow engine's eager path)
        self._prefilter_task: Optional[asyncio.Task] = None
        self._resolved_prefilter: Optional[ResolvedPreFilter] = None
        self._prefilter_rule_name = ""

    def run_pre_filters(self) -> None:
        """Start the LR concurrently with the upstream request
        (reference responsefilterer.go:120-185)."""
        if self._prefilter_started:
            raise FilterError("pre-filters already started, cannot run again")
        self._prefilter_started = True

        rule = single_pre_filter_rule(self.filtered_rules)
        loop = asyncio.get_event_loop()
        self._prefilter_future = loop.create_future()
        if rule is None:
            self._prefilter_future.set_result(PrefilterResult(all_allowed=True))
            return
        if len(rule.pre_filter) != 1:
            raise FilterError(
                "pre-filter rule must have exactly one filter defined")
        f = rule.pre_filter[0]
        rel = resolve_rel(f.rel, self.input)
        resolved = ResolvedPreFilter(
            name_from_object_id=f.name_from_object_id,
            namespace_from_object_id=f.namespace_from_object_id,
            rel=rel,
        )
        self._resolved_prefilter = resolved
        self._prefilter_rule_name = rule.name

        async def runner():
            try:
                # the LR runs concurrently with the upstream request; the
                # task inherits the request's trace context, so the
                # kernel spans it triggers land in the request trace even
                # though respfilter only WAITS for it.  NOT a phase span:
                # it overlaps the `upstream` phase in wall time, and the
                # phase set must tile the request without double-counting
                with span("prefilter"):
                    result = await run_lookup_resources(self.endpoint,
                                                        resolved, self.input)
                if not self._prefilter_future.done():
                    self._prefilter_future.set_result(result)
            except Exception as e:
                if not self._prefilter_future.done():
                    self._prefilter_future.set_exception(e)

        self._prefilter_task = asyncio.ensure_future(runner())

    async def filter_resp(self, resp: Response, req: Request) -> None:
        if not self._prefilter_started:
            raise FilterError("pre-filters were not started, cannot filter response")
        try:
            # the wait is NOT the respfilter phase: its wall time is the
            # concurrent prefilter's (already attributed) — folding it in
            # would double-count kernel time against filtering
            with span("respfilter.wait"):
                result = await asyncio.wait_for(
                    asyncio.shield(self._prefilter_future), PREFILTER_TIMEOUT)
        except asyncio.TimeoutError:
            raise FilterError("timed out waiting for pre-filter") from None
        except FilterError:
            raise
        except AdmissionRejectedError:
            # admission rejection of the prefilter lookup is a 429 with
            # Retry-After, never a 502 bad-gateway wrap
            raise
        except Exception as e:
            raise FilterError(f"pre-filter error: {e}") from e

        from .middleware import AUDIT_KEY

        sink = req.context.get(AUDIT_KEY) or NULL_SINK
        if sink.enabled:
            # record membership decisions so the pass fans one audit
            # event per object-GROUP (allowed / denied), not per object
            result = _RecordingResult(result)
        with span("respfilter", phase=True):
            await self._apply_filters(resp, req, result)
        if isinstance(result, _RecordingResult):
            await self._audit_groups(req, sink, result)

    async def _audit_groups(self, req: Request, sink,
                            rec: "_RecordingResult") -> None:
        """One event per decision group; explained denials attach a
        relation-path witness per hidden object (bounded)."""
        from .middleware import audit_event_for, explain_requested

        rule = self._prefilter_rule_name
        if not rule:
            # no prefilter rule (all_allowed pass-through): keep the
            # request's matched rules from the context
            rule = ",".join(req.context.get("matched_rules") or ())
        # the frontier's evaluator (cache|kernel|oracle) outranks the
        # check-phase source for filtered-list events: the GROUP decision
        # is the prefilter's
        source = getattr(rec.inner, "source", "")
        if rec.allowed_count:
            sink.emit(audit_event_for(
                req, "respfilter", OUTCOME_ALLOWED, rule=rule,
                names=tuple(f"{ns}/{n}" if ns else n
                            for ns, n in
                            rec.allowed_names[:MAX_NAMES_PER_EVENT]),
                count=rec.allowed_count,
                **({"decision_source": source} if source else {})))
        if not rec.denied_count:
            return
        explain = None
        rel = (self._resolved_prefilter.rel
               if self._resolved_prefilter is not None else None)
        if rel is not None and explain_requested(req):
            from .explain import witness_dict_for_rel

            explain = {}
            for ns, n in rec.denied_names[:MAX_EXPLAINED_DENIALS]:
                oid = self._explain_oid(rel, ns, n)
                w = await witness_dict_for_rel(self.endpoint, rel,
                                               object_id=oid)
                if w is not None:
                    explain[oid] = w
        sink.emit(audit_event_for(
            req, "respfilter", OUTCOME_DENIED, rule=rule,
            rel=rel.rel_string() if rel is not None else "",
            names=tuple(f"{ns}/{n}" if ns else n
                        for ns, n in
                        rec.denied_names[:MAX_NAMES_PER_EVENT]),
            count=rec.denied_count,
            explain=explain,
            **({"decision_source": source} if source else {})))

    def _explain_oid(self, rel, namespace: str, name: str) -> str:
        """Best-effort inverse of the rule's fromObjectID expressions:
        the proxy's dominant id convention is namespacedName ("ns/name",
        bare name cluster-scoped).  Rules whose namespace comes from the
        REQUEST (lookups.py namespace fallback) key objects by bare
        name — detected by asking the endpoint's store which id it
        actually knows, so the witness never probes a fabricated id."""
        primary = f"{namespace}/{name}" if namespace else name
        if namespace:
            store = getattr(self.endpoint, "store", None)
            if store is not None:
                try:
                    ids = store.object_ids_of_type(rel.resource_type)
                    if primary not in ids and name in ids:
                        return name
                except Exception:
                    pass
        return primary

    async def _apply_filters(self, resp: Response, req: Request,
                             result: PrefilterResult) -> None:
        info: RequestInfo = req.context["request_info"]
        # error responses pass through unfiltered (responsefilterer.go:229-234)
        if 400 <= resp.status <= 599:
            return

        from ..proxy import k8sproto

        # a Table request short-circuits GVK handling
        if "as=Table" in req.headers.get("Accept", ""):
            if k8sproto.is_k8s_proto(resp.body):
                try:
                    body = self._filter_table_proto(resp.body, result)
                except k8sproto.K8sProtoError as e:
                    raise FilterError(
                        f"error decoding protobuf table: {e}") from e
                self._write_resp(resp, body, None)
                return
            try:
                body, err = self._filter_table(resp.body, result)
            except ValueError as e:
                raise FilterError(f"error decoding table: {e}") from e
            self._write_resp(resp, body, err)
            return

        content_type = resp.headers.get("Content-Type", "application/json")
        media = content_type.split(";")[0].strip()
        if "json" not in media:
            if k8sproto.is_k8s_proto(resp.body):
                # negotiated protobuf body: filter at the wire level
                # (reference responsefilterer.go:241-301; unparseable
                # bodies reject like unrecognized-GVK proto at 278-280)
                await self._filter_proto(resp, info, result)
                return
            gvk = await self._gvk(info)
            raise FilterError(
                f"unsupported media type {media} for gvk {gvk}")

        from ..utils import timeline
        try:
            with timeline.serving_span("decode",
                                       nbytes=len(resp.body or b"")):
                decoded = json.loads(resp.body) if resp.body else {}
        except ValueError as e:
            raise FilterError(f"failed to decode response body: {e}") from e

        if len(info.parts) == 1:
            # list response
            with timeline.serving_span("filter"):
                err = self._filter_list(decoded, result)
            with timeline.serving_span("serialize") as ser_attrs:
                body = b"" if err else json.dumps(decoded).encode()
                ser_attrs["nbytes"] = len(body)
            self._write_resp(resp, body, err)
        else:
            with timeline.serving_span("filter"):
                err = self._filter_object(decoded, result)
            self._write_resp(resp, resp.body if not err else b"", err)

    async def _gvk(self, info: RequestInfo):
        try:
            return await self.rest_mapper.kind_for(
                info.api_group, info.api_version, info.resource)
        except NoKindMatchError as e:
            raise FilterError(str(e)) from e

    async def _filter_proto(self, resp: Response, info: RequestInfo,
                            result: PrefilterResult) -> None:
        """Filter a `k8s\\x00`-enveloped protobuf list/object body by
        wire-level splicing (proxy/k8sproto.py)."""
        from ..proxy import k8sproto

        try:
            api_version, kind, raw, ct = k8sproto.decode_unknown(resp.body)
            if len(info.parts) == 1 and kind.endswith("List"):
                filtered = k8sproto.filter_list_raw(raw, result.is_allowed)
                body = k8sproto.encode_unknown(api_version, kind, filtered, ct)
                self._write_resp(resp, body, None)
            else:
                namespace, name = k8sproto.object_meta(raw)
                if result.is_allowed(namespace, name):
                    self._write_resp(resp, resp.body, None)
                else:
                    self._write_resp(resp, b"", FilterError("unauthorized"))
        except k8sproto.K8sProtoError as e:
            raise FilterError(
                f"unable to filter protobuf body for gvk "
                f"{await self._gvk(info)}: {e}") from e

    def _filter_table_proto(self, body: bytes, result: PrefilterResult) -> bytes:
        from ..proxy import k8sproto

        api_version, kind, raw, ct = k8sproto.decode_unknown(body)
        filtered = k8sproto.filter_table_raw(raw, result.is_allowed)
        return k8sproto.encode_unknown(api_version, kind, filtered, ct)

    def _filter_table(self, body: bytes, result: PrefilterResult) -> tuple:
        table = json.loads(body)
        rows = table.get("rows") or []
        allowed_rows = []
        for r in rows:
            pom = (r.get("object") or {}).get("metadata") or {}
            if result.is_allowed(pom.get("namespace", "") or "",
                                 pom.get("name", "") or ""):
                allowed_rows.append(r)
        table["rows"] = allowed_rows
        return json.dumps(table).encode(), None

    def _filter_list(self, decoded: dict, result: PrefilterResult):
        items = decoded.get("items")
        if not isinstance(items, list):
            return None
        allowed = []
        for item in items:
            meta = (item.get("metadata") or {}) if isinstance(item, dict) else {}
            if result.is_allowed(meta.get("namespace", "") or "",
                                 meta.get("name", "") or ""):
                allowed.append(item)
        decoded["items"] = allowed
        return None

    def _filter_object(self, decoded: dict, result: PrefilterResult):
        meta = decoded.get("metadata") or {}
        if result.is_allowed(meta.get("namespace", "") or "",
                             meta.get("name", "") or ""):
            return None
        return FilterError("unauthorized")

    @staticmethod
    def _write_resp(resp: Response, body: bytes, err) -> None:
        """401-on-error / 404-on-empty (reference responsefilterer.go:716-735)."""
        if err is not None:
            body = json.dumps(_unauthorized_status(str(err))).encode()
            resp.status = 401
        resp.body = body
        resp.headers.set("Content-Length", str(len(body)))
        if len(body) == 0:
            resp.status = 404


def new_empty_response_filterer(rest_mapper, input) -> EmptyResponseFilterer:
    return EmptyResponseFilterer()


class WatchResponseFilterer(ResponseFilterer):
    # class-level defaults so partially-constructed instances (tests
    # drive _filtered_stream directly) still count and audit safely
    input: Optional[ResolveInput] = None
    watch_rule: Optional[RunnableRule] = None
    audit = NULL_SINK

    def __init__(self, rest_mapper: CachingRESTMapper, input: ResolveInput,
                 watch_rule: RunnableRule, endpoint: PermissionsEndpoint,
                 audit=NULL_SINK):
        self.rest_mapper = rest_mapper
        self.input = input
        self.watch_rule = watch_rule
        self.endpoint = endpoint
        self.audit = audit
        self._tracker: Optional[WatchTracker] = None
        self._watch_task: Optional[asyncio.Task] = None

    @property
    def _resource(self) -> str:
        """Bounded metric label: the kube resource this watch serves."""
        info = self.input.request if self.input is not None else None
        return (info.resource if info is not None else "") or "unknown"

    def _count_filtered(self) -> None:
        WATCH_FILTERED_TOTAL.inc(resource=self._resource)

    def _audit_watch(self, decision: str, namespace: str, name: str,
                     message: str = "") -> None:
        """Mid-stream decision event (no live Request context: watch
        frames outlive the request that opened the stream)."""
        if not self.audit.enabled:
            return
        from ..utils.audit import AuditEvent
        from ..utils import tracing

        user = self.input.user if self.input is not None else None
        info = (self.input.request if self.input is not None
                else None) or RequestInfo()
        tr = tracing.current_trace()
        attrs = getattr(tr, "attrs", None)
        self.audit.emit(AuditEvent(
            stage="watch", decision=decision,
            user=user.name if user else "",
            groups=tuple(user.groups) if user else (),
            verb="watch", api_group=info.api_group,
            api_version=info.api_version, resource=info.resource,
            namespace=namespace, names=(name,) if name else (), count=1,
            rule=self.watch_rule.name if self.watch_rule else "",
            backend=getattr(self.audit, "backend", ""),
            trace_id=getattr(tr, "trace_id", ""),
            tier_path=(str(attrs.get("tier_path") or "")
                       if isinstance(attrs, dict) else ""),
            message=message))

    def run_watcher(self) -> None:
        """Start the SpiceDB-side watch (reference responsefilterer.go:434-460)."""
        if self._tracker is not None:
            raise FilterError("watcher already started, cannot run again")
        if len(self.watch_rule.pre_filter) != 1:
            raise FilterError("watch rule must have exactly one pre-filter defined")
        f = self.watch_rule.pre_filter[0]
        rel = resolve_rel(f.rel, self.input)
        resolved = ResolvedPreFilter(
            name_from_object_id=f.name_from_object_id,
            namespace_from_object_id=f.namespace_from_object_id,
            rel=rel,
        )
        self._tracker = WatchTracker()
        # subscribe synchronously: tuple writes racing the watch setup must
        # not be lost before the watch task first runs
        watcher = self.endpoint.watch([resolved.rel.resource_type])
        self._watch_task = asyncio.ensure_future(
            run_watch(self.endpoint, self._tracker, resolved, self.input,
                      watcher=watcher))

    async def filter_resp(self, resp: Response, req: Request) -> None:
        if self._tracker is None:
            raise FilterError("watcher was not started, cannot filter response")
        if resp.stream is None:
            return  # error responses pass through
        with span("respfilter", phase=True):
            self._wrap_stream(resp)

    def _wrap_stream(self, resp: Response) -> None:
        upstream = resp.stream
        # the upstream Content-Type decides the stream framing/codec, the
        # analog of the reference's negotiated streaming serializer
        # (responsefilterer.go:500-506)
        content_type = resp.headers.get("Content-Type", "")
        proto = "protobuf" in content_type
        resp.stream = self._filtered_stream(upstream, proto=proto)

    @staticmethod
    def _decode_frame(raw: bytes, proto: bool) -> tuple:
        """(event_type, namespace, name, is_status) for one raw frame.
        Raises ValueError when the frame cannot be decoded — the caller
        must DROP such frames (fail closed), never relay them."""
        if proto:
            from ..proxy import k8sproto

            try:
                ev, api_version, kind, obj_raw = k8sproto.decode_watch_event(
                    raw[4:])
                if ev == "ERROR" or kind == "Status":
                    return ev, "", "", True
                # Table event unwrapping (responsefilterer.go:667-677)
                if kind == "Table" and "meta.k8s.io" in api_version:
                    namespace, name = k8sproto.table_first_row_meta(obj_raw)
                else:
                    namespace, name = k8sproto.object_meta(obj_raw)
            except k8sproto.K8sProtoError as e:
                raise ValueError(str(e)) from e
            return ev, namespace, name, False
        event = json.loads(raw)  # ValueError propagates to the caller
        if not isinstance(event, dict):
            raise ValueError("watch frame is not a JSON object")
        obj = event.get("object") or {}
        ev = event.get("type", "")
        if ev == "ERROR" or obj.get("kind") == "Status":
            return ev, "", "", True
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        namespace = meta.get("namespace", "")
        # Table event unwrapping (responsefilterer.go:667-677)
        if (obj.get("kind") == "Table"
                and "meta.k8s.io" in obj.get("apiVersion", "")):
            for r in obj.get("rows") or []:
                rmeta = (r.get("object") or {}).get("metadata") or {}
                name = rmeta.get("name", "")
                namespace = rmeta.get("namespace", "")
                break
        return ev, namespace, name, False

    async def _filtered_stream(self, upstream, proto: bool = False):
        """Replay / buffer / revoke raw frames
        (reference responsefilterer.go:487-714)."""
        from .frames import frame_length_delimited, frame_lines

        framer = frame_length_delimited if proto else frame_lines
        merged: asyncio.Queue = asyncio.Queue()

        async def pump_upstream():
            try:
                async for raw in framer(upstream):
                    await merged.put(("frame", raw))
            finally:
                await merged.put(("eof", None))

        async def pump_changes():
            while True:
                change = await self._tracker.changes.get()
                await merged.put(("change", change))

        pump1 = asyncio.ensure_future(pump_upstream())
        pump2 = asyncio.ensure_future(pump_changes())
        allowed: set = set()
        # bounded not-yet-authorized frame buffer: a watch on a resource
        # the subject will never be granted must not grow memory without
        # limit — overflow drops the OLDEST buffered frame (the client
        # re-lists on resume, matching kube watch semantics)
        buffered: dict = {}
        try:
            while True:
                kind, payload = await merged.get()
                if kind == "eof":
                    return
                if kind == "change":
                    nn = (payload.namespace, payload.name)
                    if payload.allowed:
                        if nn not in allowed:
                            # grant events are audited symmetrically
                            # with revocations (per-frame deliveries are
                            # not — one decision, not one per frame)
                            self._audit_watch(OUTCOME_ALLOWED, *nn,
                                              message="granted")
                        allowed.add(nn)
                        if nn in buffered:
                            raw = buffered.pop(nn)
                            yield raw
                    else:
                        was_visible = nn in allowed or nn in buffered
                        if nn in buffered:
                            # a buffered frame the client will never see
                            self._count_filtered()
                        allowed.discard(nn)
                        buffered.pop(nn, None)
                        if was_visible:
                            self._audit_watch(OUTCOME_DENIED, *nn,
                                              message="revoked")
                    continue
                raw = payload
                try:
                    ev, namespace, name, is_status = self._decode_frame(
                        raw, proto)
                except ValueError as e:
                    # FAIL CLOSED: an undecodable frame may carry an object
                    # we cannot authorize — drop it with an error, never
                    # relay it (this path previously passed frames through
                    # unfiltered, an authorization bypass)
                    import logging
                    logging.getLogger(__name__).error(
                        "dropping undecodable watch frame (%d bytes, "
                        "proto=%s): %s", len(raw), proto, e)
                    self._count_filtered()
                    continue
                if is_status:
                    # status events pass through and the stream CONTINUES
                    # (reference responsefilterer.go:645-651 writes the
                    # chunk and keeps reading)
                    yield raw
                    continue
                if ev in ("ADDED", "MODIFIED"):
                    nn = (namespace or "", name)
                    if nn in allowed:
                        yield raw
                    else:
                        # buffered, NOT yet counted as filtered: a later
                        # grant may still deliver it — only definitive
                        # drops (revocation/overflow/undecodable) count
                        buffered[nn] = raw
                        if len(buffered) > WATCH_BUFFER_CAP:
                            victim = next(iter(buffered))
                            buffered.pop(victim)
                            self._count_filtered()
                            import logging
                            logging.getLogger(__name__).warning(
                                "watch buffer cap %d exceeded; dropped "
                                "buffered frame for %s", WATCH_BUFFER_CAP,
                                victim)
                # DELETED / BOOKMARK events: the reference neither replays nor
                # buffers them (only ADDED/MODIFIED are handled)
        finally:
            pump1.cancel()
            pump2.cancel()
            if self._watch_task is not None:
                self._watch_task.cancel()
