"""Backend kubeconfig loading and upstream-transport construction.

Python equivalent of the reference's kubeconfig plumbing
(pkg/proxy/options.go:382-410 `configFromPath`, options.go:429-449
`NewTransportForKubeconfig`, and the in-cluster branch of `Complete`,
options.go:223-246): parse a kubeconfig YAML, honor `--override-upstream`
(rewrite every cluster server to the in-cluster service address from the
environment), and build a TLS client transport carrying the kubeconfig's
client certificate and/or bearer token.
"""

from __future__ import annotations

import base64
import ipaddress
import os
import ssl
import tempfile
from dataclasses import dataclass
from typing import Optional

import yaml

from .httpcore import Request, Response, Transport, H11Transport

IN_CLUSTER_TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
IN_CLUSTER_CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


@dataclass
class KubeconfigContext:
    """The resolved current-context of a kubeconfig."""
    server: str = ""
    ca_data: bytes = b""
    client_cert_data: bytes = b""
    client_key_data: bytes = b""
    token: str = ""
    insecure_skip_tls_verify: bool = False


def _b64_or_file(entry: dict, data_key: str, path_key: str,
                 base_dir: str = "") -> bytes:
    if entry.get(data_key):
        return base64.b64decode(entry[data_key])
    path = entry.get(path_key)
    if path:
        # relative cert paths resolve against the kubeconfig's directory
        # (standard clientcmd semantics)
        if base_dir and not os.path.isabs(path):
            path = os.path.join(base_dir, path)
        with open(path, "rb") as f:
            return f.read()
    return b""


def load_kubeconfig(path: str,
                    override_upstream: bool = False) -> KubeconfigContext:
    """Load the current-context of a kubeconfig file.

    With `override_upstream`, the server address is taken from the
    `KUBERNETES_SERVICE_HOST`/`KUBERNETES_SERVICE_PORT` environment instead of
    the file (reference options.go:396-407).
    """
    if not os.path.isabs(path):
        path = os.path.join(os.getcwd(), path)
    with open(path, "r", encoding="utf-8") as f:
        data = yaml.safe_load(f.read()) or {}

    def by_name(section: str, name: str) -> dict:
        for item in data.get(section, []) or []:
            if item.get("name") == name:
                return item
        return {}

    current = data.get("current-context", "")
    ctx = by_name("contexts", current).get("context", {}) if current else {}
    clusters = data.get("clusters", []) or []
    users = data.get("users", []) or []
    # a named cluster/user that is missing is an error (clientcmd semantics);
    # the single-entry fallback applies only when nothing is named
    if ctx.get("cluster"):
        cluster = by_name("clusters", ctx["cluster"]).get("cluster")
        if cluster is None:
            raise ValueError(
                f"kubeconfig context references unknown cluster"
                f" {ctx['cluster']!r}")
    else:
        cluster = clusters[0].get("cluster", {}) if clusters else {}
    if ctx.get("user"):
        user = by_name("users", ctx["user"]).get("user")
        if user is None:
            raise ValueError(
                f"kubeconfig context references unknown user {ctx['user']!r}")
    else:
        user = users[0].get("user", {}) if users else {}

    base_dir = os.path.dirname(path)
    out = KubeconfigContext(
        server=cluster.get("server", ""),
        ca_data=_b64_or_file(cluster, "certificate-authority-data",
                             "certificate-authority", base_dir),
        client_cert_data=_b64_or_file(user, "client-certificate-data",
                                      "client-certificate", base_dir),
        client_key_data=_b64_or_file(user, "client-key-data", "client-key",
                                     base_dir),
        token=user.get("token", ""),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")),
    )
    if override_upstream:
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "")
        if host:
            hostpart = f"[{host}]" if ":" in host else host
            out.server = f"https://{hostpart}:{port}" if port else f"https://{hostpart}"
    return out


def in_cluster_context() -> KubeconfigContext:
    """Ambient service-account config (reference options.go:225-246 via
    rest.InClusterConfig)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "")
    if not host:
        raise RuntimeError(
            "not running in-cluster: KUBERNETES_SERVICE_HOST is unset")
    token = ""
    if os.path.exists(IN_CLUSTER_TOKEN_PATH):
        with open(IN_CLUSTER_TOKEN_PATH, "r", encoding="utf-8") as f:
            token = f.read().strip()
    ca = b""
    if os.path.exists(IN_CLUSTER_CA_PATH):
        with open(IN_CLUSTER_CA_PATH, "rb") as f:
            ca = f.read()
    hostpart = f"[{host}]" if ":" in host else host
    return KubeconfigContext(server=f"https://{hostpart}:{port}",
                             ca_data=ca, token=token)


def _client_ssl_context(ctx: KubeconfigContext) -> Optional[ssl.SSLContext]:
    if not ctx.server.startswith("https"):
        return None
    ssl_ctx = ssl.create_default_context()
    if ctx.insecure_skip_tls_verify:
        ssl_ctx.check_hostname = False
        ssl_ctx.verify_mode = ssl.CERT_NONE
    elif ctx.ca_data:
        ssl_ctx = ssl.create_default_context(cadata=ctx.ca_data.decode())
    if ctx.client_cert_data and ctx.client_key_data:
        # ssl requires file paths for the client chain
        with tempfile.NamedTemporaryFile("wb", suffix=".crt",
                                         delete=False) as cf:
            cf.write(ctx.client_cert_data)
            cert_path = cf.name
        with tempfile.NamedTemporaryFile("wb", suffix=".key",
                                         delete=False) as kf:
            kf.write(ctx.client_key_data)
            key_path = kf.name
        try:
            ssl_ctx.load_cert_chain(cert_path, key_path)
        finally:
            os.unlink(cert_path)
            os.unlink(key_path)
    return ssl_ctx


class BearerTokenTransport(Transport):
    """Injects the service-account / kubeconfig bearer token upstream.

    The proxy strips the *client's* Authorization header before forwarding
    (pkg/proxy/server.go's director rewrites auth); the upstream credential
    comes from the backend kubeconfig, mirroring rest.Config's transport.
    """

    def __init__(self, base: Transport, token: str):
        self.base = base
        self.token = token

    async def round_trip(self, req: Request) -> Response:
        if self.token:
            req.headers.set("Authorization", f"Bearer {self.token}")
        return await self.base.round_trip(req)

    async def close(self) -> None:
        await self.base.close()


def transport_for(ctx: KubeconfigContext) -> Transport:
    """Build the upstream transport for a resolved kubeconfig context
    (reference NewTransportForKubeconfig, options.go:429-449)."""
    if not ctx.server:
        raise ValueError("kubeconfig has no cluster server address")
    transport: Transport = H11Transport(ctx.server,
                                        ssl_context=_client_ssl_context(ctx))
    if ctx.token:
        transport = BearerTokenTransport(transport, ctx.token)
    return transport


# ---------------------------------------------------------------------------
# Serving certificates
# ---------------------------------------------------------------------------

def generate_self_signed_cert(cert_dir: str, pair_name: str = "tls",
                              hosts: Optional[list] = None) -> tuple:
    """Generate a self-signed serving certificate into `cert_dir` if absent;
    returns (cert_path, key_path).

    Mirrors SecureServing.MaybeDefaultWithSelfSignedCerts (reference
    options.go:286-299): reused if already present, SANs cover localhost and
    the bind hosts.
    """
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(cert_dir, exist_ok=True)
    cert_path = os.path.join(cert_dir, f"{pair_name}.crt")
    key_path = os.path.join(cert_dir, f"{pair_name}.key")
    if os.path.exists(cert_path) and os.path.exists(key_path):
        return cert_path, key_path

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    names = {"localhost"}
    ips = {ipaddress.ip_address("127.0.0.1"), ipaddress.ip_address("::1")}
    for h in hosts or []:
        if not h or h == "0.0.0.0" or h == "::":
            continue
        try:
            ips.add(ipaddress.ip_address(h))
        except ValueError:
            names.add(h)
    san = x509.SubjectAlternativeName(
        [x509.DNSName(n) for n in sorted(names)]
        + [x509.IPAddress(ip) for ip in sorted(ips, key=str)])
    subject = x509.Name([x509.NameAttribute(
        NameOID.COMMON_NAME, "spicedb-kubeapi-proxy-tpu-self-signed")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(san, critical=False)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return cert_path, key_path


def serving_ssl_context(cert_file: str, key_file: str,
                        client_ca_file: str = "",
                        extra_client_ca_files: tuple = ()) -> ssl.SSLContext:
    """Server-side TLS context; with a client CA, client certificates are
    requested and verified (kube client-cert authn).  Extra CAs (e.g. the
    front-proxy requestheader CA) join the handshake trust store; the
    authenticators decide per-CA trust afterwards."""
    ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ssl_ctx.load_cert_chain(cert_file, key_file)
    ca_files = ([client_ca_file] if client_ca_file else []) + \
        [f for f in extra_client_ca_files if f]
    for ca in ca_files:
        ssl_ctx.load_verify_locations(ca)
    if ca_files:
        ssl_ctx.verify_mode = ssl.CERT_OPTIONAL
    return ssl_ctx
