"""Unified static analyzer (scripts/analysis, scripts/analyze.py;
docs/static-analysis.md).

Every rule is tested against the fixture corpus in
tests/analysis_fixtures/ — one true-positive and one near-miss negative
per rule — plus the driver-level machinery: `# noqa: AXXX(reason)`
suppression (reason REQUIRED), the checked-in baseline round-trip
(add -> grandfather -> fix -> baseline shrinks), exit codes, JSON
output, the thin scripts/lint.py wrapper, and a self-run asserting the
package itself is clean modulo the checked-in baseline.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
SCRIPTS = REPO / "scripts"
FIXTURES = REPO / "tests" / "analysis_fixtures"
sys.path.insert(0, str(SCRIPTS))

from analysis import core  # noqa: E402
from analysis.rules_async import rule_a001, rule_a002  # noqa: E402
from analysis.rules_gates import rule_a004  # noqa: E402
from analysis.rules_jit import rule_a005  # noqa: E402
from analysis.rules_locks import rule_a003  # noqa: E402
from analysis.rules_trace import rule_a006  # noqa: E402


def load(*names):
    files = [FIXTURES / n for n in names]
    sources, errors = core.load_sources(files, REPO)
    assert not errors, errors
    assert len(sources) == len(files)
    return sources


def lines(findings, rule=None):
    return sorted(f.line for f in findings
                  if rule is None or f.rule == rule)


class TestA001:
    def test_true_positives(self):
        findings = rule_a001(load("a001_tp.py"))
        assert lines(findings) == [11, 15, 19, 23, 27, 31, 36]
        assert all(f.rule == "A001" for f in findings)
        # each distinct blocking family is named in its message
        msgs = " | ".join(f.message for f in findings)
        for needle in ("time.sleep", "os.fsync", "subprocess.run",
                       "np.asarray", "block_until_ready", "open()",
                       "fsync"):
            assert needle in msgs, needle

    def test_near_misses(self):
        # executor/to_thread hops, bare references, and sync helpers
        # are all legal
        assert rule_a001(load("a001_neg.py")) == []


class TestA002:
    def test_true_positives(self):
        findings = rule_a002(load("a002_tp.py"))
        assert lines(findings) == [11, 15, 20, 24]
        assert all(f.rule == "A002" for f in findings)
        # the chained-receiver form has no resolvable name chain but
        # names the method in its message all the same
        assert any("create_task" in f.message
                   for f in findings if f.line == 24)

    def test_near_misses(self):
        # stored / awaited / appended / gathered / returned all keep a
        # reference
        assert rule_a002(load("a002_neg.py")) == []


class TestA003:
    def test_abba_cycle(self):
        findings = rule_a003(load("a003_cycle_tp.py"))
        assert len(findings) == 2
        assert all("lock-order cycle" in f.message for f in findings)
        msgs = " | ".join(f.message for f in findings)
        assert "_stats_lock" in msgs and "_window_lock" in msgs
        # the second cycle's first leg is a MULTI-ITEM `with a, b:` —
        # items must edge left-to-right like the nested form
        assert "_ledger_lock" in msgs and "_gauge_lock" in msgs

    def test_await_under_sync_lock(self):
        findings = rule_a003(load("a003_await_tp.py"))
        assert len(findings) == 1
        assert "`await` while holding sync lock" in findings[0].message
        assert findings[0].symbol == "Ledger.flush"

    def test_self_deadlock_via_call_closure(self):
        findings = rule_a003(load("a003_selfdeadlock_tp.py"))
        assert len(findings) == 1
        assert "self-deadlock" in findings[0].message
        assert "non-reentrant" in findings[0].message

    def test_near_misses(self):
        # consistent order, RLock re-entry, async-lock awaits
        assert rule_a003(load("a003_neg.py")) == []


class TestA004:
    TP = "spicedb_kubeapi_proxy_tpu/utils/admission.py"
    NEG = "spicedb_kubeapi_proxy_tpu/utils/timeline.py"

    def test_true_positives(self):
        findings = rule_a004(load(self.TP))
        # 24 = `_LIMIT += 1` (AugAssign counter idiom)
        assert lines(findings) == [10, 14, 19, 24]
        kinds = " | ".join(f.message for f in findings)
        assert "metric mutation" in kinds
        assert "module registry" in kinds
        assert "module global" in kinds
        assert all("AdmissionControl" in f.message for f in findings)

    def test_near_misses(self):
        # early-return guard, if-wrapped, gated-caller closure, and the
        # class-level `# noqa: A004(...)` constructed-behind-gate
        # declaration
        assert rule_a004(load(self.NEG)) == []

    def test_ungated_module_ignored(self):
        # the same shapes outside a gated module are not A004's business
        assert rule_a004(load("a001_tp.py")) == []


class TestA005:
    TP = "spicedb_kubeapi_proxy_tpu/ops/kernels_tp.py"
    NEG = "spicedb_kubeapi_proxy_tpu/ops/kernels_neg.py"

    def test_true_positives(self):
        findings = rule_a005(load(self.TP))
        assert lines(findings) == [15, 26, 27, 28, 30, 32, 41]
        msgs = " | ".join(f.message for f in findings)
        assert "np.zeros" in msgs          # via factory-returned closure
        assert "time.time" in msgs
        assert "datetime.datetime.now" in msgs
        assert ".item()" in msgs
        assert "while" in msgs and "for" in msgs
        # the @jax.jit DECORATOR form is a root too, not just the
        # jax.jit(fn) call form
        assert any(f.symbol == "decorated_kernel" for f in findings)

    def test_factory_reach(self):
        # the host-np finding sits inside the closure the factory
        # returned — reached through `evaluate = make_evaluate(...)`,
        # which no comment fence could see
        findings = rule_a005(load(self.TP))
        assert any(f.symbol == "make_evaluate.evaluate" for f in findings)

    def test_near_misses(self):
        # shape-range unrolls, static pytree iteration, dtype scalars,
        # and unreached host helpers
        assert rule_a005(load(self.NEG)) == []


class TestA006:
    def test_true_positives(self):
        findings = rule_a006(load("a006_tp.py"))
        # 33 is the module-scope hop (symbol "")
        assert lines(findings) == [7, 13, 14, 21, 25, 33]
        assert all(f.rule == "A006" for f in findings)
        assert all("trace propagation" in f.message for f in findings)
        by_line = {f.line: f.symbol for f in findings}
        assert by_line[21] == "Client.fetch"
        assert by_line[33] == ""
        # both round_trip calls in the fan-out helper are flagged —
        # coverage is per call site, not per function
        assert by_line[13] == by_line[14] == "fanout_no_headers"

    def test_near_misses(self):
        # hop_span / propagation_headers coverage (name and attribute
        # forms), the `round_trip`-wrapper exemption, bare references,
        # and the noqa'd external hop
        sources = load("a006_neg.py")
        kept, suppressed = core.apply_noqa(rule_a006(sources), sources)
        assert kept == []
        # the external-kube hop is suppressed WITH a reason, not clean
        assert len(suppressed) == 1
        assert "external kube" in suppressed[0].reason


class TestSuppression:
    def test_noqa_reason_required(self):
        sources = load("noqa_fixture.py")
        kept, suppressed = core.apply_noqa(rule_a001(sources), sources)
        # line 7: suppressed with reason; line 11: bare noqa -> A000;
        # line 15: wrong code named -> original finding survives
        assert [s.finding.line for s in suppressed] == [7]
        assert suppressed[0].reason.startswith("startup-only")
        assert sorted((f.rule, f.line) for f in kept) == [
            ("A000", 11), ("A001", 15)]

    def test_a000_names_the_rule(self):
        sources = load("noqa_fixture.py")
        kept, _ = core.apply_noqa(rule_a001(sources), sources)
        a000 = [f for f in kept if f.rule == "A000"][0]
        assert "A001" in a000.message


class TestBaseline:
    def _findings(self):
        return rule_a001(load("a001_tp.py"))

    def test_round_trip_and_shrink(self, tmp_path):
        findings = self._findings()
        bl_path = tmp_path / "baseline.json"
        core.Baseline.write(bl_path, findings)
        bl = core.Baseline(bl_path)
        new, baselined, stale = bl.filter(findings)
        assert new == [] and len(baselined) == len(findings)
        assert stale == []
        # "fix" two findings: they surface as stale entries, and a
        # rewrite shrinks the file
        fixed = findings[2:]
        new, baselined, stale = core.Baseline(bl_path).filter(fixed)
        assert new == [] and len(stale) == 2
        core.Baseline.write(bl_path, fixed)
        assert len(json.loads(bl_path.read_text())["findings"]) == \
            len(findings) - 2

    def test_multiplicity_consumed(self, tmp_path):
        findings = self._findings()
        # baseline knows ONE instance; a duplicate finding stays new
        bl_path = tmp_path / "baseline.json"
        core.Baseline.write(bl_path, findings[:1])
        dup = core.Finding(findings[0].rule, findings[0].path,
                           findings[0].line + 50, findings[0].message,
                           findings[0].symbol)
        new, baselined, _ = core.Baseline(bl_path).filter(
            [findings[0], dup])
        assert len(baselined) == 1 and len(new) == 1

    def test_line_drift_does_not_invalidate(self, tmp_path):
        findings = self._findings()
        bl_path = tmp_path / "baseline.json"
        core.Baseline.write(bl_path, findings)
        drifted = [core.Finding(f.rule, f.path, f.line + 7, f.message,
                                f.symbol) for f in findings]
        new, baselined, stale = core.Baseline(bl_path).filter(drifted)
        assert new == [] and stale == []


def run_driver(*args):
    return subprocess.run(
        [sys.executable, str(SCRIPTS / "analyze.py"), *args],
        capture_output=True, text=True, cwd=REPO)


class TestDriverCli:
    def test_findings_fail(self, tmp_path):
        out = run_driver(str(FIXTURES / "a001_tp.py"),
                         "--baseline", str(tmp_path / "b.json"))
        assert out.returncode == 1, out.stdout
        assert "A001" in out.stdout

    def test_clean_file_passes(self, tmp_path):
        out = run_driver(str(FIXTURES / "a001_neg.py"),
                         "--baseline", str(tmp_path / "b.json"))
        assert out.returncode == 0, out.stdout

    def test_update_baseline_then_clean(self, tmp_path):
        bl = tmp_path / "b.json"
        out = run_driver(str(FIXTURES / "a001_tp.py"),
                         "--baseline", str(bl), "--update-baseline")
        assert out.returncode == 0, out.stdout
        assert len(json.loads(bl.read_text())["findings"]) == 7
        out = run_driver(str(FIXTURES / "a001_tp.py"), "--baseline",
                         str(bl))
        assert out.returncode == 0, out.stdout
        assert "7 baselined" in out.stdout

    def test_json_output_shape(self, tmp_path):
        out = run_driver(str(FIXTURES / "a002_tp.py"), "--json",
                         "--baseline", str(tmp_path / "b.json"))
        assert out.returncode == 1
        payload = json.loads(out.stdout)
        assert payload["version"] == 1
        assert {f["rule"] for f in payload["findings"]} == {"A002"}
        assert {"rule", "path", "line", "symbol", "message"} <= set(
            payload["findings"][0])

    def test_rule_subset(self, tmp_path):
        out = run_driver(str(FIXTURES / "a001_tp.py"), "--rules", "A002",
                         "--baseline", str(tmp_path / "b.json"))
        assert out.returncode == 0, out.stdout  # A001 bugs, A002 lens

    def test_unknown_rule_is_usage_error(self):
        out = run_driver("--rules", "A999")
        assert out.returncode == 2

    def test_noqa_without_reason_fails_driver(self, tmp_path):
        out = run_driver(str(FIXTURES / "noqa_fixture.py"),
                         "--baseline", str(tmp_path / "b.json"))
        assert out.returncode == 1
        assert "A000" in out.stdout
        assert "no reason" in out.stdout

    def test_self_run_package_clean_modulo_baseline(self):
        """The acceptance gate: the package analyzes clean against the
        CHECKED-IN baseline (the same invocation check.sh runs, minus
        the schema subprocess)."""
        out = run_driver("--legacy")
        assert out.returncode == 0, out.stdout
        assert "0 new findings" in out.stdout


class TestLegacyWrapper:
    def test_lint_py_contract_preserved(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1 \n")  # trailing whitespace -> W291
        out = subprocess.run(
            [sys.executable, str(SCRIPTS / "lint.py"), str(bad)],
            capture_output=True, text=True, cwd=tmp_path)
        assert out.returncode == 1
        assert "W291" in out.stdout
        assert "lint: 1 files, 1 findings" in out.stdout

    def test_lint_py_clean_exit(self, tmp_path):
        ok = tmp_path / "ok.py"
        ok.write_text("x = 1\n")
        out = subprocess.run(
            [sys.executable, str(SCRIPTS / "lint.py"), str(ok)],
            capture_output=True, text=True, cwd=tmp_path)
        assert out.returncode == 0, out.stdout

    def test_fixture_corpus_quarantined(self):
        # the intentionally-buggy corpus must never leak into a
        # default-path lint/analyze run
        from analysis.legacy_lint import DEFAULT_PATHS, iter_py
        scanned = {str(p) for p in iter_py(DEFAULT_PATHS)}
        assert not any("analysis_fixtures" in p for p in scanned)


class TestSchemaLintJson:
    def test_json_contract(self):
        out = subprocess.run(
            [sys.executable, "-m", "spicedb_kubeapi_proxy_tpu",
             "--lint-schema", "--lint-schema-json"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["version"] == 1
        assert {"errors", "warnings", "strict"} <= set(payload["summary"])
        for f in payload["findings"]:
            assert {"code", "severity", "where", "message"} <= set(f)
