"""SpiceDB-side watch bridge (reference pkg/authz/watch.go).

Watches the tuple store for updates on the prefilter's resource type; each
update triggers a CheckPermission for the watching subject and pushes an
allow/revoke change keyed by NamespacedName into the tracker consumed by
the watch response filterer.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from ..rules.engine import ResolveInput, ResolvedPreFilter
from ..spicedb.endpoints import PermissionsEndpoint
from ..spicedb.types import CheckRequest, ObjectRef, SubjectRef
from .lookups import extract_namespaced_name


@dataclass
class ResultChange:
    allowed: bool
    namespace: str
    name: str


@dataclass
class WatchTracker:
    changes: asyncio.Queue = field(default_factory=asyncio.Queue)


async def run_watch(endpoint: PermissionsEndpoint, tracker: WatchTracker,
                    config: ResolvedPreFilter, input: ResolveInput,
                    watcher=None) -> None:
    """Long-lived store watch -> per-update check -> tracker change
    (reference watch.go:27-111).

    `watcher` should be subscribed by the caller BEFORE scheduling this
    coroutine, so tuple writes racing the watch setup are not lost."""
    if watcher is None:
        watcher = endpoint.watch([config.rel.resource_type])
    try:
        while True:
            # push-based: the store/stream wakes this coroutine directly
            # (WatchQueue.next) — no executor thread, no poll interval
            update = await watcher.next()
            if update is None:
                return  # closed and drained
            for u in update.updates:
                resource_id = u.rel.resource.id
                result = await endpoint.check_permission(CheckRequest(
                    resource=ObjectRef(config.rel.resource_type, resource_id),
                    permission=config.rel.resource_relation,
                    subject=SubjectRef(config.rel.subject_type,
                                       config.rel.subject_id,
                                       config.rel.subject_relation),
                ))
                namespace, name = extract_namespaced_name(
                    config, input, resource_id, u.rel.subject.id)
                await tracker.changes.put(ResultChange(
                    allowed=result.allowed, namespace=namespace, name=name))
    except asyncio.CancelledError:
        raise
    finally:
        watcher.close()
