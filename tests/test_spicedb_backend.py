"""Schema DSL, tuple store, and host evaluator (oracle) tests."""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    EmbeddedEndpoint,
    EndpointConfigError,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    AlreadyExistsError,
    CheckRequest,
    MaxDepthExceededError,
    ObjectRef,
    Precondition,
    PreconditionFailedError,
    PreconditionOp,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SchemaError,
    SubjectFilter,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

BOOTSTRAP_SCHEMA = """
use expiration

definition cluster {}
definition user {}
definition namespace {
  relation cluster: cluster
  relation creator: user
  relation viewer: user

  permission admin = creator
  permission edit = creator
  permission view = viewer + creator
  permission no_one_at_all = nil
}
definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user
  permission edit = creator
  permission view = viewer + creator
}
"""


def rel(s):
    return parse_relationship(s)


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, rel(r)) for r in rels]


class TestSchemaParser:
    def test_bootstrap_schema_parses(self):
        s = sch.parse_schema(BOOTSTRAP_SCHEMA)
        assert set(s.definitions) == {"cluster", "user", "namespace", "pod"}
        assert s.uses == ("expiration",)
        ns = s.definitions["namespace"]
        assert set(ns.relations) == {"cluster", "creator", "viewer"}
        assert set(ns.permissions) == {"admin", "edit", "view", "no_one_at_all"}
        assert isinstance(ns.permissions["view"], sch.Union)
        assert isinstance(ns.permissions["no_one_at_all"], sch.Nil)

    def test_subject_relation_and_wildcard(self):
        s = sch.parse_schema("""
definition user {}
definition group {
  relation member: user | group#member | user:*
}
""")
        refs = s.definitions["group"].relations["member"]
        assert refs[0] == sch.TypeRef("user")
        assert refs[1] == sch.TypeRef("group", relation="member")
        assert refs[2] == sch.TypeRef("user", wildcard=True)

    def test_with_expiration_trait(self):
        s = sch.parse_schema("""
definition activity {}
definition workflow {
  relation idempotency_key: activity with expiration
}
""")
        ref = s.definitions["workflow"].relations["idempotency_key"][0]
        assert ref.traits == ("expiration",)

    def test_arrow_and_operators(self):
        s = sch.parse_schema("""
definition user {}
definition org { relation admin: user }
definition doc {
  relation org: org
  relation writer: user
  relation banned: user
  permission edit = (writer + org->admin) & writer - banned
}
""")
        e = s.definitions["doc"].permissions["edit"]
        assert isinstance(e, sch.Intersection)

    def test_comments(self):
        s = sch.parse_schema("""
// line comment
definition user {} /* block
comment */ definition t { relation u: user }
""")
        assert set(s.definitions) == {"user", "t"}

    def test_unknown_subject_type_rejected(self):
        with pytest.raises(SchemaError, match="unknown subject type"):
            sch.parse_schema("definition t { relation r: missing }")

    def test_unknown_permission_target_rejected(self):
        with pytest.raises(SchemaError, match="unknown relation"):
            sch.parse_schema("definition t { permission p = nope }")

    def test_duplicate_definition_rejected(self):
        with pytest.raises(SchemaError, match="duplicate"):
            sch.parse_schema("definition t {} definition t {}")

    def test_caveat_skipped(self):
        s = sch.parse_schema("""
caveat only_on_tuesday(day string) { day == "tuesday" }
definition user {}
""")
        assert set(s.definitions) == {"user"}


class TestTupleStore:
    def test_create_touch_delete(self):
        st = TupleStore()
        st.write([RelationshipUpdate(UpdateOp.CREATE, rel("namespace:a#creator@user:u1"))])
        assert st.has_exact(rel("namespace:a#creator@user:u1"))
        with pytest.raises(AlreadyExistsError):
            st.write([RelationshipUpdate(UpdateOp.CREATE, rel("namespace:a#creator@user:u1"))])
        st.write(touch("namespace:a#creator@user:u1"))  # touch is idempotent
        st.write([RelationshipUpdate(UpdateOp.DELETE, rel("namespace:a#creator@user:u1"))])
        assert not st.has_exact(rel("namespace:a#creator@user:u1"))

    def test_atomic_create_failure_leaves_store_unchanged(self):
        st = TupleStore()
        st.write(touch("a:1#r@user:u"))
        with pytest.raises(AlreadyExistsError):
            st.write([
                RelationshipUpdate(UpdateOp.TOUCH, rel("a:2#r@user:u")),
                RelationshipUpdate(UpdateOp.CREATE, rel("a:1#r@user:u")),
            ])
        assert not st.has_exact(rel("a:2#r@user:u"))

    def test_preconditions(self):
        st = TupleStore()
        st.write(touch("namespace:a#creator@user:u1"))
        must = Precondition(PreconditionOp.MUST_MATCH,
                            RelationshipFilter(resource_type="namespace",
                                               resource_id="a"))
        must_not = Precondition(PreconditionOp.MUST_NOT_MATCH,
                                RelationshipFilter(resource_type="namespace",
                                                   resource_id="b"))
        st.write(touch("namespace:a#viewer@user:u2"), [must, must_not])
        bad = Precondition(PreconditionOp.MUST_NOT_MATCH,
                           RelationshipFilter(resource_type="namespace",
                                              resource_id="a"))
        with pytest.raises(PreconditionFailedError):
            st.write(touch("namespace:c#creator@user:u1"), [bad])
        assert not st.has_exact(rel("namespace:c#creator@user:u1"))

    def test_filters(self):
        st = TupleStore()
        st.write(touch(
            "pod:ns/p1#creator@user:u1",
            "pod:ns/p2#creator@user:u2",
            "pod:ns/p1#viewer@user:u2",
            "namespace:ns#creator@user:u1",
        ))
        assert len(st.read(RelationshipFilter(resource_type="pod"))) == 3
        assert len(st.read(RelationshipFilter(resource_type="pod",
                                              relation="creator"))) == 2
        assert len(st.read(RelationshipFilter(
            subject=SubjectFilter(type="user", id="u2")))) == 2
        assert len(st.read(RelationshipFilter(resource_id="ns/p1"))) == 2

    def test_delete_by_filter(self):
        st = TupleStore()
        st.write(touch("pod:ns/p1#creator@user:u1", "pod:ns/p2#creator@user:u1",
                       "namespace:ns#creator@user:u1"))
        _, deleted = st.delete_by_filter(RelationshipFilter(resource_type="pod"))
        assert len(deleted) == 2
        assert len(st.read()) == 1

    def test_expiration(self):
        now = [1000.0]
        st = TupleStore(clock=lambda: now[0])
        r = Relationship(ObjectRef("workflow", "w1"), "idempotency_key",
                         SubjectRef("activity", "a1"), expires_at=1010.0)
        st.write([RelationshipUpdate(UpdateOp.TOUCH, r)])
        assert st.has_exact(r)
        now[0] = 1011.0
        assert not st.has_exact(r)
        assert st.read() == []
        # expired entry can be re-created
        st.write([RelationshipUpdate(UpdateOp.CREATE, r)])

    def test_revision_monotonic(self):
        st = TupleStore()
        r0 = st.revision
        r1 = st.write(touch("a:1#r@user:u"))
        r2 = st.write(touch("a:2#r@user:u"))
        assert r0 < r1 < r2

    def test_watch(self):
        st = TupleStore()
        w = st.subscribe(object_types=["pod"])
        st.write(touch("namespace:ns#creator@user:u1"))  # filtered out
        st.write(touch("pod:ns/p1#creator@user:u1"))
        ev = w.poll(timeout=1)
        assert ev is not None
        assert ev.updates[0].rel.resource.type == "pod"
        assert ev.updates[0].op == UpdateOp.TOUCH
        st.write([RelationshipUpdate(UpdateOp.DELETE, rel("pod:ns/p1#creator@user:u1"))])
        ev2 = w.poll(timeout=1)
        assert ev2.updates[0].op == UpdateOp.DELETE
        w.close()
        assert w.poll(timeout=0.01) is None

    def test_delete_nonexistent_emits_no_event(self):
        st = TupleStore()
        w = st.subscribe()
        st.write([RelationshipUpdate(UpdateOp.DELETE, rel("a:1#r@user:u"))])
        assert w.poll(timeout=0.05) is None


def make_eval(schema_text, rels):
    schema = sch.parse_schema(schema_text)
    store = TupleStore()
    if rels:
        store.write(touch(*rels))
    return Evaluator(schema, store), store


GROUPS_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition team {
  relation member: user | group#member
}
definition namespace {
  relation viewer: user | group#member | team#member
  permission view = viewer
}
"""


class TestEvaluator:
    def test_direct_relation(self):
        ev, _ = make_eval(BOOTSTRAP_SCHEMA, ["namespace:a#creator@user:u1"])
        assert ev.check(ObjectRef("namespace", "a"), "creator", SubjectRef("user", "u1"))
        assert not ev.check(ObjectRef("namespace", "a"), "creator", SubjectRef("user", "u2"))

    def test_union_permission(self):
        ev, _ = make_eval(BOOTSTRAP_SCHEMA, [
            "namespace:a#creator@user:owner",
            "namespace:a#viewer@user:watcher",
        ])
        for u in ("owner", "watcher"):
            assert ev.check(ObjectRef("namespace", "a"), "view", SubjectRef("user", u))
        assert not ev.check(ObjectRef("namespace", "a"), "view", SubjectRef("user", "nobody"))
        assert ev.check(ObjectRef("namespace", "a"), "admin", SubjectRef("user", "owner"))
        assert not ev.check(ObjectRef("namespace", "a"), "admin", SubjectRef("user", "watcher"))

    def test_nil_permission(self):
        ev, _ = make_eval(BOOTSTRAP_SCHEMA, ["namespace:a#creator@user:u"])
        assert not ev.check(ObjectRef("namespace", "a"), "no_one_at_all", SubjectRef("user", "u"))

    def test_nested_groups_depth4(self):
        ev, _ = make_eval(GROUPS_SCHEMA, [
            "group:inner#member@user:alice",
            "group:outer#member@group:inner#member",
            "team:t#member@group:outer#member",
            "namespace:ns#viewer@team:t#member",
        ])
        assert ev.check(ObjectRef("namespace", "ns"), "view", SubjectRef("user", "alice"))
        assert not ev.check(ObjectRef("namespace", "ns"), "view", SubjectRef("user", "bob"))

    def test_userset_exact_match(self):
        ev, _ = make_eval(GROUPS_SCHEMA, [
            "namespace:ns#viewer@group:g#member",
        ])
        assert ev.check(ObjectRef("namespace", "ns"), "view",
                        SubjectRef("group", "g", "member"))

    def test_wildcard(self):
        schema = """
definition user {}
definition doc {
  relation viewer: user | user:*
  permission view = viewer
}
"""
        ev, _ = make_eval(schema, ["doc:d#viewer@user:*"])
        assert ev.check(ObjectRef("doc", "d"), "view", SubjectRef("user", "anyone"))
        # wildcard does not satisfy userset subjects
        assert not ev.check(ObjectRef("doc", "d"), "view",
                            SubjectRef("group", "g", "member"))

    def test_intersection_exclusion(self):
        schema = """
definition user {}
definition doc {
  relation assigned: user
  relation approved: user
  relation banned: user
  permission edit = assigned & approved - banned
}
"""
        ev, _ = make_eval(schema, [
            "doc:d#assigned@user:a", "doc:d#approved@user:a",
            "doc:d#assigned@user:b",
            "doc:d#assigned@user:c", "doc:d#approved@user:c", "doc:d#banned@user:c",
        ])
        assert ev.check(ObjectRef("doc", "d"), "edit", SubjectRef("user", "a"))
        assert not ev.check(ObjectRef("doc", "d"), "edit", SubjectRef("user", "b"))
        assert not ev.check(ObjectRef("doc", "d"), "edit", SubjectRef("user", "c"))

    def test_arrow(self):
        schema = """
definition user {}
definition namespace {
  relation admin: user
  permission admin_perm = admin
}
definition pod {
  relation namespace: namespace
  relation creator: user
  permission edit = creator + namespace->admin_perm
}
"""
        ev, _ = make_eval(schema, [
            "namespace:ns#admin@user:boss",
            "pod:ns/p#namespace@namespace:ns",
            "pod:ns/p#creator@user:dev",
        ])
        assert ev.check(ObjectRef("pod", "ns/p"), "edit", SubjectRef("user", "dev"))
        assert ev.check(ObjectRef("pod", "ns/p"), "edit", SubjectRef("user", "boss"))
        assert not ev.check(ObjectRef("pod", "ns/p"), "edit", SubjectRef("user", "rando"))

    def test_cyclic_groups_terminate(self):
        ev, _ = make_eval(GROUPS_SCHEMA, [
            "group:a#member@group:b#member",
            "group:b#member@group:a#member",
            "group:b#member@user:alice",
            "namespace:ns#viewer@group:a#member",
        ])
        assert ev.check(ObjectRef("namespace", "ns"), "view", SubjectRef("user", "alice"))
        assert not ev.check(ObjectRef("namespace", "ns"), "view", SubjectRef("user", "bob"))

    def test_cycle_memo_not_poisoned(self):
        # checking `a` first must not cache a stale False for `b`
        ev, _ = make_eval(GROUPS_SCHEMA, [
            "group:a#member@group:b#member",
            "group:b#member@group:a#member",
            "group:a#member@user:alice",
        ])
        assert ev.check(ObjectRef("group", "a"), "member", SubjectRef("user", "alice"))
        assert ev.check(ObjectRef("group", "b"), "member", SubjectRef("user", "alice"))

    def test_max_depth(self):
        rels = [f"group:g{i}#member@group:g{i+1}#member" for i in range(60)]
        rels.append("group:g60#member@user:deep")
        ev, _ = make_eval(GROUPS_SCHEMA, rels)
        with pytest.raises(MaxDepthExceededError):
            ev.check(ObjectRef("group", "g0"), "member", SubjectRef("user", "deep"))

    def test_unknown_relation_errors(self):
        ev, _ = make_eval(BOOTSTRAP_SCHEMA, [])
        with pytest.raises(SchemaError):
            ev.check(ObjectRef("namespace", "a"), "nope", SubjectRef("user", "u"))

    def test_lookup_resources(self):
        ev, _ = make_eval(BOOTSTRAP_SCHEMA, [
            "namespace:a#creator@user:u1",
            "namespace:b#viewer@user:u1",
            "namespace:c#creator@user:u2",
        ])
        assert ev.lookup_resources("namespace", "view", SubjectRef("user", "u1")) == ["a", "b"]
        assert ev.lookup_resources("namespace", "view", SubjectRef("user", "u2")) == ["c"]
        assert ev.lookup_resources("namespace", "view", SubjectRef("user", "u3")) == []

    def test_lookup_resources_nested(self):
        ev, _ = make_eval(GROUPS_SCHEMA, [
            "group:eng#member@user:alice",
            "namespace:ns1#viewer@group:eng#member",
            "namespace:ns2#viewer@user:alice",
            "namespace:ns3#viewer@user:bob",
        ])
        assert ev.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice")) == ["ns1", "ns2"]

    def test_lookup_subjects(self):
        ev, _ = make_eval(BOOTSTRAP_SCHEMA, [
            "namespace:a#creator@user:u1",
            "namespace:a#viewer@user:u2",
            "namespace:b#viewer@user:u3",
        ])
        assert ev.lookup_subjects(ObjectRef("namespace", "a"), "view", "user") == ["u1", "u2"]


class TestEmbeddedEndpoint:
    def test_bootstrap_and_verbs(self):
        bs = Bootstrap(schema_text=BOOTSTRAP_SCHEMA,
                       relationships_text="namespace:spicedb-kubeapi-proxy#viewer@user:rakis\n")
        ep = EmbeddedEndpoint.from_bootstrap(bs)

        async def run():
            res = await ep.check_permission(CheckRequest(
                ObjectRef("namespace", "spicedb-kubeapi-proxy"), "view",
                SubjectRef("user", "rakis")))
            assert res.allowed
            bulk = await ep.check_bulk_permissions([
                CheckRequest(ObjectRef("namespace", "spicedb-kubeapi-proxy"),
                             "view", SubjectRef("user", "rakis")),
                CheckRequest(ObjectRef("namespace", "spicedb-kubeapi-proxy"),
                             "view", SubjectRef("user", "other")),
            ])
            assert [b.allowed for b in bulk] == [True, False]
            ids = await ep.lookup_resources("namespace", "view",
                                            SubjectRef("user", "rakis"))
            assert ids == ["spicedb-kubeapi-proxy"]
        asyncio.run(run())

    def test_create_endpoint_dispatch(self):
        ep = create_endpoint("embedded://")
        assert isinstance(ep, EmbeddedEndpoint)
        from spicedb_kubeapi_proxy_tpu.spicedb.grpc_remote import RemoteEndpoint
        remote = create_endpoint("grpc://localhost:50051")
        assert isinstance(remote, RemoteEndpoint)
        # scheme-less host:port = remote over TLS, the reference's default
        # endpoint shape (options.go:107 `localhost:50051`)
        bare = create_endpoint("localhost:50051")
        assert isinstance(bare, RemoteEndpoint)
        assert bare.target == "localhost:50051" and not bare.insecure
        with pytest.raises(EndpointConfigError, match="unsupported"):
            create_endpoint("carrier-pigeon://x")

    def test_default_bootstrap_schema(self):
        ep = create_endpoint("embedded://")
        assert "workflow" in ep.schema.definitions
        assert "lock" in ep.schema.definitions


class TestRelationshipParsing:
    def test_round_trip(self):
        r = rel("pod:ns/p1#creator@user:alice")
        assert r.rel_string() == "pod:ns/p1#creator@user:alice"

    def test_subject_relation(self):
        r = rel("namespace:ns#viewer@group:eng#member")
        assert r.subject.relation == "member"

    def test_ellipsis_normalized(self):
        r = rel("namespace:ns#viewer@user:u#...")
        assert r.subject.relation == ""

    def test_expiration_suffix(self):
        r = rel("workflow:w#idempotency_key@activity:a[expiration:12345.5]")
        assert r.expires_at == 12345.5
        assert r.rel_string().endswith("[expiration:12345.5]")

    def test_template_rejected(self):
        with pytest.raises(ValueError):
            rel("pod:{{name}}#view@user:u")
