"""Rule-engine core: findings, parsed sources, noqa suppressions,
baseline matching.

A rule is a callable `rule(sources) -> list[Finding]` over the full
parsed file set (rules that need a cross-file graph — lock order,
jit reach — get it for free; per-file rules just loop).  The driver
(scripts/analyze.py) applies suppression and baseline filtering
uniformly AFTER the rules run, so every rule family (A/M/SL) shares
one suppression story.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

# `# noqa: A001(reason)` — reason required; `# noqa: A001, M003(x)` is
# two directives.  Bare flake8-style `# noqa` (no code) is ignored: it
# belongs to external tools and must not silently swallow A-rules.
_NOQA_RE = re.compile(r"#\s*noqa:\s*([A-Z]{1,2}\d{3})\s*(?:\(([^)]*)\))?")

# directories never analyzed: bytecode, the intentionally-buggy rule
# fixture corpus (tests/analysis_fixtures), VCS internals
SKIP_DIRS = frozenset(("__pycache__", "analysis_fixtures", ".git"))


def parse_noqa_lines(lines) -> dict:
    """{lineno: [(code, reason-or-None)]} for every `# noqa: AXXX(...)`
    directive — the ONE parser behind both SourceFile and the driver's
    lookaside for files outside the A-rule source set."""
    out: dict = {}
    for i, line in enumerate(lines, 1):
        if "noqa" not in line:
            continue
        for mm in _NOQA_RE.finditer(line):
            out.setdefault(i, []).append((mm.group(1), mm.group(2)))
    return out


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    symbol: str = ""   # enclosing function qualname ("" = module scope)

    def key(self) -> tuple:
        """Baseline identity: line numbers drift, (rule, path, symbol,
        message) is stable until the code itself changes."""
        return (self.rule, self.path, self.symbol, self.message)

    def text(self) -> str:
        where = f" [in {self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{where}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


class SourceFile:
    """One parsed file: AST + parent links + qualname index + noqa map."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=rel)
        self.parents: dict = {}
        self.qualnames: dict = {}   # id(def-node) -> qualname
        self._index()
        # line -> [(code, reason-or-None)]
        self.noqa = parse_noqa_lines(self.lines)

    def _index(self) -> None:
        def walk(node, qual):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                q = qual
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    q = f"{qual}.{child.name}" if qual else child.name
                    if not isinstance(child, ast.ClassDef):
                        self.qualnames[id(child)] = q
                walk(child, q)
        walk(self.tree, "")

    def symbol_at(self, node: ast.AST) -> str:
        """Qualname of the innermost function enclosing `node`."""
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self.qualnames.get(id(cur), cur.name)
            cur = self.parents.get(cur)
        return ""

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        if isinstance(node_or_line, int):
            line, symbol = node_or_line, ""
        else:
            line = getattr(node_or_line, "lineno", 0)
            symbol = self.symbol_at(node_or_line)
        return Finding(rule, self.rel, line, message, symbol)


def attr_chain(node) -> tuple:
    """('self', 'store', 'lock') for `self.store.lock`; () when the
    expression is not a plain name/attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def iter_py(paths) -> list:
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not SKIP_DIRS.intersection(f.parts):
                    out.append(f)
        elif p.suffix == ".py":
            out.append(p)
    return out


def load_sources(paths, root: Path) -> tuple:
    """-> (sources, parse_error_findings).  Syntax errors become E999
    findings instead of crashing the driver (same contract as lint.py)."""
    sources, errors = [], []
    for f in iter_py(paths):
        try:
            rel = str(f.resolve().relative_to(root.resolve()))
        except ValueError:
            rel = str(f)
        try:
            sources.append(SourceFile(f, rel))
        except SyntaxError as e:
            errors.append(Finding("E999", rel, e.lineno or 0,
                                  f"syntax error: {e.msg}"))
    return sources, errors


@dataclass
class Suppression:
    finding: Finding
    reason: str


def apply_noqa(findings, sources) -> tuple:
    """Split findings into (kept, suppressed) per the per-line noqa
    directives; a directive with no reason emits an A000 finding in
    place of the suppression (rationale is the whole point: six months
    later the suppression must still explain itself)."""
    by_rel = {s.rel: s for s in sources}
    kept, suppressed = [], []
    for f in findings:
        src = by_rel.get(f.path)
        directives = src.noqa.get(f.line, []) if src else []
        matched = None
        for code, reason in directives:
            if code == f.rule:
                matched = (code, reason)
                break
        if matched is None:
            kept.append(f)
        elif not (matched[1] or "").strip():
            kept.append(Finding(
                "A000", f.path, f.line,
                f"noqa for {f.rule} has no reason — write "
                f"`# noqa: {f.rule}(why this is safe)`", f.symbol))
        else:
            suppressed.append(Suppression(f, matched[1].strip()))
    return kept, suppressed


class Baseline:
    """Checked-in grandfathered findings (scripts/analysis/baseline.json).

    Matching consumes multiplicity: two identical findings need two
    baseline entries, so fixing one instance shrinks the file."""

    def __init__(self, path: Path):
        self.path = path
        self.entries: list = []
        if path.exists():
            data = json.loads(path.read_text())
            self.entries = [
                (e["rule"], e["path"], e.get("symbol", ""), e["message"])
                for e in data.get("findings", ())]

    def filter(self, findings) -> tuple:
        """-> (new, baselined, stale_keys)."""
        budget: dict = {}
        for k in self.entries:
            budget[k] = budget.get(k, 0) + 1
        new, baselined = [], []
        for f in findings:
            k = f.key()
            if budget.get(k, 0) > 0:
                budget[k] -= 1
                baselined.append(f)
            else:
                new.append(f)
        stale = [k for k, n in budget.items() for _ in range(n)]
        return new, baselined, stale

    @staticmethod
    def write(path: Path, findings, note: str = "") -> None:
        data = {
            "comment": note or (
                "Grandfathered findings (docs/static-analysis.md). "
                "Regenerate with scripts/analyze.py --update-baseline; "
                "fix entries rather than adding new ones."),
            "findings": [
                {"rule": f.rule, "path": f.path, "symbol": f.symbol,
                 "message": f.message}
                for f in sorted(findings, key=Finding.key)],
        }
        path.write_text(json.dumps(data, indent=1) + "\n")


@dataclass
class RuleResult:
    findings: list = field(default_factory=list)
