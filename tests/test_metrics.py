"""Metrics subsystem tests: registry/exposition primitives, the
endpoint-boundary instrumentation wrapper (SURVEY.md §5), and the proxy's
/metrics route."""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.instrumented import InstrumentedEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import metrics as m

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""


# -- primitives --------------------------------------------------------------

def test_counter_labels_and_render():
    c = m.Counter("reqs_total", "requests", labels=("verb",))
    c.inc(verb="get")
    c.inc(verb="get")
    c.inc(verb="list")
    assert c.value(verb="get") == 2
    lines = c.render()
    assert 'reqs_total{verb="get"} 2' in lines
    assert 'reqs_total{verb="list"} 1' in lines


def test_histogram_buckets_sum_count():
    h = m.Histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    lines = h.render()
    assert 'lat_bucket{le="0.1"} 1' in lines
    assert 'lat_bucket{le="1"} 2' in lines
    assert 'lat_bucket{le="+Inf"} 3' in lines
    assert any(line.startswith("lat_sum") and "5.55" in line
               for line in lines)
    assert "lat_count 3" in lines


def test_gauge_callback_sampled_at_render():
    state = {"v": 1.0}
    g = m.Gauge("g", callback=lambda: state["v"])
    assert "g 1" in g.render()
    state["v"] = 7.5
    assert "g 7.5" in g.render()


def test_registry_render_and_dedup():
    reg = m.Registry()
    c1 = reg.counter("x_total", "help text")
    c2 = reg.counter("x_total")
    assert c1 is c2
    c1.inc()
    text = reg.render()
    assert "# HELP x_total help text" in text
    assert "# TYPE x_total counter" in text
    assert "\nx_total 1\n" in text


def test_label_escaping():
    c = m.Counter("c_total", labels=("path",))
    c.inc(path='we"ird\npath')
    assert c.render() == ['c_total{path="we\\"ird\\npath"} 1']


# -- endpoint instrumentation ------------------------------------------------

def make_instrumented():
    reg = m.Registry()
    ep = EmbeddedEndpoint(sch.parse_schema(SCHEMA))
    inst = InstrumentedEndpoint(ep, registry=reg, backend_label="embedded")
    return inst, reg


def test_instrumented_endpoint_records_latency_and_batch_size():
    inst, reg = make_instrumented()

    async def run():
        await inst.write_relationships([RelationshipUpdate(
            op=UpdateOp.TOUCH,
            rel=parse_relationship("doc:d1#viewer@user:alice"))])
        reqs = [CheckRequest(resource=ObjectRef("doc", "d1"),
                             permission="view",
                             subject=SubjectRef("user", u))
                for u in ("alice", "bob", "carol")]
        results = await inst.check_bulk_permissions(reqs)
        ids = await inst.lookup_resources_batch(
            "doc", "view", [SubjectRef("user", "alice")])
        return results, ids

    results, ids = asyncio.run(run())
    assert [r.allowed for r in results] == [True, False, False]
    assert ids == [["d1"]]
    assert inst.latency.count(verb="check_bulk", backend="embedded") == 1
    assert inst.batch_size.count(verb="check_bulk", backend="embedded") == 1
    text = reg.render()
    assert 'authz_endpoint_batch_size_bucket{verb="check_bulk"' in text
    # the 3-check bulk lands in the le="4" bucket
    assert ('authz_endpoint_batch_size_bucket{verb="check_bulk",'
            'backend="embedded",le="4"} 1') in text


def test_instrumented_endpoint_counts_errors():
    inst, _ = make_instrumented()

    async def bad():
        await inst.lookup_resources("nosuchtype", "view",
                                    SubjectRef("user", "alice"))

    with pytest.raises(Exception):
        asyncio.run(bad())
    assert inst.errors.value(verb="lookup_resources",
                             backend="embedded") == 1


def test_instrumented_passthrough_store_and_watch():
    inst, _ = make_instrumented()
    assert inst.store is inst.inner.store
    w = inst.watch()
    assert w is not None
    w.close()


def test_jax_stats_gauges():
    pytest.importorskip("jax")
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint

    reg = m.Registry()
    ep = JaxEndpoint(sch.parse_schema(SCHEMA))
    inst = InstrumentedEndpoint(ep, registry=reg, backend_label="jax")

    async def run():
        await inst.write_relationships([RelationshipUpdate(
            op=UpdateOp.TOUCH,
            rel=parse_relationship("doc:d1#viewer@user:alice"))])
        return await inst.check_permission(CheckRequest(
            resource=ObjectRef("doc", "d1"), permission="view",
            subject=SubjectRef("user", "alice")))

    res = asyncio.run(run())
    assert res.allowed
    text = reg.render()
    assert "authz_backend_rebuilds_total 1" in text
    assert "authz_backend_kernel_calls_total 1" in text


# -- proxy /metrics route ----------------------------------------------------

def test_proxy_metrics_route():
    from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
        Response, Transport)
    from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap

    class Upstream(Transport):
        async def round_trip(self, req):
            return Response(status=200, body=b"{}")

    rules = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-ns}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
"""
    bootstrap = Bootstrap(schema_text="""
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
""", relationships_text="namespace:ns1#viewer@user:alice")

    server = ProxyServer(Options(
        rules_yaml=rules, bootstrap=bootstrap,
        upstream_transport=Upstream()))
    client = server.get_embedded_client(user="alice")

    anon = server.get_embedded_client()  # no user header

    async def run():
        ok = await client.get("/api/v1/namespaces/ns1")
        metrics = await client.get("/metrics")
        denied = await anon.get("/metrics")
        return ok, metrics, denied

    ok, metrics, denied = asyncio.run(run())
    assert ok.status == 200
    text = metrics.body.decode()
    assert metrics.status == 200
    assert "authz_endpoint_latency_seconds" in text
    assert 'proxy_http_requests_total{verb="get",code="200"}' in text
    # /metrics requires authentication (kube-apiserver semantics)
    assert denied.status == 401


# -- exposition edge cases ---------------------------------------------------

def _unescape_label(v: str) -> str:
    """Reverse of metrics._escape, for round-trip assertions."""
    out = []
    i = 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == '"':
                out.append('"')
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(v[i])
        i += 1
    return "".join(out)


def test_label_value_escaping_round_trips():
    c = m.Counter("esc_total", labels=("path",))
    tricky = 'a\\b"c\nd'
    c.inc(path=tricky)
    (line,) = c.render()
    # the exposition line itself must stay single-line and parseable
    assert "\n" not in line
    assert line.endswith(" 1")
    start = line.index('path="') + len('path="')
    end = line.rindex('"')
    assert _unescape_label(line[start:end]) == tricky


def test_histogram_inf_bucket_and_count_stay_consistent():
    h = m.Histogram("edge_h", buckets=(0.1, 1.0))
    # boundary values are le-inclusive; 50.0 lands only in +Inf
    for v in (0.0, 0.1, 1.0, 1.0000001, 50.0):
        h.observe(v)
    lines = h.render()
    bucket_counts = [int(line.rsplit(" ", 1)[1])
                     for line in lines if "_bucket" in line]
    assert bucket_counts == [2, 3, 5]  # cumulative, monotone
    inf = int([line for line in lines
               if 'le="+Inf"' in line][0].rsplit(" ", 1)[1])
    count = int([line for line in lines
                 if line.startswith("edge_h_count")][0].rsplit(" ", 1)[1])
    assert inf == count == h.count() == 5


def test_gauge_callback_raising_at_scrape_keeps_last_value():
    state = {"fail": False}

    def sampler():
        if state["fail"]:
            raise RuntimeError("sampler broke at scrape time")
        return 2.0

    g = m.Gauge("g_cb", callback=sampler)
    assert g.render() == ["g_cb 2"]
    state["fail"] = True
    # a raising callback must never break the whole /metrics scrape;
    # the last good value is served
    assert g.render() == ["g_cb 2"]


def test_gauge_callback_raising_before_first_sample_renders_default():
    def sampler():
        raise RuntimeError("always broken")

    g = m.Gauge("g_cb_never", callback=sampler)
    assert g.render() == ["g_cb_never 0"]


def test_concurrent_observe_from_threads_is_consistent():
    import threading

    h = m.Histogram("conc_h", buckets=(0.5,))

    def work():
        for i in range(1000):
            h.observe(0.25 if i % 2 else 0.75)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count() == 8000
    lines = h.render()
    first = int([line for line in lines
                 if 'le="0.5"' in line][0].rsplit(" ", 1)[1])
    inf = int([line for line in lines
               if 'le="+Inf"' in line][0].rsplit(" ", 1)[1])
    total = int([line for line in lines
                 if line.startswith("conc_h_count")][0].rsplit(" ", 1)[1])
    assert first == 4000
    assert inf == total == 8000
    s = float([line for line in lines
               if line.startswith("conc_h_sum")][0].rsplit(" ", 1)[1])
    assert abs(s - (4000 * 0.25 + 4000 * 0.75)) < 1e-6


def test_label_churn_under_concurrent_render_no_torn_lines():
    """Histogram/gauge label churn from multiple threads while render()
    runs: every rendered line must be well-formed (never torn), every
    rendered histogram labelset must be internally consistent
    (`_bucket{le="+Inf"}` == `_count`), and the final exposition must
    carry exactly the observations made."""
    import re
    import threading

    reg = m.Registry()
    h = reg.histogram("churn_h", "h", labels=("verb",), buckets=(0.5, 1.0))
    g = reg.gauge("churn_g", "g", labels=("verb",))
    c = reg.counter("churn_c", "c", labels=("verb",))
    n_threads, n_iters = 8, 500
    stop = threading.Event()
    renders: list = []
    errors: list = []

    line_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+="[^"]*"'
        r'(,[a-zA-Z0-9_]+="[^"]*")*\})? -?[0-9+.eInf]+$')

    def writer(tid):
        try:
            for i in range(n_iters):
                verb = f"verb{tid}_{i % 7}"  # churning label values
                h.observe(0.25 if i % 2 else 0.75, verb=verb)
                g.set(float(i), verb=verb)
                c.inc(verb=verb)
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append(e)

    def reader():
        while not stop.is_set():
            renders.append(reg.render())

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert not errors
    renders.append(reg.render())  # final, quiescent exposition

    for text in renders:
        counts: dict = {}
        infs: dict = {}
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            assert line_re.match(line), f"torn exposition line: {line!r}"
            name, _, value = line.rpartition(" ")
            if name.startswith("churn_h_count"):
                counts[name] = int(value)
            elif name.startswith("churn_h_bucket") and 'le="+Inf"' in name:
                infs[name.replace(',le="+Inf"', "").replace(
                    "churn_h_bucket", "churn_h_count")] = int(value)
        # +Inf cumulative == _count for every labelset in every render
        # (each metric renders under its own lock — no torn labelsets)
        assert infs == counts

    # final totals carry exactly the observations made
    final = renders[-1]
    total = sum(int(line.rsplit(" ", 1)[1])
                for line in final.splitlines()
                if line.startswith("churn_h_count"))
    assert total == n_threads * n_iters
    c_total = sum(int(float(line.rsplit(" ", 1)[1]))
                  for line in final.splitlines()
                  if line.startswith("churn_c{"))
    assert c_total == n_threads * n_iters
    s_total = sum(float(line.rsplit(" ", 1)[1])
                  for line in final.splitlines()
                  if line.startswith("churn_h_sum"))
    want = n_threads * (n_iters // 2) * (0.25 + 0.75)
    assert abs(s_total - want) < 1e-6


def test_counter_snapshot_and_histogram_raw_consistent_under_threads():
    """The window-delta reader APIs (Counter.snapshot, Histogram.raw)
    must return internally consistent copies while writers run: in every
    raw() result, sum(bucket counts) == total per labelset."""
    import threading

    h = m.Histogram("raw_h", labels=("verb",), buckets=(0.5,))
    c = m.Counter("raw_c", labels=("verb",))
    stop = threading.Event()
    errors: list = []

    def writer():
        i = 0
        while not stop.is_set():
            h.observe(0.25 if i % 2 else 0.75, verb=f"v{i % 5}")
            c.inc(verb=f"v{i % 5}")
            i += 1

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            for key, (counts, _s, total) in h.raw().items():
                if sum(counts) != total:
                    errors.append((key, counts, total))
            snap = c.snapshot()
            assert all(v >= 0 for v in snap.values())
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, f"torn raw() snapshots: {errors[:3]}"
