"""Durable relationship store (spicedb/persist): WAL framing, segment
rolling, torn-tail repair, checkpoint round trips, recovery parity,
revision continuity, bootstrap-once semantics, and CLI wiring."""

import glob
import os
import tempfile

import pytest

from spicedb_kubeapi_proxy_tpu.cli import (
    DEFAULT_WORKFLOW_DATABASE_PATH,
    build_parser,
    resolve_workflow_db,
    validate,
)
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    EmbeddedEndpoint,
    EndpointConfigError,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.persist import (
    PersistenceManager,
    PersistenceUnavailableError,
    SegmentedWal,
    WalCorruptionError,
)
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CaveatRef,
    ObjectRef,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import failpoints

BOOT = """\
doc:d1#viewer@user:u1
doc:d2#viewer@user:u2
doc:d3#viewer@user:u3[expiration:99999999999]
"""

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""


@pytest.fixture(autouse=True)
def reset_failpoints():
    failpoints.disable_all()
    yield
    failpoints.disable_all()


@pytest.fixture()
def tmpdir():
    with tempfile.TemporaryDirectory() as d:
        yield d


def touch(s):
    return RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(s))


def delete(s):
    return RelationshipUpdate(UpdateOp.DELETE, parse_relationship(s))


def rels_of(store):
    return sorted(r.rel_string() for r in store.read(None))


class TestWal:
    def test_append_replay_round_trip(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="never")
        payloads = [b'{"k":"c","r":%d}' % i for i in range(1, 8)]
        for p in payloads:
            wal.append(p)
        wal.close()
        got = [rec for rec in SegmentedWal(tmpdir).replay()]
        assert [rec["r"] for rec in got] == list(range(1, 8))

    def test_segment_rolling_and_cut(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="never", segment_bytes=64)
        for i in range(1, 11):
            wal.append(b'{"k":"c","r":%d}' % i)
        assert wal.segment_count() > 1
        watermark = wal.cut()
        wal.append(b'{"k":"c","r":11}')
        # records after the cut land in segments above the watermark
        assert max(wal.segment_seqs()) > watermark
        got = [rec["r"] for rec in SegmentedWal(tmpdir).replay()]
        assert got == list(range(1, 12))

    def test_torn_tail_truncated(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="never")
        for i in range(1, 6):
            wal.append(b'{"k":"c","r":%d}' % i)
        wal.close()
        seg = sorted(glob.glob(os.path.join(tmpdir, "seg-*.wal")))[-1]
        with open(seg, "rb+") as f:
            f.truncate(os.path.getsize(seg) - 5)
        reader = SegmentedWal(tmpdir)
        got = [rec["r"] for rec in reader.replay()]
        assert got == [1, 2, 3, 4]
        assert reader.torn_records == 1
        # the repaired file replays cleanly a second time
        assert [r["r"] for r in SegmentedWal(tmpdir).replay()] == got

    def test_mid_segment_corruption_raises(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="never")
        for i in range(1, 6):
            wal.append(b'{"k":"c","r":%d}' % i)
        wal.close()
        seg = sorted(glob.glob(os.path.join(tmpdir, "seg-*.wal")))[-1]
        with open(seg, "rb+") as f:
            f.seek(20)
            f.write(b"\xff")
        with pytest.raises(WalCorruptionError):
            list(SegmentedWal(tmpdir).replay())

    def test_sealed_segment_corruption_raises_even_at_its_tail(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="never", segment_bytes=32)
        for i in range(1, 6):
            wal.append(b'{"k":"c","r":%d}' % i)
        wal.close()
        segs = sorted(glob.glob(os.path.join(tmpdir, "seg-*.wal")))
        assert len(segs) > 1
        with open(segs[0], "rb+") as f:
            f.truncate(os.path.getsize(segs[0]) - 3)
        with pytest.raises(WalCorruptionError):
            list(SegmentedWal(tmpdir).replay())

    def test_bad_fsync_policy_rejected(self, tmpdir):
        with pytest.raises(ValueError):
            SegmentedWal(tmpdir, fsync="sometimes")

    def test_torn_segment_header_survives_two_restarts(self, tmpdir):
        """A segment whose header write was torn is removed on the first
        recovery; once newer segments exist, the remnant must not read
        as mid-stream corruption on LATER recoveries."""
        wal = SegmentedWal(tmpdir, fsync="never")
        for i in range(1, 4):
            wal.append(b'{"k":"c","r":%d}' % i)
        wal.close()
        # torn creation of the next segment: only 3 bytes of magic land
        segs = sorted(glob.glob(os.path.join(tmpdir, "seg-*.wal")))
        torn = os.path.join(tmpdir, "seg-%08d.wal" % (len(segs) + 1))
        with open(torn, "wb") as f:
            f.write(b"SPW")
        # restart 1: repaired (removed), records intact
        w2 = SegmentedWal(tmpdir)
        assert [r["r"] for r in w2.replay()] == [1, 2, 3]
        assert not os.path.exists(torn)
        w2.append(b'{"k":"c","r":4}')
        w2.close()
        # restart 2: the full stream replays with no corruption error
        assert [r["r"] for r in SegmentedWal(tmpdir).replay()] == [1, 2, 3, 4]

    def test_empty_segment_tolerated_mid_stream(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="never")
        wal.append(b'{"k":"c","r":1}')
        wal.close()
        # zero-byte segment between two real ones (crash before magic)
        open(os.path.join(tmpdir, "seg-00000002.wal"), "wb").close()
        w2 = SegmentedWal(tmpdir)
        w2.append(b'{"k":"c","r":2}')
        w2.close()
        assert [r["r"] for r in SegmentedWal(tmpdir).replay()] == [1, 2]

    def test_idle_fsync_hook(self, tmpdir):
        wal = SegmentedWal(tmpdir, fsync="interval", fsync_interval=3600)
        wal.append(b'{"k":"c","r":1}')  # interval not elapsed: no fsync
        assert wal.fsync_if_dirty() is True
        assert wal.fsync_if_dirty() is False  # nothing new since


class TestRecoveryParity:
    def drive(self, store):
        """A deterministic mixed update stream."""
        store.bulk_load_text(BOOT)
        for i in range(12):
            store.write([touch(f"doc:w{i}#viewer@user:u{i % 3}")])
        store.write([delete("doc:w5#viewer@user:u2"),
                     touch("doc:extra#viewer@user:u1")])
        store.delete_by_filter(RelationshipFilter(resource_id="w7"))
        store.write([])  # effect-free revision bump
        # caveated + expiring tuples ride the object path
        store.write([RelationshipUpdate(UpdateOp.TOUCH, Relationship(
            resource=ObjectRef("doc", "cav"), relation="viewer",
            subject=SubjectRef("user", "u9"),
            caveat=CaveatRef.make("tod", {"x": 1}),
            expires_at=88888888888.0))])

    def test_wal_only_recovery(self, tmpdir):
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        assert not mgr.recovered and store.revision == 0
        mgr.attach(store)
        self.drive(store)
        want, rev = rels_of(store), store.revision
        # crash: abandon without close
        mgr2 = PersistenceManager(tmpdir)
        s2 = mgr2.recover()
        assert mgr2.recovered
        assert s2.revision == rev
        assert rels_of(s2) == want
        # caveat context survives the round trip
        assert any("[caveat:tod:" in r for r in rels_of(s2))

    def test_checkpoint_plus_tail_and_reclaim(self, tmpdir):
        mgr = PersistenceManager(tmpdir, fsync="never", segment_bytes=256)
        store = mgr.recover()
        mgr.attach(store)
        self.drive(store)
        pre_segments = mgr.wal.segment_count()
        manifest = mgr.checkpoint()
        assert manifest["revision"] == store.revision
        assert mgr.wal.segment_count() < pre_segments
        # idempotent: no new revision -> no new checkpoint
        assert mgr.checkpoint() is None
        store.write([touch("doc:tail#viewer@user:u1")])
        want, rev = rels_of(store), store.revision
        mgr2 = PersistenceManager(tmpdir)
        s2 = mgr2.recover()
        info = mgr2.recovery_info
        assert info["checkpoint_revision"] == manifest["revision"]
        assert info["replayed_records"] == 1  # just the tail write
        assert s2.revision == rev
        assert rels_of(s2) == want

    def test_delete_all_and_object_path_bulk_survive(self, tmpdir):
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        store.delete_all()
        store.bulk_load([parse_relationship("doc:obj#viewer@user:u4")])
        want, rev = rels_of(store), store.revision
        s2 = PersistenceManager(tmpdir).recover()
        assert (rels_of(s2), s2.revision) == (want, rev)
        assert rels_of(s2) == ["doc:obj#viewer@user:u4"]

    def test_object_path_checkpoint(self, tmpdir):
        """A store with no columnar base (pure object inserts, incl.
        caveats) checkpoints and recovers identically."""
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.write([touch("doc:a#viewer@user:u1"),
                     touch("doc:b#viewer@user:u2")])
        store.write([RelationshipUpdate(UpdateOp.TOUCH, Relationship(
            resource=ObjectRef("doc", "c"), relation="viewer",
            subject=SubjectRef("user", "u3"),
            caveat=CaveatRef.make("tod")))])
        mgr.checkpoint()
        want, rev = rels_of(store), store.revision
        s2 = PersistenceManager(tmpdir).recover()
        assert (rels_of(s2), s2.revision) == (want, rev)

    def test_revision_continuity_after_recovery(self, tmpdir):
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        self.drive(store)
        rev = store.revision
        mgr2 = PersistenceManager(tmpdir)
        s2 = mgr2.recover()
        mgr2.attach(s2)
        assert s2.write([touch("doc:post#viewer@user:u1")]) == rev + 1

    def test_adopt_recovery_state_guards(self):
        store = TupleStore()
        with pytest.raises(ValueError):
            store.adopt_recovery_state(None, [], 0)  # revision < 1
        store.adopt_recovery_state(
            None, [parse_relationship("doc:a#viewer@user:u1")], 7)
        assert store.revision == 7
        assert rels_of(store) == ["doc:a#viewer@user:u1"]
        with pytest.raises(ValueError):  # only ever onto an empty store
            store.adopt_recovery_state(None, [], 9)

    def test_wal_append_failure_fail_stops_untouched(self, tmpdir):
        """An IO failure mid-append aborts the commit with the store
        UNTOUCHED (journal-before-mutate): the failed write is never
        visible, every later write raises PersistenceUnavailableError,
        and the data dir stays recoverable with no revision gap."""
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.write([touch("doc:a#viewer@user:u1")])
        real_append = mgr.wal.append

        def flaky_append(payload, kind=""):
            raise OSError("disk on fire")
        mgr.wal.append = flaky_append
        with pytest.raises(OSError):
            store.write([touch("doc:b#viewer@user:u1")])
        # the failed write never became visible and consumed no revision
        assert store.revision == 1
        assert rels_of(store) == ["doc:a#viewer@user:u1"]
        mgr.wal.append = real_append  # the fault clears, but...
        with pytest.raises(PersistenceUnavailableError):
            store.write([touch("doc:c#viewer@user:u1")])
        # a checkpoint after the failure persists only committed state
        ck = mgr.checkpoint()
        assert ck is not None and ck["revision"] == 1
        # recovery sees the intact prefix, gap-free
        s2 = PersistenceManager(tmpdir).recover()
        assert s2.revision == 1
        assert rels_of(s2) == ["doc:a#viewer@user:u1"]

    def test_rev1_checkpoint_with_overlay_recovers(self, tmpdir):
        """A checkpoint taken at revision 1 whose state mixes columnar
        and overlay (caveated) tuples must recover at exactly revision
        1 — loading base + overlay as separate revision-bumping steps
        would brick the data dir."""
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load([
            parse_relationship("doc:plain#viewer@user:u1"),
            Relationship(resource=ObjectRef("doc", "cav"),
                         relation="viewer",
                         subject=SubjectRef("user", "u2"),
                         caveat=CaveatRef.make("tod", {"x": 1})),
        ])
        assert store.revision == 1
        mgr.checkpoint()
        mgr.close()
        for _ in range(2):  # recovery must be repeatable
            s2 = PersistenceManager(tmpdir).recover()
            assert s2.revision == 1
            assert rels_of(s2) == rels_of(store)

    def test_sidecar_written_before_record(self, tmpdir):
        """A WAL record referencing a bulk-load sidecar implies the
        sidecar file exists (write-then-reference ordering)."""
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        recs = list(mgr.wal.replay())
        snaps = [r for r in recs if r["k"] == "s"]
        assert snaps
        for r in snaps:
            assert os.path.exists(os.path.join(mgr.wal.dir, r["f"]))


class TestBootstrapOnce:
    def test_restart_does_not_double_apply_bootstrap(self, tmpdir):
        boot = Bootstrap(schema_text=SCHEMA, relationships_text=BOOT)
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        ep = create_endpoint("embedded://", bootstrap=boot, store=store)
        assert store.revision > 0
        store.write([touch("doc:post#viewer@user:u1")])
        rev, want = store.revision, rels_of(store)
        # restart
        mgr2 = PersistenceManager(tmpdir)
        s2 = mgr2.recover()
        mgr2.attach(s2)
        ep2 = create_endpoint("embedded://", bootstrap=boot, store=s2)
        # the bootstrap was NOT re-applied: revision unchanged, state
        # equals recovered (bootstrap + post-bootstrap write)
        assert s2.revision == rev
        assert rels_of(s2) == want
        assert isinstance(ep2, EmbeddedEndpoint) and ep2.store is s2
        del ep

    def test_fresh_store_still_bootstraps(self):
        ep = EmbeddedEndpoint.from_bootstrap(
            Bootstrap(schema_text=SCHEMA, relationships_text=BOOT))
        assert ep.store.count() == 3

    def test_store_kwarg_rejected_for_grpc(self):
        with pytest.raises(EndpointConfigError):
            create_endpoint("grpc://localhost:50051", store=TupleStore())


class TestCheckpointCrashWindows:
    def test_checkpoint_rename_crash_keeps_old_state(self, tmpdir):
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        store.write([touch("doc:one#viewer@user:u1")])
        want, rev = rels_of(store), store.revision
        failpoints.enable_failpoint("checkpointBeforeRename", 1)
        with pytest.raises(failpoints.FailPointPanic):
            mgr.checkpoint()
        s2 = PersistenceManager(tmpdir).recover()
        assert (rels_of(s2), s2.revision) == (want, rev)

    def test_manifest_rename_crash_keeps_old_manifest(self, tmpdir):
        mgr = PersistenceManager(tmpdir, fsync="never")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        first = mgr.checkpoint()
        store.write([touch("doc:two#viewer@user:u2")])
        want, rev = rels_of(store), store.revision
        failpoints.enable_failpoint("manifestBeforeRename", 1)
        with pytest.raises(failpoints.FailPointPanic):
            mgr.checkpoint()
        mgr2 = PersistenceManager(tmpdir)
        s2 = mgr2.recover()
        # manifest still points at the FIRST checkpoint; the tail write
        # replays from the WAL
        assert mgr2.recovery_info["checkpoint_revision"] == first["revision"]
        assert (rels_of(s2), s2.revision) == (want, rev)


class TestCliWiring:
    def base_args(self, *extra):
        return build_parser().parse_args([
            "--backend-kubeconfig", "x", "--rule-config", "y", *extra])

    def test_flags_parse(self):
        args = self.base_args("--data-dir", "/tmp/dd", "--wal-fsync",
                              "always", "--checkpoint-interval", "60")
        assert args.data_dir == "/tmp/dd"
        assert args.wal_fsync == "always"
        assert args.checkpoint_interval == 60.0
        assert validate(args) == []

    def test_defaults(self):
        args = self.base_args()
        assert args.data_dir == ""
        assert args.wal_fsync == "interval"
        assert args.checkpoint_interval == 300.0

    def test_data_dir_requires_store_backed_endpoint(self):
        args = self.base_args("--data-dir", "/tmp/dd",
                              "--spicedb-endpoint", "grpc://h:1")
        assert any("--data-dir" in e for e in validate(args))

    def test_checkpoint_interval_positive(self):
        args = self.base_args("--checkpoint-interval", "0")
        assert any("--checkpoint-interval" in e for e in validate(args))

    def test_bad_fsync_choice_rejected(self):
        with pytest.raises(SystemExit):
            self.base_args("--wal-fsync", "sometimes")

    def test_workflow_db_defaults_into_data_dir(self, tmpdir):
        dd = os.path.join(tmpdir, "data")
        assert resolve_workflow_db(dd, DEFAULT_WORKFLOW_DATABASE_PATH) == \
            os.path.join(dd, "dtx.sqlite")
        assert os.path.isdir(dd)
        # an explicit path wins
        assert resolve_workflow_db(dd, "/elsewhere.sqlite") == \
            "/elsewhere.sqlite"
        # no data dir: unchanged default
        assert resolve_workflow_db("", DEFAULT_WORKFLOW_DATABASE_PATH) == \
            DEFAULT_WORKFLOW_DATABASE_PATH
