"""Admission control: bounded dispatcher queues + load shedding
(docs/performance.md "Overload & rebuild behavior").

Overload used to turn into unbounded queueing: the dispatcher's check/LR
queues grew without limit, every caller waited, and the proxy's latency
under 2x sustained capacity was "eventually" instead of an answer.  This
module is the shared vocabulary for turning overload into *fast failure*:

1. **Queue bounds** (`spicedb/dispatch.py --max-queue-depth`): an enqueue
   that would push a dispatcher queue past its bound raises
   `AdmissionRejectedError(reason="queue_limit")` instead of queueing.

2. **Load shedder** (`LoadShedder`, wired in proxy/server.py): read-only
   verbs are rejected BEFORE authorization work starts when the
   dispatcher queues are already past a threshold or the flight
   recorder's SLO burn-rate signal (utils/devtel.py) is burning on both
   horizons.  Dual-writes are never shed — an interrupted two-phase
   write is strictly worse than a slow one — and the middleware marks
   update-verb requests exempt (`exempt()`) so their authorization
   checks bypass the queue bounds too.

3. **429 semantics**: every rejection carries a `retry_after_s` hint the
   server turns into a kube-style 429 `Status` with a `Retry-After`
   header; `/readyz` reports recent shedding as degraded-but-200 (load
   shed is an alert, not an outage — ejecting the pod would make it
   one).

Metrics: `authz_admission_rejected_total{reason=}` counts every
rejection (reasons: queue_limit, queue_depth, slo_burn, replica_lag) and
`authz_admission_queue_limit` exports the configured dispatcher bound
(0 = unbounded).  The `AdmissionControl` feature gate is the killswitch:
off, bounds and shedding are inert and overload queues exactly as
before.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Callable, Optional

from . import metrics as m

# verbs that may be shed: reads can be retried by any well-behaved kube
# client; update verbs ride the dual-write workflow and are never shed
READ_ONLY_VERBS = frozenset(("get", "list", "watch"))


class AdmissionRejectedError(Exception):
    """A request rejected by admission control (never a correctness
    failure): the caller should surface HTTP 429 with Retry-After."""

    def __init__(self, message: str, reason: str = "queue_limit",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


def enabled() -> bool:
    """AdmissionControl gate (killswitch); unknown-gate errors fail open
    so embedded users with a stripped gate registry keep the bounds they
    configured."""
    try:
        from .features import GATES
        return GATES.enabled("AdmissionControl")
    except Exception:
        return True


# -- write-path exemption -----------------------------------------------------
# Update-verb requests (dual-writes) must never be rejected by a queue
# bound mid-workflow: the middleware wraps their whole authorization in
# exempt(), and the contextvar crosses executor hops with the rest of
# the request context.

_EXEMPT: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "authz_admission_exempt", default=False)


@contextlib.contextmanager
def exempt():
    token = _EXEMPT.set(True)
    try:
        yield
    finally:
        _EXEMPT.reset(token)


def is_exempt() -> bool:
    return _EXEMPT.get()


# -- metrics ------------------------------------------------------------------

_REJECTED = m.REGISTRY.counter(
    "authz_admission_rejected_total",
    "Requests rejected by admission control, by reason (queue_limit = "
    "dispatcher queue bound, queue_depth / slo_burn / replica_lag = "
    "load shedder)",
    labels=("reason",))
_QUEUE_LIMIT = m.REGISTRY.gauge(
    "authz_admission_queue_limit",
    "Configured dispatcher queue bound (--max-queue-depth; 0 = unbounded)")
_QUEUE_LIMIT.set(0.0)


def note_rejected(reason: str) -> None:
    """Count one admission rejection.  Gate-guarded here as well as at
    every caller: with the AdmissionControl killswitch off NOTHING may
    reject, so a counter tick from a stale caller would be a lie to the
    operator reading the overload dashboard (analyzer rule A004)."""
    if not enabled():
        return
    _REJECTED.inc(reason=reason)


def set_queue_limit(n: int) -> None:
    _QUEUE_LIMIT.set(float(n))


# -- load shedder -------------------------------------------------------------


class LoadShedder:
    """Sheds read-only traffic above the endpoint when the system is
    already saturated, so queue depth stays bounded and in-flight
    requests keep their latency.

    Three independent signals, any sufficient:
    - `shed_queue_depth` > 0: total dispatcher queue depth (check + LR,
      read through `stats_fn`) at/over the threshold.
    - `shed_on_burn`: the flight recorder reports an SLO burning on both
      horizons (`burning_fn` non-empty) — the PR 5 burn-rate signal.
    - `shed_lag_s` > 0: the replication follower's staleness (`lag_fn`,
      seconds behind the leader) at/over the threshold — a stale
      replica sheds reads before serving garbage
      (spicedb/replication, docs/replication.md).

    `check(verb)` returns the rejection reason (or None to admit);
    callers build the 429 from `retry_after_s`.  `shedding_recently()`
    feeds /readyz: shed decisions within the last window mark the proxy
    degraded (still 200)."""

    RECENT_WINDOW_S = 10.0

    def __init__(self, shed_queue_depth: int = 0, shed_on_burn: bool = False,
                 retry_after_s: float = 1.0,
                 stats_fn: Optional[Callable[[], dict]] = None,
                 burning_fn: Optional[Callable[[], list]] = None,
                 depth_fn: Optional[Callable[[], int]] = None,
                 shed_lag_s: float = 0.0,
                 lag_fn: Optional[Callable[[], float]] = None):
        self.shed_queue_depth = shed_queue_depth
        self.shed_on_burn = shed_on_burn
        self.shed_lag_s = shed_lag_s
        self.retry_after_s = max(retry_after_s, 0.001)
        self._stats_fn = stats_fn
        self._burning_fn = burning_fn
        self._lag_fn = lag_fn
        # depth_fn (an O(1), allocation-free queue-depth accessor) is
        # preferred over stats_fn: the door check runs on EVERY
        # read-only request, before any authorization work — it must
        # not build the full merged stats dict each time
        self._depth_fn = depth_fn
        self._lock = threading.Lock()
        self._last_shed = 0.0
        self._shed_total = 0

    @property
    def active(self) -> bool:
        return (self.shed_queue_depth > 0 or self.shed_on_burn
                or (self.shed_lag_s > 0 and self._lag_fn is not None))

    def _queue_depth(self) -> int:
        if self._depth_fn is not None:
            try:
                return int(self._depth_fn())
            except Exception:
                return 0
        if self._stats_fn is None:
            return 0
        try:
            stats = self._stats_fn() or {}
        except Exception:
            return 0
        return (int(stats.get("check_queue_depth", 0))
                + int(stats.get("lr_queue_depth", 0)))

    def check(self, verb: str) -> Optional[str]:
        """Rejection reason for one request, or None to admit.  Only
        read-only verbs are ever shed; update verbs always pass."""
        if not self.active or not enabled():
            return None
        if verb not in READ_ONLY_VERBS:
            return None
        reason = None
        if (self.shed_queue_depth > 0
                and self._queue_depth() >= self.shed_queue_depth):
            reason = "queue_depth"
        elif self.shed_on_burn and self._burning_fn is not None:
            try:
                if self._burning_fn():
                    reason = "slo_burn"
            except Exception:
                reason = None
        if (reason is None and self.shed_lag_s > 0
                and self._lag_fn is not None):
            try:
                if self._lag_fn() >= self.shed_lag_s:
                    reason = "replica_lag"
            except Exception:
                reason = None
        if reason is not None:
            note_rejected(reason)
            with self._lock:
                self._last_shed = time.monotonic()
                self._shed_total += 1
        return reason

    def shedding_recently(self) -> bool:
        with self._lock:
            last = self._last_shed
        return bool(last) and time.monotonic() - last <= self.RECENT_WINDOW_S

    def snapshot(self) -> dict:
        with self._lock:
            last = self._last_shed
            total = self._shed_total
        recent = bool(last) and (time.monotonic() - last
                                 <= self.RECENT_WINDOW_S)
        return {"shed_total": total,
                "shedding_recently": recent,
                "shed_queue_depth": self.shed_queue_depth,
                "shed_on_burn": self.shed_on_burn}
