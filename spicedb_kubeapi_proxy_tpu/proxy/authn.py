"""Request authentication.

Mirrors the reference's authenticator stack (pkg/proxy/authn.go): in
embedded mode a header-based authenticator reads `X-Remote-User`,
`X-Remote-Group`, and `X-Remote-Extra-*` (reference authn.go:78-119); in
serving mode a TLS client certificate maps CN -> user and O -> groups (the
kube client-cert convention).  Authenticators compose: first success wins.
"""

from __future__ import annotations

from typing import Optional

from .httpcore import Request
from .kube import UserInfo

REMOTE_USER_HEADER = "X-Remote-User"
REMOTE_GROUP_HEADER = "X-Remote-Group"
REMOTE_EXTRA_PREFIX = "X-Remote-Extra-"


class Authenticator:
    def authenticate(self, req: Request) -> Optional[UserInfo]:
        raise NotImplementedError


class HeaderAuthenticator(Authenticator):
    """Embedded-mode authenticator (reference authn.go:78-119)."""

    def authenticate(self, req: Request) -> Optional[UserInfo]:
        name = req.headers.get(REMOTE_USER_HEADER)
        if not name:
            return None
        groups = req.headers.get_all(REMOTE_GROUP_HEADER)
        extra: dict = {}
        for k, v in req.headers.items():
            if k.lower().startswith(REMOTE_EXTRA_PREFIX.lower()):
                extra.setdefault(k[len(REMOTE_EXTRA_PREFIX):].lower(), []).append(v)
        return UserInfo(name=name, groups=list(groups), extra=extra)


class ClientCertAuthenticator(Authenticator):
    """TLS client-certificate authenticator: CN -> user, O -> groups."""

    def authenticate(self, req: Request) -> Optional[UserInfo]:
        cert = req.peer_cert
        if not cert:
            return None
        name = ""
        groups: list = []
        for rdn in cert.get("subject", ()):  # ((('commonName', 'x'),), ...)
            for key, value in rdn:
                if key == "commonName":
                    name = value
                elif key == "organizationName":
                    groups.append(value)
        if not name:
            return None
        return UserInfo(name=name, groups=groups)


class TokenFileAuthenticator(Authenticator):
    """Static bearer-token authenticator in the kube token-auth-file format
    (`token,user,uid[,"group1,group2"]` CSV rows), one of the built-in
    authentication modes the reference composes in via
    BuiltInAuthenticationOptions (reference authn.go:17-53)."""

    def __init__(self, path: str):
        import csv

        self._by_token: dict[str, UserInfo] = {}
        with open(path, "r", encoding="utf-8", newline="") as f:
            for row in csv.reader(f):
                if not row or len(row) < 3:
                    continue
                token, name, uid = row[0], row[1], row[2]
                groups = [g for g in (row[3].split(",") if len(row) > 3 else [])
                          if g]
                self._by_token[token] = UserInfo(name=name, uid=uid,
                                                 groups=groups)

    def authenticate(self, req: Request) -> Optional[UserInfo]:
        auth = req.headers.get("Authorization")
        if not auth.startswith("Bearer "):
            return None
        user = self._by_token.get(auth[len("Bearer "):].strip())
        if user is None:
            return None
        return UserInfo(name=user.name, uid=user.uid,
                        groups=list(user.groups),
                        extra={k: list(v) for k, v in user.extra.items()})


class AnonymousAuthenticator(Authenticator):
    """Kube-style anonymous fallback."""

    def authenticate(self, req: Request) -> Optional[UserInfo]:
        return UserInfo(name="system:anonymous",
                        groups=["system:unauthenticated"])


class AuthenticatorChain(Authenticator):
    def __init__(self, authenticators: list):
        self.authenticators = authenticators

    def authenticate(self, req: Request) -> Optional[UserInfo]:
        for a in self.authenticators:
            user = a.authenticate(req)
            if user is not None:
                return user
        return None
