"""The scripts/lint.py rule families, absorbed behind the unified
driver (scripts/analyze.py); `scripts/lint.py` is now a thin wrapper
over this module so existing invocations (check.sh history, pre-commit
hooks, tests/test_audit.py's subprocess tests) keep working.

Rules (docs/static-analysis.md has the full catalog):

  F401 unused import          E722 bare except          B006 mutable default
  E711 ==/!= None             F811 top-level redef      W291 trailing ws
  E501 long line              TAB  tab indent           E999 syntax error
  M001 metric label outside the bounded-cardinality allowlist
  M002 docs-vs-registry metric drift (default-path runs only)
  M003 host work inside a `# hotpath:` fenced device region (ops/*.py)

M003 remains as the narrow lexical fence check; rule A005 (rules_jit)
is its call-graph-reach superset and covers unfenced helpers.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import Finding

DEFAULT_PATHS = ["spicedb_kubeapi_proxy_tpu", "tests", "scripts",
                 "bench.py", "__graft_entry__.py"]
MAX_LINE = 100

# bounded-cardinality metric label names (M001).  Everything here has a
# value set bounded by configuration or schema — never by traffic.
ALLOWED_METRIC_LABELS = frozenset((
    "verb", "code", "phase", "backend", "resource", "reason", "stage",
    "decision", "generation", "kind", "le", "bucket", "slo", "window",
    "cause", "mode", "shard", "tier",
    # sweep telemetry: which fixpoint kernel produced the measurement
    # (ell | segment — bounded by the code, not by traffic)
    "kernel",
    # per-shard HBM accounting: owning device id of a sharded mesh
    # buffer (bounded by the local device count, not by traffic)
    "device",
    # Leopard fragment maintenance state (indexed | quarantined |
    # retired — bounded by the code, not by traffic)
    "state",
))
_METRIC_FACTORIES = ("counter", "gauge", "histogram")
_M001_PREFIX = "spicedb_kubeapi_proxy_tpu"

_HOTPATH_BEGIN = "hotpath: begin"
_HOTPATH_END = "hotpath: end"
_M003_NP = re.compile(
    r"(?<![A-Za-z_0-9])np\."
    r"(?!(ndarray|dtype|int32|int64|uint32|uint8|float32|bool_)\b)")
_M003_LOOP = re.compile(r"^\s*(async\s+)?(for|while)\b")

_METRICS_DOC = Path("docs/observability.md")
_DYNAMIC_METRIC_PREFIXES = ("authz_backend",)

# the analyzer's rule-fixture corpus is intentionally buggy
_SKIP_DIRS = frozenset(("__pycache__", "analysis_fixtures"))


def iter_py(paths):
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _SKIP_DIRS.intersection(f.parts):
                    yield f
        elif p.suffix == ".py":
            yield p


class Visitor(ast.NodeVisitor):
    def __init__(self, findings, path, metric_families=None):
        self.findings = findings
        self.path = path
        self.imports: dict = {}
        self.used: set = set()
        self.metric_families = metric_families

    def _add(self, lineno, code, msg):
        self.findings.append(Finding(code, str(self.path), lineno, msg))

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node.lineno
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno
        self.generic_visit(node)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self._add(node.lineno, "E722", "bare `except:`")
        self.generic_visit(node)

    def _check_defaults(self, node):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self._add(d.lineno, "B006", "mutable default argument")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node):
        for op, cmp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)):
                if isinstance(cmp, ast.Constant) and cmp.value is None:
                    self._add(node.lineno, "E711",
                              "comparison to None with ==/!= "
                              "(use is/is not)")
        self.generic_visit(node)

    def visit_Call(self, node):
        self._check_metric_labels(node)
        self.generic_visit(node)

    def _check_metric_labels(self, node):
        """M001: registry.counter/gauge/histogram(labels=(...)) label
        names must come from the bounded-cardinality allowlist."""
        if _M001_PREFIX not in Path(self.path).parts:
            return
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in _METRIC_FACTORIES):
            return
        if (self.metric_families is not None and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("authz_")):
            self.metric_families[node.args[0].value] = (
                self.path, node.lineno)
        label_values = [kw.value for kw in node.keywords
                        if kw.arg == "labels"]
        if len(node.args) >= 3:
            label_values.append(node.args[2])
        for value in label_values:
            if not isinstance(value, (ast.Tuple, ast.List)):
                self._add(node.lineno, "M001",
                          "metric labels must be a literal tuple/list so "
                          "the cardinality gate can verify the names")
                continue
            for el in value.elts:
                if not (isinstance(el, ast.Constant)
                        and isinstance(el.value, str)):
                    self._add(el.lineno, "M001",
                              "metric label name must be a string literal")
                    continue
                if el.value not in ALLOWED_METRIC_LABELS:
                    self._add(el.lineno, "M001",
                              f"metric label {el.value!r} is not in the "
                              f"bounded-cardinality allowlist "
                              f"(identities belong in audit events, not "
                              f"metric labels)")


def lint_file(path, findings, metric_families=None):
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        findings.append(Finding("E999", str(path), e.lineno or 0,
                                f"syntax error: {e}"))
        return
    v = Visitor(findings, path, metric_families=metric_families)
    v.visit(tree)

    src_names = v.used
    exempt = path.name == "__init__.py" or "__all__" in text
    if not exempt:
        for name, lineno in v.imports.items():
            if name not in src_names and f"{name}." not in text:
                findings.append(Finding("F401", str(path), lineno,
                                        f"unused import `{name}`"))

    seen: dict = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen:
                findings.append(Finding(
                    "F811", str(path), node.lineno,
                    f"redefinition of `{node.name}` "
                    f"(first at line {seen[node.name]})"))
            seen[node.name] = node.lineno

    m003 = ("ops" in Path(path).parts
            and _M001_PREFIX in Path(path).parts)
    in_hotpath = False
    hotpath_open_line = 0
    for i, line in enumerate(text.splitlines(), 1):
        if line != line.rstrip():
            findings.append(Finding("W291", str(path), i,
                                    "trailing whitespace"))
        if len(line) > MAX_LINE:
            findings.append(Finding(
                "E501", str(path), i,
                f"line too long ({len(line)} > {MAX_LINE})"))
        stripped = line.lstrip(" ")
        if stripped.startswith("\t"):
            findings.append(Finding("TAB", str(path), i,
                                    "hard tab in indentation"))
        if not m003:
            continue
        if _HOTPATH_BEGIN in line:
            if in_hotpath:
                findings.append(Finding(
                    "M003", str(path), i,
                    f"nested hotpath fence (previous begin at line "
                    f"{hotpath_open_line} never ended)"))
            in_hotpath, hotpath_open_line = True, i
            continue
        if _HOTPATH_END in line:
            in_hotpath = False
            continue
        if not in_hotpath:
            continue
        code_part = line.split("#", 1)[0]
        if _M003_NP.search(code_part):
            findings.append(Finding(
                "M003", str(path), i,
                "host numpy (`np.`) inside a device hot-path fence — "
                "per-batch staging belongs on device (jnp) or outside "
                "the fence; this is the host-pack regression the "
                "device-resident pipeline removed"))
        if _M003_LOOP.match(code_part):
            findings.append(Finding(
                "M003", str(path), i,
                "per-item Python loop inside a device hot-path fence — "
                "batch it on device or move it outside the fence"))
    if m003 and in_hotpath:
        findings.append(Finding(
            "M003", str(path), hotpath_open_line,
            "hotpath fence never closed (`# hotpath: end` missing)"))


def _is_dynamic_family(name):
    return any(name == p or name.startswith(p + "_")
               for p in _DYNAMIC_METRIC_PREFIXES)


def check_metric_drift(metric_families, findings):
    """M002: the docs/observability.md metric catalog and the families
    package code actually registers must agree, both directions."""
    if not _METRICS_DOC.exists():
        findings.append(Finding("M002", str(_METRICS_DOC), 0,
                                "metrics doc missing "
                                "(docs/observability.md)"))
        return
    text = _METRICS_DOC.read_text()
    doc_names: dict = {}
    for i, line in enumerate(text.splitlines(), 1):
        for match in re.finditer(r"authz_[a-z0-9][a-z0-9_]*", line):
            doc_names.setdefault(match.group(0).rstrip("_"), i)
    for name, (path, lineno) in sorted(metric_families.items()):
        if _is_dynamic_family(name):
            continue
        if name not in doc_names:
            findings.append(Finding(
                "M002", str(path), lineno,
                f"metric family {name!r} is registered here but absent "
                f"from {_METRICS_DOC} — document it (operators cannot "
                f"use what the catalog does not name)"))
    code_names = set(metric_families)
    for name, lineno in sorted(doc_names.items()):
        if _is_dynamic_family(name):
            continue
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        if name not in code_names and base not in code_names:
            findings.append(Finding(
                "M002", str(_METRICS_DOC), lineno,
                f"doc names metric family {name!r} but no package code "
                f"registers it — a renamed or removed metric leaves "
                f"dashboards reading zeros"))


def run_legacy(paths=None) -> tuple:
    """-> (findings, n_files).  M002 (cross-file drift) runs only on a
    default-path (full-tree) invocation, same contract as before."""
    default_run = not paths
    paths = paths or DEFAULT_PATHS
    findings: list = []
    metric_families: dict = {}
    n = 0
    for f in iter_py(paths):
        n += 1
        lint_file(f, findings, metric_families=metric_families)
    if default_run:
        check_metric_drift(metric_families, findings)
    findings.sort(key=lambda f: (f.path, f.line))
    return findings, n
