"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding tests run without TPU hardware.

The sandbox's sitecustomize registers the `axon` TPU-relay PJRT plugin at
interpreter start and forces `jax_platforms="axon,cpu"` via jax.config —
the env var alone is not enough, so we override the config value too,
before any backend initializes."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tiers (budgeted fuzz search, full-profile "
        "differential replays) — excluded from the tier-1 gate via "
        "-m 'not slow'")
