"""Workflow engine setup (reference pkg/authz/distributedtx/client.go):
SQLite-file or in-memory journal, monoprocess worker, registers the two
workflows and the activities."""

from __future__ import annotations


from ...proxy.httpcore import Transport
from ...spicedb.endpoints import PermissionsEndpoint
from .activity import ActivityHandler
from .engine import WorkflowEngine
from .journal import MemoryJournal, SQLiteJournal
from .workflow import (
    STRATEGY_PESSIMISTIC,
    WORKFLOWS)


def setup_workflow_engine(endpoint: PermissionsEndpoint,
                          kube_transport: Transport,
                          database_path: str = "",
                          default_lock_mode: str = STRATEGY_PESSIMISTIC,
                          audit=None) -> tuple:
    """Returns (engine-as-client, engine-as-worker); the caller starts the
    worker (reference SetupWithSQLiteBackend / SetupWithMemoryBackend).
    `audit` (utils/audit.AuditSink) receives one dual-write decision
    event per completed workflow instance."""
    from ...utils.audit import NULL_SINK
    journal = SQLiteJournal(database_path) if database_path else MemoryJournal()
    engine = WorkflowEngine(journal, audit=audit if audit is not None
                            else NULL_SINK)
    handler = ActivityHandler(endpoint, kube_transport)
    engine.register_activity("write_to_spicedb", handler.write_to_spicedb)
    engine.register_activity("read_relationships", handler.read_relationships)
    engine.register_activity("write_to_kube", handler.write_to_kube)
    engine.register_activity("check_kube_resource", handler.check_kube_resource)
    for name, fn in WORKFLOWS.items():
        engine.register_workflow(name, fn)
    engine.default_lock_mode = default_lock_mode  # type: ignore[attr-defined]
    return engine, engine
