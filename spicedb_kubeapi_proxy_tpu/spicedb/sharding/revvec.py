"""Revision-vector ZedTokens for the sharded write path.

With one leader, the ZedToken is a single integer revision
(X-Authz-Revision / X-Authz-Min-Revision, spicedb/replication).  With N
independent shard leaders there is no global revision — each shard's
WAL advances on its own — so the client-facing token becomes an encoded
`{shard: revision}` VECTOR:

    0:12,2:7        components for shards 0 and 2
    *:5             legacy floor: applies to EVERY shard (a bare
                    integer token from a pre-sharding client decodes
                    to this)
    12              bare integer == floor 12 (legacy round-trip)

The router owns the vector: on the way in it extracts the single
component for the target shard and forwards it as a bare integer — so
the per-shard leader's existing `X-Authz-Min-Revision` wait-or-forward
gate (proxy/server.py _leader_gate, _replica_gate) runs byte-identical
to the single-leader deployment, enforcing ONLY its own component.  On
the way out the router merges the serving shard's response revision
into the request's vector (pointwise max), so a client threading the
token through reads-after-writes accumulates exactly the components it
has observed — a token ahead of one shard waits/forwards on that shard
only, while every other shard serves immediately.
"""

from __future__ import annotations

from typing import Optional


class RevisionVectorError(ValueError):
    """Malformed revision-vector token."""


class RevisionVector:
    """Immutable-ish {shard: revision} vector with a legacy floor
    component applying to every shard."""

    __slots__ = ("parts", "floor")

    def __init__(self, parts: Optional[dict] = None, floor: int = 0):
        self.parts = dict(parts or {})
        self.floor = int(floor)
        for k, v in self.parts.items():
            if not isinstance(k, int) or k < 0:
                raise RevisionVectorError(f"invalid shard id {k!r}")
            if not isinstance(v, int) or v < 0:
                raise RevisionVectorError(
                    f"invalid revision {v!r} for shard {k}")
        if self.floor < 0:
            raise RevisionVectorError(f"invalid floor revision {floor!r}")

    @classmethod
    def decode(cls, raw: Optional[str]) -> "RevisionVector":
        """Parse a token header value.  Empty/None -> the empty vector;
        a bare integer -> legacy floor; otherwise comma-separated
        `shard:revision` components (`*` = floor)."""
        raw = (raw or "").strip()
        if not raw:
            return cls()
        if raw.isdigit():
            return cls(floor=int(raw))
        parts: dict = {}
        floor = 0
        for piece in raw.split(","):
            piece = piece.strip()
            if not piece:
                continue
            shard_s, colon, rev_s = piece.partition(":")
            shard_s, rev_s = shard_s.strip(), rev_s.strip()
            if not colon or not rev_s.isdigit():
                raise RevisionVectorError(
                    f"invalid revision-vector component {piece!r}: want "
                    f"shard:revision or *:revision")
            rev = int(rev_s)
            if shard_s == "*":
                floor = max(floor, rev)
            elif shard_s.isdigit():
                shard = int(shard_s)
                parts[shard] = max(parts.get(shard, 0), rev)
            else:
                raise RevisionVectorError(
                    f"invalid shard id in component {piece!r}")
        return cls(parts, floor=floor)

    def encode(self) -> str:
        """Header-safe encoding.  A floor-only vector encodes as the
        bare integer (so a legacy token round-trips unchanged through a
        router that touched nothing)."""
        if not self.parts:
            return str(self.floor) if self.floor else ""
        pieces = []
        if self.floor:
            pieces.append(f"*:{self.floor}")
        pieces.extend(f"{k}:{v}" for k, v in sorted(self.parts.items()))
        return ",".join(pieces)

    # -- accessors -----------------------------------------------------------

    @property
    def is_empty(self) -> bool:
        return not self.parts and not self.floor

    def component(self, shard: int) -> int:
        """The minimum revision this token demands of `shard` (0 = no
        demand)."""
        return max(self.parts.get(shard, 0), self.floor)

    # -- merging -------------------------------------------------------------

    def merged(self, shard: int, revision: int) -> "RevisionVector":
        """New vector with `shard`'s component raised to `revision`."""
        parts = dict(self.parts)
        parts[shard] = max(parts.get(shard, 0), int(revision))
        return RevisionVector(parts, floor=self.floor)

    def merged_with(self, other: "RevisionVector") -> "RevisionVector":
        """Pointwise max of two vectors."""
        parts = dict(self.parts)
        for k, v in other.parts.items():
            parts[k] = max(parts.get(k, 0), v)
        return RevisionVector(parts, floor=max(self.floor, other.floor))

    def __eq__(self, other) -> bool:
        return (isinstance(other, RevisionVector)
                and self.parts == other.parts and self.floor == other.floor)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"RevisionVector({self.parts}, floor={self.floor})"
