"""Probe round 2: (a) bit-plane extract layout (transpose in packed
space, no [L, C] bool transpose), (b) kernel/transfer overlap with the
plain full fetch, (c) tighter 64k-granule flat size.

Run:  PYTHONPATH=/root/repo python scripts/probe_compact2.py
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from spicedb_kubeapi_proxy_tpu.models import workloads as wl
from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef, parse_relationship


def main():
    print("devices:", jax.devices(), flush=True)
    w = wl.multitenant_1m()
    schema = sch.parse_schema(w.schema_text)
    ep = JaxEndpoint(schema)
    t0 = time.perf_counter()
    ep.store.bulk_load([parse_relationship(r) for r in w.relationships])
    print(f"load {time.perf_counter()-t0:.1f}s", flush=True)

    subjects = [SubjectRef("user", w.subjects[i]) for i in range(256)]
    with ep._lock:
        graph = ep._current_graph()
        q_arr, cols, _ = ep._encode_subjects(graph, subjects)
        snap = graph.snapshot()
    rng = graph.prog.slot_range(w.resource_type, w.permission)

    def kernel():
        return jnp.asarray(graph.run_lookup_packed(rng[0], rng[1], q_arr,
                                                   snap=snap))

    out = kernel()
    out.block_until_ready()
    full = np.ascontiguousarray(out)   # warm transfer mode
    L, W = full.shape
    C = W * 32
    total_set = 615400

    # -- A baseline: serial kernel+fetch x2 ---------------------------------
    def serial_once():
        o = kernel()
        return np.ascontiguousarray(o)

    serial_once()
    t0 = time.perf_counter()
    serial_once()
    serial_once()
    ta = time.perf_counter() - t0
    print(f"A serial 2x (kernel+fetch): {ta*1e3:.0f} ms ({ta/2*1e3:.0f}/batch)",
          flush=True)

    # -- B overlap: dispatch both kernels, then fetch both ------------------
    t0 = time.perf_counter()
    o1 = kernel()
    o2 = kernel()
    f1 = np.ascontiguousarray(o1)
    f2 = np.ascontiguousarray(o2)
    tb = time.perf_counter() - t0
    print(f"B overlapped 2x (dispatch,dispatch,fetch,fetch): {tb*1e3:.0f} ms "
          f"({tb/2*1e3:.0f}/batch)", flush=True)

    # -- B2 with copy_to_host_async -----------------------------------------
    t0 = time.perf_counter()
    o1 = kernel()
    o1.copy_to_host_async()
    o2 = kernel()
    o2.copy_to_host_async()
    f1 = np.ascontiguousarray(o1)
    f2 = np.ascontiguousarray(o2)
    tb2 = time.perf_counter() - t0
    print(f"B2 async-copy 2x: {tb2*1e3:.0f} ms ({tb2/2*1e3:.0f}/batch)",
          flush=True)

    # -- C bit-plane extract -------------------------------------------------
    K = ((int(total_set * 1.15) >> 16) + 1) << 16   # 64k granules
    print(f"K = {K} ({K*4/1e6:.1f} MB)", flush=True)

    @jax.jit
    def extract_bitplane(sl):
        # sl [L, W] -> [W, L] (packed transpose, small) -> per-bit planes
        slT = sl.T                                    # [W, L]
        shifts = jnp.arange(32, dtype=jnp.uint32)
        # [32, W, L]: plane b of word w = column w*32+b
        planes = (slT[None, :, :] >> shifts[:, None, None]) & jnp.uint32(1)
        # column-major order wants [W, 32, L] flattened
        b = planes.transpose(1, 0, 2).reshape(-1)     # [W*32*L]
        counts = planes.sum(axis=2, dtype=jnp.int32).T.reshape(-1)  # [C]
        flat = jnp.nonzero(b, size=K, fill_value=C * L)[0]
        return counts, flat.astype(jnp.uint32)

    def fetch_compact():
        sl = kernel()
        counts, flat = extract_bitplane(sl)
        return np.asarray(counts), np.asarray(flat)

    t0 = time.perf_counter()
    counts, flat = fetch_compact()
    print(f"C first (compile) {time.perf_counter()-t0:.1f}s", flush=True)
    for _ in range(3):
        t0 = time.perf_counter()
        counts, flat = fetch_compact()
        tc = time.perf_counter() - t0
        print(f"C bit-plane compact fetch: {tc*1e3:.0f} ms "
              f"({(counts.nbytes+flat.nbytes)/1e6:.1f} MB)", flush=True)

    # device-only cost of the extract (no transfer): time scalar fetch
    t0 = time.perf_counter()
    c2, f2 = extract_bitplane(out)
    _ = int(np.asarray(c2[0]))
    print(f"C extract device-only (first count scalar): "
          f"{(time.perf_counter()-t0)*1e3:.0f} ms", flush=True)

    # verify
    total = int(counts.sum())
    assert total == total_set, (total, total_set)
    starts = np.concatenate([[0], np.cumsum(counts)])
    for c in (0, 7, 100, 255):
        got = np.sort(flat[starts[c]:starts[c+1]] % np.uint32(L))
        wcol = np.ascontiguousarray(full[:, c // 32])
        want = np.nonzero((wcol >> np.uint32(c % 32)) & np.uint32(1))[0]
        assert np.array_equal(got, np.sort(want.astype(np.uint32))), c
    print("equivalence ok", flush=True)

    # -- D overlapped compact: dispatch k+extract for both, fetch both ------
    t0 = time.perf_counter()
    e1 = extract_bitplane(kernel())
    e2 = extract_bitplane(kernel())
    r1 = (np.asarray(e1[0]), np.asarray(e1[1]))
    r2 = (np.asarray(e2[0]), np.asarray(e2[1]))
    td = time.perf_counter() - t0
    print(f"D overlapped compact 2x: {td*1e3:.0f} ms ({td/2*1e3:.0f}/batch)",
          flush=True)


if __name__ == "__main__":
    main()
