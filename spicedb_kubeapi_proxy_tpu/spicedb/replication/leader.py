"""Leader side of WAL-shipping replication: the ReplicationHub.

Serves the persistence data dir over the proxy's authenticated HTTP
surface (routes wired in proxy/server.py):

    GET /replication/manifest
        {"revision": N, "checkpoint": {...MANIFEST.json...} | null,
         "segments": [{"name", "seq", "size", "sealed"}...],
         "sidecars": ["snap-*.npz"...], "leader_id": "..."}
        ?wait_revision=R&timeout_ms=T long-polls until the store's
        revision EXCEEDS R (or the timeout lapses — the caller gets the
        current manifest either way and decides from `revision`).

    GET /replication/segment/<name>[?offset=N]
        Raw bytes of a WAL segment or bulk-load snapshot sidecar from
        byte N (also honors `Range: bytes=N-`).  206 on a partial
        serve, 404 when reclaimed — the follower's signal to
        re-bootstrap from the newest checkpoint.

    GET /replication/checkpoint/<name>
        Raw bytes of a columnar checkpoint file.

Names are validated against the exact artifact patterns before touching
the filesystem (no traversal).  The long-poll is fed by the store's
commit-listener hook: the hub attaches AFTER the PersistenceManager, so
by WAL-before-visibility ordering every revision a waiter is woken for
is already on disk and replayable.
"""

from __future__ import annotations

import asyncio
import os
import re
import threading
import time
import uuid
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ...utils import metrics as m
from ..store import TupleStore

_SAFE_NAME = re.compile(
    r"^(seg-\d{8}\.wal|snap-\d{12}\.npz|ckpt-\d{12}\.npz)$")

DEFAULT_LONGPOLL_S = 25.0
MAX_LONGPOLL_S = 60.0


def safe_artifact_name(name: str) -> bool:
    """True when `name` is exactly one WAL segment / sidecar / checkpoint
    file name — the only paths the hub will ever read."""
    return bool(_SAFE_NAME.match(name))


# gate-off = no hub exists (the server 503s /replication/* without
# constructing/attaching one), so nothing here can tick
class ReplicationHub:  # noqa: A004(built behind gate)
    """Publishes one PersistenceManager's data dir to followers."""

    def __init__(self, store: TupleStore, persistence,
                 leader_id: str = "",
                 registry: Optional[m.Registry] = None):
        self.store = store
        self.persistence = persistence
        # unique per INCARNATION, not per host: segment seqs restart
        # after a leader restart (reclaim empties the wal dir), so a
        # follower must detect "same name, different log" by the id
        # changing and re-bootstrap rather than resume its byte cursor
        self.leader_id = (leader_id
                          or f"leader-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        # (loop, future) pairs parked in wait_for_revision; woken from
        # the commit listener via call_soon_threadsafe (the listener runs
        # under the store lock — it must only schedule, never block)
        self._waiters: list = []
        self._waiters_lock = threading.Lock()
        self._attached = False
        self.stats = {"manifest_serves": 0, "longpoll_waits": 0,
                      "segment_serves": 0, "checkpoint_serves": 0}
        registry = registry or m.REGISTRY
        self._shipped = registry.counter(
            "authz_replication_shipped_bytes_total",
            "Bytes of WAL segments / sidecars / checkpoints served to "
            "replication followers, by artifact kind",
            labels=("kind",))

    # -- commit hook ---------------------------------------------------------

    def attach(self) -> None:
        """Start waking long-poll waiters on commits.  Call AFTER the
        PersistenceManager attached: listener order is append order, so
        the WAL append precedes the wakeup for every commit."""
        if not self._attached:
            self.store.add_commit_listener(self._on_commit)
            self._attached = True

    def detach(self) -> None:
        if self._attached:
            self.store.remove_commit_listener(self._on_commit)
            self._attached = False

    def _on_commit(self, kind: str, revision: int, payload) -> None:
        # under the store lock — schedule only.  The waiter re-checks the
        # store revision on its own loop, which cannot run before this
        # commit completes and the new revision is reader-visible.
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, []
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(self._resolve, fut)
            except RuntimeError:
                pass  # waiter's loop already closed

    @staticmethod
    def _resolve(fut) -> None:
        if not fut.done():
            fut.set_result(None)

    async def wait_for_revision(self, min_exclusive: int,
                                timeout_s: float) -> bool:
        """Park until store.revision > min_exclusive (True) or the
        timeout lapses (False)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        loop = asyncio.get_running_loop()
        while self.store.revision <= min_exclusive:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            fut = loop.create_future()
            with self._waiters_lock:
                self._waiters.append((loop, fut))
            # re-check AFTER publishing the waiter: a commit landing
            # between the loop-condition read and the append above has
            # already drained the (then-empty) waiter list — without
            # this, that waiter sleeps the full timeout on a revision
            # that is long since visible
            if self.store.revision > min_exclusive:
                with self._waiters_lock:
                    try:
                        self._waiters.remove((loop, fut))
                    except ValueError:
                        pass
                return True
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return self.store.revision > min_exclusive
            finally:
                with self._waiters_lock:
                    try:
                        self._waiters.remove((loop, fut))
                    except ValueError:
                        pass
        return True

    # -- manifest ------------------------------------------------------------

    def manifest(self) -> dict:
        from ..persist import checkpoint as ckpt
        wal = self.persistence.wal
        segments = []
        for seq in wal.segment_seqs():
            path = wal._path(seq)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue  # reclaimed between listdir and stat
            segments.append({
                "name": os.path.basename(path), "seq": seq, "size": size,
                # the open segment keeps growing; anything else is sealed
                "sealed": not (seq == wal._cur_seq
                               and wal._cur_file is not None),
            })
        sidecars = []
        try:
            for name in sorted(os.listdir(wal.dir)):
                if re.match(r"^snap-\d{12}\.npz$", name):
                    sidecars.append(name)
        except OSError:
            pass
        self.stats["manifest_serves"] += 1
        return {
            "leader_id": self.leader_id,
            "revision": self.store.revision,
            "checkpoint": ckpt.read_manifest(self.persistence.data_dir),
            "segments": segments,
            "sidecars": sidecars,
        }

    async def serve_manifest(self, req) -> "Response":
        from ...proxy.httpcore import json_response
        params = parse_qs(urlsplit(req.target).query)
        wait_raw = (params.get("wait_revision") or [""])[0]
        if wait_raw:
            try:
                wait_rev = int(wait_raw)
                timeout_ms = float(
                    (params.get("timeout_ms")
                     or [str(DEFAULT_LONGPOLL_S * 1e3)])[0])
            except ValueError:
                return json_response(400, {
                    "kind": "Status", "apiVersion": "v1", "metadata": {},
                    "status": "Failure", "code": 400,
                    "message": "wait_revision/timeout_ms must be integers"})
            self.stats["longpoll_waits"] += 1
            await self.wait_for_revision(
                wait_rev, min(max(timeout_ms / 1e3, 0.0), MAX_LONGPOLL_S))
        return json_response(200, self.manifest())

    # -- artifact bytes ------------------------------------------------------

    async def _serve_file(self, req, path: str, kind: str) -> "Response":
        from ...proxy.httpcore import Response, json_response
        params = parse_qs(urlsplit(req.target).query)
        offset = 0
        raw_off = (params.get("offset") or ["0"])[0]
        range_hdr = req.headers.get("Range")
        try:
            offset = int(raw_off)
            if range_hdr:
                mm = re.match(r"^bytes=(\d+)-$", range_hdr.strip())
                if mm is None:
                    raise ValueError(f"unsupported Range {range_hdr!r}")
                offset = int(mm.group(1))
        except ValueError as e:
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400, "message": str(e)})

        def _read():
            # a sealed segment is up to segment_bytes and a checkpoint
            # tens of MB — reading it synchronously would park the
            # leader's event loop (which is also serving live traffic)
            # for the whole disk read, once per follower fetch
            # (analyzer A001 class); the read runs on an executor thread
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                if offset:
                    f.seek(offset)
                return size, f.read()

        try:
            size, body = await asyncio.get_running_loop().run_in_executor(
                None, _read)
        except OSError:
            return json_response(404, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "reason": "NotFound", "code": 404,
                "message": f"artifact {os.path.basename(path)!r} is gone "
                           f"(reclaimed by a checkpoint?); re-bootstrap "
                           f"from /replication/manifest"})
        self._shipped.inc(len(body), kind=kind)
        self.stats[f"{kind}_serves"] += 1
        resp = Response(status=206 if offset else 200, body=body)
        resp.headers.set("Content-Type", "application/octet-stream")
        resp.headers.set("X-Replication-Offset", str(offset))
        resp.headers.set("X-Replication-Size", str(size))
        return resp

    async def serve_segment(self, req, name: str) -> "Response":
        from ...proxy.httpcore import json_response
        if not safe_artifact_name(name) or name.startswith("ckpt-"):
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400,
                "message": f"invalid segment name {name!r}"})
        return await self._serve_file(
            req, os.path.join(self.persistence.wal.dir, name), "segment")

    async def serve_checkpoint(self, req, name: str) -> "Response":
        from ...proxy.httpcore import json_response
        if not safe_artifact_name(name) or not name.startswith("ckpt-"):
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400,
                "message": f"invalid checkpoint name {name!r}"})
        return await self._serve_file(
            req, os.path.join(self.persistence.ckpt_dir, name), "checkpoint")

    def snapshot(self) -> dict:
        """/debug/replication payload (leader role)."""
        with self._waiters_lock:
            waiters = len(self._waiters)
        man = self.manifest()
        return {"role": "leader", "leader_id": self.leader_id,
                "revision": man["revision"],
                "checkpoint_revision": (man["checkpoint"] or {}).get(
                    "revision"),
                "segments": man["segments"],
                "longpoll_waiters": waiters,
                **self.stats}
