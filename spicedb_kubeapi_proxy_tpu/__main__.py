"""`python -m spicedb_kubeapi_proxy_tpu` (reference
cmd/spicedb-kubeapi-proxy/main.go:20-29)."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
