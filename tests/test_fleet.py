"""Fleet-wide distributed tracing (utils/fleet.py + the propagation
seams in proxy/server.py, spicedb/sharding/router.py,
spicedb/replication; docs/observability.md "Fleet tracing").

- pure merge unit tests: parent-hop alignment (skew-immune by
  construction), per-tier self/network attribution reconciling against
  the root duration, wall-clock fallback accounting, segment dedupe,
  serving-stage roll-ups, the merged chrome-trace, /metrics parsing;
- trace continuity over real in-process processes: one client trace id
  spans HTTP router -> shard leader with per-tier spans and hop
  parent/child linkage; a follower forwarding a dual-write (and a
  min-revision read) to its leader joins the client's trace, and the
  leader's audit events name the full tier path;
- the /debug/fleet merged view over router + shard leaders;
- the Timeline gate-off tripwire: no propagation headers leave the
  process, the receiving side mints locally, response bytes identical.
"""

import asyncio
import json
import os
import shutil
import tempfile

import pytest

from spicedb_kubeapi_proxy_tpu.config import proxyrule
from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
    HandlerTransport,
    Headers,
    Request,
)
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    merge_internal_definitions,
)
from spicedb_kubeapi_proxy_tpu.spicedb.replication import MIN_REVISION_HEADER
from spicedb_kubeapi_proxy_tpu.spicedb.sharding import (
    PartitionMap,
    ShardRouter,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import fleet, tracing
from spicedb_kubeapi_proxy_tpu.utils.features import GATES

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
definition podns {
  relation creator: user
  permission view = creator
}
definition pod {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources: {tpl: "namespace:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-pods}
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
lock: Optimistic
check: [{tpl: "podns:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"
"""

PMAP_SPEC = "pod=1,podns=1"

TID = "f0" * 16
TID2 = "e1" * 16


def parsed_schema():
    return merge_internal_definitions(sch.parse_schema(SCHEMA))


@pytest.fixture(autouse=True)
def clean_state():
    tracing.RECORDER.drain()
    yield
    tracing.RECORDER.drain()
    GATES.reset()


@pytest.fixture
def tmp():
    d = tempfile.mkdtemp(prefix="fleet-test-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


# -- synthetic segment builders for the pure merge tests ----------------------


def seg(tid, tier, dur_ms, start_unix=100.0, parent=None, spans=()):
    attrs = {"tier": tier, "tier_path": tier}
    if parent:
        attrs["parent_span"] = parent
    return {"trace_id": tid, "start_unix": start_unix,
            "duration_ms": dur_ms, "attrs": attrs, "spans": list(spans)}


def hop(span_id, start_ms, dur_ms, name="hop.forward"):
    return {"name": name, "start_ms": start_ms, "duration_ms": dur_ms,
            "attrs": {"span_id": span_id}}


def member(url, traces, skew=None, lag=None, flight=None):
    return {"url": url, "error": None, "traces": traces,
            "flight": flight or {}, "skew_s": skew, "lag_s": lag}


HOP_A = "aa" * 8
HOP_B = "bb" * 8


class TestMergeUnit:
    def test_two_tier_alignment_and_attribution(self):
        root = seg(TID, "router", 12.0,
                   spans=[hop(HOP_A, 2.0, 8.0, "hop.shard_forward")])
        child = seg(TID, "leader", 6.0, parent=HOP_A)
        a = fleet.assemble_trace([(member("r", []), root),
                                  (member("s0", []), child)])
        assert a["tier_count"] == 2
        assert not a["aligned_by_wall"] and a["wall_fallbacks"] == 0
        offsets = {s["tier"]: s["offset_ms"] for s in a["segments"]}
        # child anchored at the PARENT's hop start, in the parent clock
        assert offsets == {"router": 0.0, "leader": 2.0}
        assert a["tiers"]["router"]["self_ms"] == pytest.approx(4.0)
        assert a["tiers"]["leader"]["self_ms"] == pytest.approx(6.0)
        assert a["network_ms"] == pytest.approx(2.0)
        # the attribution reconciles against the root duration exactly
        assert a["attributed_ms"] == pytest.approx(a["duration_ms"])

    def test_alignment_is_skew_immune(self):
        root = seg(TID, "router", 12.0,
                   spans=[hop(HOP_A, 2.0, 8.0)])
        for skew_s in (0.0, +1000.0, -1000.0):
            child = seg(TID, "leader", 6.0, parent=HOP_A,
                        start_unix=100.0 + skew_s)
            a = fleet.assemble_trace([(member("r", []), root),
                                      (member("s0", []), child)])
            off = {s["tier"]: s["offset_ms"] for s in a["segments"]}
            # a member clock off by ±1000s moves NOTHING
            assert off["leader"] == 2.0
            assert a["attributed_ms"] == pytest.approx(12.0)

    def test_three_tier_chain(self):
        root = seg(TID, "router", 12.0, spans=[hop(HOP_A, 1.0, 10.0)])
        mid = seg(TID, "follower", 8.0, parent=HOP_A,
                  spans=[hop(HOP_B, 2.0, 5.0, "hop.forward_to_leader")])
        deep = seg(TID, "leader", 4.0, parent=HOP_B)
        a = fleet.assemble_trace([(member("r", []), root),
                                  (member("f", []), mid),
                                  (member("l", []), deep)])
        assert a["tier_count"] == 3
        off = {s["tier"]: s["offset_ms"] for s in a["segments"]}
        assert off == {"router": 0.0, "follower": 1.0, "leader": 3.0}
        assert a["tiers"]["router"]["self_ms"] == pytest.approx(2.0)
        assert a["tiers"]["follower"]["self_ms"] == pytest.approx(3.0)
        assert a["tiers"]["leader"]["self_ms"] == pytest.approx(4.0)
        assert a["network_ms"] == pytest.approx(3.0)  # (10-8) + (5-4)
        assert a["attributed_ms"] == pytest.approx(12.0)

    def test_orphan_falls_back_to_wall_clock(self):
        root = seg(TID, "router", 12.0, start_unix=100.0)
        orphan = seg(TID, "leader", 6.0, parent="cc" * 8,
                     start_unix=100.050)
        a = fleet.assemble_trace([(member("r", []), root),
                                  (member("s0", []), orphan)])
        assert a["wall_fallbacks"] == 1
        off = {s["tier"]: s["offset_ms"] for s in a["segments"]}
        assert off["leader"] == pytest.approx(50.0)

    def test_serving_stage_rollup_per_tier(self):
        child_spans = [
            {"name": "serving.decode", "start_ms": 0.5,
             "duration_ms": 3.0},
            {"name": "serving.filter", "start_ms": 3.5,
             "duration_ms": 2.0},
            {"name": "match", "start_ms": 0.0, "duration_ms": 1.0,
             "phase": True},
        ]
        root = seg(TID, "router", 12.0, spans=[hop(HOP_A, 2.0, 8.0)])
        child = seg(TID, "leader", 6.0, parent=HOP_A,
                    spans=child_spans)
        a = fleet.assemble_trace([(member("r", []), root),
                                  (member("s0", []), child)])
        assert a["serving_stages_ms"]["leader"] == {
            "decode": 3.0, "filter": 2.0}

    def test_merge_dedupes_and_drops_single_process(self):
        root = seg(TID, "router", 12.0, spans=[hop(HOP_A, 2.0, 8.0)])
        child = seg(TID, "leader", 6.0, parent=HOP_A)
        lonely = seg(TID2, "leader", 3.0)
        # the router aggregates itself AND shows up in its own peer
        # scrape: the duplicated segments must not double-count a tier
        merged = fleet.merge_fleet([
            member("router", [root, child, lonely]),
            member("http://s0", [child, lonely]),
        ])
        assert [t["trace_id"] for t in merged["traces"]] == [TID]
        t = merged["traces"][0]
        assert t["tier_count"] == 2
        assert t["tiers"]["leader"]["segments"] == 1
        # tier stats carry the per-trace self times
        assert merged["tiers"]["router"]["count"] == 1
        assert merged["tiers"]["network"]["p50_ms"] == pytest.approx(2.0)

    def test_chrome_trace_one_track_per_tier_process(self):
        root = seg(TID, "router", 12.0, spans=[hop(HOP_A, 2.0, 8.0)])
        child = seg(TID, "leader", 6.0, parent=HOP_A)
        merged = fleet.merge_fleet([member("router", [root]),
                                    member("http://s0", [child])])
        ct = merged["chrome_trace"]
        names = [e for e in ct["traceEvents"] if e["ph"] == "M"]
        slices = [e for e in ct["traceEvents"] if e["ph"] == "X"]
        assert len(names) == 2          # (router, router) + (leader, s0)
        assert ct["otherData"]["tracks"] == 2
        assert any(e["cat"] == "request" for e in slices)
        # slice ts is µs on the merged (aligned) timeline
        leader_pid = next(e["pid"] for e in names
                          if "leader" in e["args"]["name"])
        leader_req = next(e for e in slices
                          if e["pid"] == leader_pid
                          and e["cat"] == "request")
        assert leader_req["ts"] == pytest.approx(2000.0)

    def test_slo_and_member_rollup(self):
        merged = fleet.merge_fleet([
            member("http://f0", [], skew=0.012, lag=1.5,
                   flight={"burning": [{"slo": "latency_p99"}]}),
            {"url": "http://dead", "error": "GET /metrics: boom",
             "traces": [], "flight": {}, "skew_s": None, "lag_s": None},
        ])
        assert merged["slo_burning"] == [
            {"url": "http://f0", "slo": {"slo": "latency_p99"}}]
        by_url = {m["url"]: m for m in merged["members"]}
        assert by_url["http://f0"]["skew_s"] == 0.012
        assert by_url["http://dead"]["error"].startswith("GET /metrics")

    def test_parse_metric(self):
        text = ("# HELP authz_clock_skew_seconds skew\n"
                "authz_clock_skew_seconds -0.025\n"
                "authz_replica_lag_seconds 1.75\n")
        assert fleet.parse_metric(text, fleet._SKEW_RE) == -0.025
        assert fleet.parse_metric(text, fleet._LAG_RE) == 1.75
        assert fleet.parse_metric("", fleet._SKEW_RE) is None


# -- real processes: router -> shard leaders ----------------------------------


class CapturingTransport:
    """Transport wrapper recording every forwarded request (the gate-off
    tripwire inspects the exact header set that crossed the hop)."""

    def __init__(self, inner):
        self.inner = inner
        self.seen = []

    async def round_trip(self, req):
        self.seen.append(req)
        return await self.inner.round_trip(req)


def make_shard_leader(tmp, subdir, seed_rels):
    kube = FakeKubeApiServer()
    kube.seed("", "v1", "namespaces", {"metadata": {"name": "team-a"}})
    proxy = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        data_dir=os.path.join(tmp, subdir),
        wal_fsync="never",
    ))
    if seed_rels and proxy.endpoint.store.revision == 0:
        proxy.endpoint.store.bulk_load(
            [parse_relationship(r) for r in seed_rels])
    proxy.enable_dual_writes()
    return proxy


def make_router(tmp):
    s0 = make_shard_leader(tmp, "s0",
                           ["namespace:team-a#creator@user:alice"])
    s1 = make_shard_leader(tmp, "s1",
                           ["podns:team-a#creator@user:alice"])
    pm = PartitionMap.parse(PMAP_SPEC, n_shards=2)
    cap0 = CapturingTransport(HandlerTransport(s0.handler))
    cap1 = CapturingTransport(HandlerTransport(s1.handler))
    router = ShardRouter(
        pm, [cap0, cap1],
        rule_configs=proxyrule.parse(RULES), schema=parsed_schema(),
        fleet_peers=["http://s0.test", "http://s1.test"],
        fleet_transports={
            "http://s0.test": HandlerTransport(s0.handler),
            "http://s1.test": HandlerTransport(s1.handler)})
    return router, s0, s1, cap0, cap1


async def router_req(router, method, target, user="alice", body=None,
                     headers=()):
    h = Headers(list(headers))
    if user:
        h.set("X-Remote-User", user)
    h.set("Accept", "application/json")
    data = b""
    if body is not None:
        data = json.dumps(body).encode()
        h.set("Content-Type", "application/json")
    return await router.handle(Request(method=method, target=target,
                                       headers=h, body=data))


def segments_for(tid):
    return [t for t in tracing.RECORDER.snapshot()
            if t["trace_id"] == tid]


class TestRouterContinuity:
    def test_one_trace_spans_router_and_shard_leader(self, tmp):
        router, s0, _s1, cap0, _cap1 = make_router(tmp)

        async def go():
            resp = await router_req(
                router, "GET", "/api/v1/namespaces/team-a",
                headers=[(tracing.TRACE_ID_HEADER, TID)])
            assert resp.status == 200, resp.body
            # the client's id is echoed back from the ROUTER tier
            assert resp.headers.get(tracing.TRACE_ID_HEADER) == TID
            segs = segments_for(TID)
            by_tier = {t["attrs"].get("tier"): t for t in segs}
            assert set(by_tier) == {"router", "leader"}
            # hop parent/child linkage: the leader's whole request is a
            # child of the router's client-side hop span
            hop_sp = next(sp for sp in by_tier["router"]["spans"]
                          if sp["name"] == "hop.shard_forward")
            assert by_tier["leader"]["attrs"]["parent_span"] == \
                hop_sp["attrs"]["span_id"]
            assert by_tier["leader"]["attrs"]["tier_path"] == \
                "router>leader"
            # the propagation headers crossed the wire
            fwd = cap0.seen[-1]
            assert fwd.headers.get(tracing.PROP_TRACE_HEADER) == TID
            assert fwd.headers.get(tracing.PROP_TIER_PATH_HEADER) == \
                "router"
            # the leader recorded serving-stage spans inside the trace
            stage_names = {sp["name"]
                           for sp in by_tier["leader"]["spans"]}
            assert "serving.authn" in stage_names

        asyncio.run(go())

    def test_fleet_merged_view_reconciles(self, tmp):
        router, _s0, _s1, _c0, _c1 = make_router(tmp)

        async def go():
            r1 = await router_req(
                router, "GET", "/api/v1/namespaces/team-a",
                headers=[(tracing.TRACE_ID_HEADER, TID)])
            assert r1.status == 200
            r2 = await router_req(
                router, "POST", "/api/v1/namespaces/team-a/pods",
                body={"apiVersion": "v1", "kind": "Pod",
                      "metadata": {"name": "p1", "namespace": "team-a"}},
                headers=[(tracing.TRACE_ID_HEADER, TID2)])
            assert r2.status in (200, 201), r2.body

            resp = await router_req(router, "GET", "/debug/fleet")
            assert resp.status == 200
            merged = json.loads(resp.body)
            assert merged["enabled"] and merged["tier"] == "router"
            traces = {t["trace_id"]: t for t in merged["traces"]}
            assert {TID, TID2} <= set(traces)
            for t in (traces[TID], traces[TID2]):
                assert t["tier_count"] >= 2
                assert {"router", "leader"} <= set(t["tiers"])
                assert not t["aligned_by_wall"]
                # per-tier self + network reconciles against the
                # client-observed (router) duration by construction
                assert t["attributed_ms"] == pytest.approx(
                    t["duration_ms"], abs=0.05)
                assert "authn" in t["serving_stages_ms"].get(
                    "leader", {})
            ct = merged["chrome_trace"]
            assert ct["otherData"]["tracks"] >= 2
            assert any(e["ph"] == "X" for e in ct["traceEvents"])
            assert "router" in merged["tiers"]
            assert "leader" in merged["tiers"]

        asyncio.run(go())

    def test_fleet_requires_identity(self, tmp):
        router, _s0, _s1, _c0, _c1 = make_router(tmp)

        async def go():
            resp = await router_req(router, "GET", "/debug/fleet",
                                    user="")
            assert resp.status == 401
            resp = await router_req(router, "GET", "/debug/traces",
                                    user="")
            assert resp.status == 401

        asyncio.run(go())

    def test_gate_off_no_headers_and_byte_identical(self, tmp):
        router, _s0, _s1, cap0, _c1 = make_router(tmp)

        async def go():
            on = await router_req(router, "GET",
                                  "/api/v1/namespaces/team-a")
            assert on.status == 200
            assert cap0.seen[-1].headers.get(
                tracing.PROP_TRACE_HEADER)

            GATES.set("Timeline", False)
            tracing.RECORDER.drain()
            off = await router_req(router, "GET",
                                   "/api/v1/namespaces/team-a")
            assert off.status == 200
            # tripwire: the router ATTACHED no fleet headers of its own
            fwd = cap0.seen[-1]
            assert not fwd.headers.get(tracing.PROP_TRACE_HEADER)
            assert not fwd.headers.get(tracing.PROP_PARENT_HEADER)
            assert not fwd.headers.get(tracing.PROP_TIER_PATH_HEADER)

            # a client-injected propagation header passes through the
            # gate-off router VERBATIM (transparent proxy), but the
            # receiving side never reads it: it mints locally and no
            # tier attribution leaks into the trace
            tracing.RECORDER.drain()
            off2 = await router_req(
                router, "GET", "/api/v1/namespaces/team-a",
                headers=[(tracing.PROP_TRACE_HEADER, TID),
                         (tracing.PROP_PARENT_HEADER, HOP_A),
                         (tracing.PROP_TIER_PATH_HEADER, "router")])
            assert off2.status == 200
            assert cap0.seen[-1].headers.get(
                tracing.PROP_TRACE_HEADER) == TID  # untouched bytes
            assert segments_for(TID) == []
            for t in tracing.RECORDER.snapshot():
                assert "tier" not in t["attrs"]
            # the response BYTES are identical to the gate-on run
            assert off.body == on.body
            # the echoed trace id is the LEADER's locally-minted one
            # (X-Trace-Id echo predates fleet tracing), not the
            # injected fleet id
            assert off2.headers.get(tracing.TRACE_ID_HEADER) != TID

        asyncio.run(go())


# -- real processes: follower -> leader forwards ------------------------------


class LeaderLink:
    def __init__(self, proxy):
        self.proxy = proxy

    async def round_trip(self, req):
        return await self.proxy.handler(req)


def make_leader(tmp):
    kube = FakeKubeApiServer()
    for i in range(4):
        kube.seed("", "v1", "namespaces",
                  {"metadata": {"name": f"ns{i}"}})
    leader = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        data_dir=os.path.join(tmp, "leader"), wal_fsync="never"))
    leader.endpoint.store.bulk_load(
        [parse_relationship(f"namespace:ns{i}#creator@user:alice")
         for i in range(4)]
        + [parse_relationship("podns:ns0#creator@user:alice")])
    return leader, kube


def make_follower(leader, kube, **opt_kw):
    return ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        replicate_from="http://leader.test",
        leader_transport=LeaderLink(leader), **opt_kw))


class TestFollowerContinuity:
    def test_forwarded_dual_write_joins_trace_and_audit(self, tmp):
        leader, kube = make_leader(tmp)
        follower = make_follower(leader, kube)

        async def go():
            await follower.replication.sync_once()
            leader.enable_dual_writes()
            client = follower.get_embedded_client("alice")
            resp = await client.post(
                "/api/v1/namespaces/ns0/pods",
                {"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "p1", "namespace": "ns0"}},
                headers=[(tracing.TRACE_ID_HEADER, TID)])
            assert resp.status in (200, 201), resp.body
            assert resp.headers.get("X-Authz-Forwarded-To") == "leader"

            segs = segments_for(TID)
            by_tier = {t["attrs"].get("tier"): t for t in segs}
            assert set(by_tier) == {"follower", "leader"}
            assert by_tier["follower"]["attrs"]["tier_path"] == \
                "follower"
            assert by_tier["leader"]["attrs"]["tier_path"] == \
                "follower>leader"
            hop_sp = next(sp for sp in by_tier["follower"]["spans"]
                          if sp["name"] == "hop.forward_to_leader")
            assert by_tier["leader"]["attrs"]["parent_span"] == \
                hop_sp["attrs"]["span_id"]
            # audit provenance: the LEADER's decision events name the
            # full hop chain of the forwarded dual-write
            forwarded = [e for e in leader.audit.recent()
                         if e.get("tier_path") == "follower>leader"]
            assert forwarded, leader.audit.recent()
            assert any(e["trace_id"] == TID for e in forwarded)

        asyncio.run(go())

    def test_min_revision_forward_joins_trace(self, tmp):
        leader, kube = make_leader(tmp)
        follower = make_follower(leader, kube, replica_wait_ms=30.0)

        async def go():
            await follower.replication.sync_once()
            rev = await leader.endpoint.write_relationships([
                RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                    "namespace:ns1#viewer@user:zed"))])
            client = follower.get_embedded_client("zed")
            resp = await client.get(
                "/api/v1/namespaces",
                headers=[(MIN_REVISION_HEADER, str(rev)),
                         (tracing.TRACE_ID_HEADER, TID)])
            assert resp.status == 200, resp.body
            assert resp.headers.get("X-Authz-Forwarded-To") == "leader"
            by_tier = {t["attrs"].get("tier"): t
                       for t in segments_for(TID)}
            # the stale follower forwarded the read: same trace id on
            # both sides of the hop, leader as the child tier
            assert set(by_tier) == {"follower", "leader"}
            assert by_tier["leader"]["attrs"]["tier_path"] == \
                "follower>leader"

        asyncio.run(go())

    def test_follower_fleet_view_over_leader(self, tmp):
        leader, kube = make_leader(tmp)
        follower = make_follower(
            leader, kube,
            fleet_peers=["http://leader.test"],
            peer_transports={"http://leader.test": LeaderLink(leader)})

        async def go():
            await follower.replication.sync_once()
            leader.enable_dual_writes()
            client = follower.get_embedded_client("alice")
            resp = await client.post(
                "/api/v1/namespaces/ns0/pods",
                {"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "p2", "namespace": "ns0"}},
                headers=[(tracing.TRACE_ID_HEADER, TID)])
            assert resp.status in (200, 201), resp.body

            resp = await client.get("/debug/fleet")
            assert resp.status == 200
            merged = json.loads(resp.body)
            assert merged["enabled"] and merged["tier"] == "follower"
            traces = {t["trace_id"]: t for t in merged["traces"]}
            assert TID in traces
            t = traces[TID]
            assert {"follower", "leader"} <= set(t["tiers"])
            assert t["attributed_ms"] == pytest.approx(
                t["duration_ms"], abs=0.05)
            # the member scrape lifts the leader's clock-skew gauge
            # slot (None here: a leader exports no skew)
            by_url = {m["url"]: m for m in merged["members"]}
            assert by_url["http://leader.test"]["error"] is None
            assert by_url["http://leader.test"]["traces"] >= 1

        asyncio.run(go())
