"""Replication fault tolerance: leader failover, incarnation fencing,
follower fan-out trees (docs/replication.md "Failover runbook").

Three cooperating pieces, all behind the `Replication` gate:

- **Promotion** (`promote_follower`): a bootstrapped follower becomes
  the leader — it mints a promotion incarnation epoch (strictly
  dominating any later resurrection of the dead leader, see
  leader.mint_promotion_incarnation), attaches a fresh
  PersistenceManager over its `--promote-data-dir` (journaling every
  commit from here on), anchors an initial checkpoint at the adopted
  revision so the rest of the fleet (and the rejoining ex-leader) can
  bootstrap, and starts serving `/replication/*` as the new log.
  Promotion adopts exactly the follower's applied revision — the
  highest *durably shipped* revision — never guessing at writes that
  may or may not have survived on the dead leader's disk.

- **Demotion + rejoin** (`demote_and_rejoin`): a resurrected ex-leader
  that learns of a newer incarnation (via a follower's poll headers or
  a `FenceMonitor` peer probe) steps down instead of split-braining:
  it bounds its unshipped WAL tail using the new leader's `fenced`
  manifest marker (records past the revision the promotion adopted),
  re-bootstraps its live store from the new leader as an ordinary
  follower, and replays that tail through `/replication/rejoin` as
  forwarded writes — the PR 4 idempotency-key tuples make dual-write
  replays converge, and plain TOUCH/DELETE records re-apply
  idempotently.  Acknowledged writes are therefore never lost: either
  they shipped before the crash (the promotion adopted them) or they
  ride the rejoin replay.

- **Election** (`LeaderLossWatchdog`, `--promote-on-leader-loss`): each
  follower watches its own sync health; after `--leader-loss-grace`
  seconds without a successful pass it polls its `--replica-peers` for
  `/replication/status` and applies the decision rule *highest adopted
  revision wins, ties break on smallest replica id*.  The winner
  promotes itself; losers defer, then repoint to whoever shows up as a
  leader with a newer incarnation.  Unreachable peers simply don't
  vote — they are dead or on the wrong side of the partition.

`FanoutHub` is the fan-out tree piece: a follower running with
`--serve-replication` spools every artifact byte it applies into a
data-dir-shaped mirror (follower.py), and this hub serves that mirror
with the exact protocol the leader speaks — manifest long-poll included
— so N leaf followers chain off intermediates instead of NIC-saturating
one leader.  Incarnation and leader id pass through unchanged (it is
the leader's log), and the manifest's `chain` block accumulates hop
lags and the hub-id path down the tree.
"""

from __future__ import annotations

import asyncio
import logging
import os
import re
import time
from typing import Optional

from ...utils import metrics as m
from ...utils.failpoints import fail_point
from .follower import ReplicaFollower
from .leader import (
    ReplicationHub,
    mint_promotion_incarnation,
    serve_artifact_file,
)

logger = logging.getLogger("spicedb_kubeapi_proxy_tpu.replication")

_SEG_NAME = re.compile(r"^seg-(\d{8})\.wal$")
_SNAP_NAME = re.compile(r"^snap-\d{12}\.npz$")

# rejoin replay batch size (well under the store's per-write limit)
REJOIN_BATCH = 500


class PromotionError(Exception):
    """A promotion / demotion precondition failed; carries the HTTP
    status the server should answer with."""

    def __init__(self, message: str, status: int = 503):
        self.status = status
        super().__init__(message)


def _promotions() -> "m.Counter":
    return m.REGISTRY.counter(
        "authz_replication_promotions_total",
        "Leader-failover promotions completed by this process")


def _rejoin_records() -> "m.Counter":
    return m.REGISTRY.counter(
        "authz_replication_rejoin_records_total",
        "Unshipped WAL tail updates an ex-leader replayed into the new "
        "leader while rejoining as a follower")


async def _peer_json(transport, identity: str, method: str, target: str,
                     body: Optional[dict] = None) -> dict:
    """One authenticated JSON round trip to a peer proxy."""
    import json
    from ...proxy.httpcore import Headers, Request
    from ...utils import tracing
    h = Headers([("Accept", "application/json"),
                 ("X-Remote-User", identity)])
    # fleet tracing: election/fence/repoint control calls carry
    # provenance too (empty when the Timeline gate is off)
    for pk, pv in tracing.propagation_headers(
            default_tier="follower").items():
        h.set(pk, pv)
    data = b""
    if body is not None:
        data = json.dumps(body).encode()
        h.set("Content-Type", "application/json")
    resp = await transport.round_trip(Request(
        method=method, target=target, headers=h, body=data))
    if resp.status not in (200, 201):
        raise ConnectionError(
            f"{method} {target} -> HTTP {resp.status}: "
            f"{resp.body[:200]!r}")
    return json.loads(resp.body) if resp.body else {}


# -- promotion ---------------------------------------------------------------


async def promote_follower(server) -> dict:
    """Promote `server` (a bootstrapped follower) to leader.  Atomic
    from the caller's view: any failure inside the critical section
    rolls back to an intact follower (the tail task restarts if it was
    running).  Returns {leader_id, incarnation, revision,
    promoted_from}."""
    from . import enabled as replication_enabled
    if not replication_enabled():
        raise PromotionError("Replication feature gate is disabled", 503)
    async with server._promote_lock:
        repl = server.replication
        if repl is None:
            if server.replication_hub is not None:
                raise PromotionError("already the leader", 409)
            raise PromotionError(
                "not a replication follower (nothing to promote)", 503)
        if not repl.ever_bootstrapped:
            raise PromotionError(
                "no adopted state to promote (still bootstrapping)", 503)
        promote_dir = server.opts.promote_data_dir
        if not promote_dir:
            raise PromotionError(
                "--promote-data-dir is not configured on this follower",
                503)
        was_running = repl._task is not None and not repl._task.done()
        await repl.stop()
        fanout = server.fanout_hub
        if fanout is not None:
            # retire the fan-out relay NOW: its mirror belongs to the
            # superseded upstream log, and parked downstream long-polls
            # must wake immediately — their next poll reaches the
            # successor hub (or a clean 503 during the build) instead of
            # stalling a full poll window on a stopped tail
            server.fanout_hub = None
            fanout.close()
        loop = asyncio.get_running_loop()
        store = repl.store
        old_id = repl.max_leader_id or repl._boot_leader_id or repl.leader_id
        # the fencing marker the new manifest carries: which log this
        # incarnation superseded, and the exact revision the promotion
        # adopted (the highest durably SHIPPED revision) — a rejoining
        # ex-leader bounds its unshipped-tail replay at this revision
        fenced = {"leader_id": old_id,
                  "incarnation": repl.max_incarnation,
                  "revision": store.revision}
        persistence = None
        hub = None
        try:
            def _build():
                import shutil
                from ..persist import PersistenceManager
                from ..persist import checkpoint as ckpt
                # wipe artifacts from any OLDER promotion of this node
                # (they belong to a superseded incarnation); the
                # INCARNATION file stays — it is the epoch source
                os.makedirs(promote_dir, exist_ok=True)
                for sub in ("wal", ckpt.CHECKPOINT_DIR):
                    shutil.rmtree(os.path.join(promote_dir, sub),
                                  ignore_errors=True)
                try:
                    os.unlink(os.path.join(promote_dir, ckpt.MANIFEST_NAME))
                except OSError:
                    pass
                epoch = mint_promotion_incarnation(
                    promote_dir, repl.max_incarnation, fenced)
                p = PersistenceManager(
                    promote_dir, fsync=server.opts.wal_fsync,
                    checkpoint_interval=server.opts.checkpoint_interval)
                return epoch, p

            epoch, persistence = await loop.run_in_executor(None, _build)
            persistence.attach(store)
            fail_point("replPromote")
            # anchor checkpoint at the adopted revision: new followers
            # and the rejoining ex-leader bootstrap from it immediately
            # (a revision-0 promotion skips it; followers then anchor
            # at revision 0 exactly as against a fresh leader)
            await loop.run_in_executor(None, persistence.checkpoint)
            hub = ReplicationHub(store, persistence, incarnation=epoch,
                                 fenced=fenced)
            hub.attach()
        except BaseException:
            # roll back to an intact follower: promotion either
            # completes or changes nothing
            if hub is not None:
                hub.detach()
            if persistence is not None:
                persistence.detach()
                persistence.wal.close()
            if fanout is not None:
                # restore the relay over the same mirror (cheap: a
                # fresh hub just re-registers the progress listener)
                server.fanout_hub = FanoutHub(repl, fanout.mirror_dir)
            if was_running:
                repl.start()
            raise
        server.replication_hub = hub
        server.persistence = persistence
        server.replication = None
        if server._http is not None:
            await persistence.start()
        _promotions().inc()
        logger.warning(
            "promoted to leader: incarnation %d at revision %d "
            "(superseding %s at shipped revision %d)",
            epoch, store.revision, old_id, fenced["revision"])
        return {"leader_id": hub.leader_id, "incarnation": epoch,
                "revision": store.revision, "promoted_from": old_id}


# -- demotion + rejoin -------------------------------------------------------


def collect_unshipped_tail(persistence, store, from_revision: int) -> tuple:
    """(updates, skipped, reclaimed_window): every acknowledged update
    past `from_revision` as [op, rel_string] pairs — the writes the dead
    leader acknowledged but never shipped.

    Normally the live WAL carries the whole stream.  But a pre-crash
    checkpoint may have RECLAIMED segments covering part of the window
    (wal.reclaim deletes sealed segments the checkpoint covers): the
    record stream for (from_revision, checkpoint_revision] is gone from
    disk.  The surviving EFFECTS are still in the recovered `store`, so
    in that case the export is every live tuple written after
    `from_revision` as a TOUCH (store.relationships_since) plus the
    DELETE records the remaining WAL tail still carries.  Deletes whose
    records fell inside the reclaimed window are unrecoverable as a
    stream — `reclaimed_window` is True so the caller logs the bound.
    Mass-change records (snapshot sidecar / delete_all) past the
    watermark cannot be replayed as forwarded writes and are counted in
    `skipped`."""
    from ..persist import checkpoint as ckpt
    man = ckpt.read_manifest(persistence.data_dir) or {}
    ckpt_rev = int(man.get("revision", 0) or 0)
    updates: list = []
    skipped = 0
    if ckpt_rev > from_revision:
        from ..types import parse_relationship
        since = store.relationships_since(from_revision)
        updates.extend(["t", rel.rel_string()] for rel in since)
        live_keys = {rel.key() for rel in since}
        deletes = []
        for rec in persistence.wal.replay():
            if int(rec["r"]) <= from_revision:
                continue
            kind = rec["k"]
            if kind == "d":
                for op, s in rec.get("u", ()):
                    if op != "d":
                        continue
                    # a delete later re-touched is live in the final
                    # state: exporting both (touch set + raw delete)
                    # would wrongly end deleted — final state wins
                    try:
                        if parse_relationship(s).key() in live_keys:
                            continue
                    except ValueError:
                        pass
                    deletes.append(["d", s])
            elif kind not in ("d", "b"):
                skipped += 1
        updates.extend(deletes)  # final-state touches, then tail deletes
        return updates, skipped, True
    for rec in persistence.wal.replay():
        if int(rec["r"]) <= from_revision:
            continue
        kind = rec["k"]
        if kind == "d":
            updates.extend([op, s] for op, s in rec.get("u", ()))
        elif kind == "b":
            updates.extend(["t", s] for s in rec.get("u", ()))
        else:
            skipped += 1
    return updates, skipped, False


async def demote_and_rejoin(server, leader_url: str, transport) -> dict:
    """Step a fenced (or about-to-be-fenced) ex-leader down into a
    follower of the proxy at `leader_url`, replaying its unshipped WAL
    tail through /replication/rejoin so no acknowledged write is lost.
    Returns {replayed, skipped_records, leader, incarnation}."""
    from . import enabled as replication_enabled
    if not replication_enabled():
        raise PromotionError("Replication feature gate is disabled", 503)
    hub = server.replication_hub
    if hub is None:
        raise PromotionError("not a leader (nothing to demote)", 409)
    identity = server.opts.replica_user
    man = await _peer_json(transport, identity, "GET",
                           "/replication/manifest")
    new_inc = int(man.get("incarnation", 0) or 0)
    if new_inc <= hub.incarnation and hub.fenced_by is None:
        raise PromotionError(
            f"refusing demotion: {leader_url} serves incarnation "
            f"{new_inc}, not newer than own {hub.incarnation}", 409)
    fen = man.get("fenced") or {}
    tail: list = []
    skipped = 0
    reclaimed = False
    # "the promotion superseded MY log": the new leader's fenced marker
    # names the hub id the promoting follower was tailing — any id in
    # this data dir's lineage, even across our own restarts (each mints
    # a fresh id)
    from .leader import leader_lineage
    lineage = set(leader_lineage(server.persistence.data_dir)
                  if server.persistence is not None else ())
    lineage.add(hub.leader_id)
    if fen.get("leader_id") in lineage:
        try:
            tail, skipped, reclaimed = \
                await asyncio.get_running_loop().run_in_executor(
                    None, collect_unshipped_tail, server.persistence,
                    hub.store, int(fen.get("revision", 0)))
            if reclaimed:
                logger.warning(
                    "a pre-crash checkpoint reclaimed WAL segments past "
                    "shipped revision %s: replaying the surviving "
                    "EFFECTS (%d touch/delete updates) instead of the "
                    "exact stream; deletes inside the reclaimed window "
                    "cannot be replayed", fen.get("revision"), len(tail))
        except Exception:
            logger.exception(
                "could not read the local WAL tail; rejoining without "
                "replay (writes past shipped revision %s may be lost)",
                fen.get("revision"))
    else:
        logger.warning(
            "new leader %s superseded %r, which is not in this data "
            "dir's lineage: cannot bound the unshipped tail, rejoining "
            "without replay", leader_url, fen.get("leader_id"))
    # step down: stop publishing, stop journaling (the old data dir
    # stays on disk as cold history of the superseded log)
    hub.detach()
    persistence = server.persistence
    if persistence is not None:
        await persistence.stop(final_checkpoint=False)
        server.persistence = None
    server.replication_hub = None
    follower = ReplicaFollower(
        hub.store, transport, identity=identity,
        replica_id=server.replica_id, upstream_url=leader_url)
    server.replication = follower
    server._leader_transport = transport
    server.opts.replicate_from = leader_url
    replayed = 0
    try:
        # bootstrap from the new leader (replica_reset works on the
        # non-empty store and fires the reset listeners: device graph /
        # decision cache rebuild from the adopted state)
        await follower.sync_once()
        for i in range(0, len(tail), REJOIN_BATCH):
            batch = tail[i:i + REJOIN_BATCH]
            for attempt in range(3):
                try:
                    resp = await _peer_json(
                        transport, identity, "POST", "/replication/rejoin",
                        body={"from_leader_id": hub.leader_id,
                              "from_incarnation": hub.incarnation,
                              "updates": batch})
                    replayed += int(resp.get("applied", 0))
                    break
                except asyncio.CancelledError:
                    raise
                except Exception:
                    if attempt == 2:
                        raise
                    await asyncio.sleep(0.2 * (attempt + 1))
        if replayed:
            _rejoin_records().inc(replayed)
            # pull our own replayed writes back through the tail
            await follower.sync_once()
    except BaseException:
        # the step-down is done and cannot be unwound (a fenced leader
        # must not resume writes): leave an ALIVE follower behind —
        # its tail task retries forever, so reads keep serving at
        # bounded staleness.  The unreplayed remainder is logged as
        # at-risk; the old data dir remains on disk as cold history.
        if server._http is not None:
            follower.start()
        logger.exception(
            "rejoin to %s interrupted after step-down: %d/%d tail "
            "update(s) replayed; the remainder is preserved in the old "
            "data dir only", leader_url, replayed, len(tail))
        raise
    if server._http is not None:
        follower.start()
    logger.warning(
        "demoted to follower of %s (incarnation %d): replayed %d "
        "unshipped update(s), %d mass-change record(s) skipped%s",
        leader_url, new_inc, replayed, skipped,
        " (checkpoint-reclaimed window: effects replay)" if reclaimed
        else "")
    return {"replayed": replayed, "skipped_records": skipped,
            "reclaimed_window": reclaimed,
            "leader": leader_url, "incarnation": new_inc}


# -- fan-out hub -------------------------------------------------------------


# gate-off = no hub exists (the server requires --serve-replication AND
# the Replication gate before constructing one)
class FanoutHub:  # noqa: A004(built behind gate)
    """Serves a follower's artifact mirror with the leader's protocol,
    so downstream followers chain off this intermediate."""

    def __init__(self, follower: ReplicaFollower, mirror_dir: str,
                 registry: Optional[m.Registry] = None):
        self.follower = follower
        self.mirror_dir = mirror_dir
        os.makedirs(os.path.join(mirror_dir, "wal"), exist_ok=True)
        from ..persist import checkpoint as ckpt
        os.makedirs(os.path.join(mirror_dir, ckpt.CHECKPOINT_DIR),
                    exist_ok=True)
        follower.mirror_dir = mirror_dir
        self.stats = {"manifest_serves": 0, "longpoll_waits": 0,
                      "segment_serves": 0, "checkpoint_serves": 0}
        self._waiters: list = []
        self._closed = False
        registry = registry or m.REGISTRY
        self._shipped = registry.counter(
            "authz_replication_shipped_bytes_total",
            "Bytes of WAL segments / sidecars / checkpoints served to "
            "replication followers, by artifact kind",
            labels=("kind",))
        follower.add_progress_listener(self._on_progress)

    def close(self) -> None:
        # retire: wake every parked long-poll NOW and refuse to re-park
        # (the while-loop would otherwise re-enqueue a waiter nothing
        # resolves) — downstream followers get their (stale) manifest
        # immediately, and their NEXT poll reaches the successor hub
        # instead of stalling a full poll timeout
        self._closed = True
        self.follower.remove_progress_listener(self._on_progress)
        self._on_progress()

    def _on_progress(self) -> None:
        # runs on the serving loop (follower sync path): resolve plainly
        waiters, self._waiters = self._waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    async def wait_for_revision(self, min_exclusive: int,
                                timeout_s: float) -> bool:
        deadline = time.monotonic() + max(0.0, timeout_s)
        loop = asyncio.get_running_loop()
        while (not self._closed
               and self.follower.store.revision <= min_exclusive):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            fut = loop.create_future()
            self._waiters.append(fut)
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return self.follower.store.revision > min_exclusive
            finally:
                if fut in self._waiters:
                    self._waiters.remove(fut)
        return self.follower.store.revision > min_exclusive

    def manifest(self) -> dict:
        from ..persist import checkpoint as ckpt
        f = self.follower
        wal_dir = os.path.join(self.mirror_dir, "wal")
        segments = []
        sidecars = []
        try:
            names = sorted(os.listdir(wal_dir))
        except OSError:
            names = []
        for name in names:
            mm = _SEG_NAME.match(name)
            if mm:
                seq = int(mm.group(1))
                try:
                    size = os.path.getsize(os.path.join(wal_dir, name))
                except OSError:
                    continue
                segments.append({
                    "name": name, "seq": seq, "size": size,
                    # the segment at the cursor may still grow as the
                    # upstream tail is consumed; everything below it is
                    # complete in the mirror
                    "sealed": seq < f._cursor_seq,
                })
            elif _SNAP_NAME.match(name):
                sidecars.append(name)
        chain_path = list(f.upstream_chain.get("path") or ())
        self.stats["manifest_serves"] += 1
        return {
            # the log is the LEADER's log: id and incarnation pass
            # through unchanged, so fencing decisions are identical at
            # every depth of the tree
            "leader_id": f.max_leader_id or f.leader_id,
            "incarnation": f.max_incarnation,
            "fenced": None,
            "revision": f.store.revision,
            "checkpoint": ckpt.read_manifest(self.mirror_dir),
            "segments": segments,
            "sidecars": sidecars,
            # chain lag is additive: this follower's lag gauges already
            # include the upstream's reported chain lag
            "chain": {"path": chain_path + [f.replica_id],
                      "lag_revisions": max(0.0, f.lag_revisions()),
                      "lag_seconds": max(0.0, f.lag_seconds())},
            # THIS hub's wall clock (not the root leader's): the skew a
            # chained follower estimates is per-hop, matching the
            # per-hop chain lag it inherits
            "server_time_unix": time.time(),
        }

    async def serve_manifest(self, req) -> "Response":
        from urllib.parse import parse_qs, urlsplit
        from ...proxy.httpcore import json_response
        params = parse_qs(urlsplit(req.target).query)
        wait_raw = (params.get("wait_revision") or [""])[0]
        if wait_raw:
            from .leader import DEFAULT_LONGPOLL_S, MAX_LONGPOLL_S
            try:
                wait_rev = int(wait_raw)
                timeout_ms = float(
                    (params.get("timeout_ms")
                     or [str(DEFAULT_LONGPOLL_S * 1e3)])[0])
            except ValueError:
                return json_response(400, {
                    "kind": "Status", "apiVersion": "v1", "metadata": {},
                    "status": "Failure", "code": 400,
                    "message": "wait_revision/timeout_ms must be integers"})
            self.stats["longpoll_waits"] += 1
            await self.wait_for_revision(
                wait_rev, min(max(timeout_ms / 1e3, 0.0), MAX_LONGPOLL_S))
        return json_response(200, self.manifest())

    async def serve_segment(self, req, name: str) -> "Response":
        from ...proxy.httpcore import json_response
        from .leader import safe_artifact_name
        if not safe_artifact_name(name) or name.startswith("ckpt-"):
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400,
                "message": f"invalid segment name {name!r}"})
        return await serve_artifact_file(
            req, os.path.join(self.mirror_dir, "wal", name), "segment",
            self._shipped, self.stats)

    async def serve_checkpoint(self, req, name: str) -> "Response":
        from ...proxy.httpcore import json_response
        from ..persist import checkpoint as ckpt
        from .leader import safe_artifact_name
        if not safe_artifact_name(name) or not name.startswith("ckpt-"):
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400,
                "message": f"invalid checkpoint name {name!r}"})
        return await serve_artifact_file(
            req,
            os.path.join(self.mirror_dir, ckpt.CHECKPOINT_DIR, name),
            "checkpoint", self._shipped, self.stats)

    def snapshot(self) -> dict:
        return {"serves_replication": True,
                "mirror_dir": self.mirror_dir,
                "longpoll_waiters": len(self._waiters),
                **self.stats}


# -- leader-loss watchdog (follower side) ------------------------------------


class LeaderLossWatchdog:
    """`--promote-on-leader-loss`: detect a dead upstream and run the
    election (highest adopted revision wins; ties break on the smallest
    replica id)."""

    def __init__(self, server, grace_s: float = 5.0,
                 interval_s: float = 0.0):
        self.server = server
        self.grace_s = grace_s
        self.interval_s = interval_s or max(0.05, grace_s / 4.0)
        self.stats = {"checks": 0, "elections": 0, "deferrals": 0,
                      "repoints": 0, "promotions": 0}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                if await self.check_once() == "promoted":
                    return  # now the leader: nothing left to watch
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("leader-loss watchdog pass failed")

    async def check_once(self) -> str:
        repl = self.server.replication
        if repl is None:
            return "promoted"
        self.stats["checks"] += 1
        if repl.seconds_since_success() < self.grace_s:
            return "healthy"
        # stale success is NOT loss by itself: an idle tail parks in a
        # manifest long-poll for tens of seconds.  Confirm with a
        # direct bounded probe — only an unreachable, hung, or fenced
        # upstream turns into an election.
        try:
            await asyncio.wait_for(repl.probe_upstream(),
                                   max(0.25, min(self.grace_s, 2.0)))
            self.stats["probes_ok"] = self.stats.get("probes_ok", 0) + 1
            return "healthy"
        except asyncio.CancelledError:
            raise
        except Exception:
            pass
        return await self.run_election()

    async def run_election(self) -> str:
        server = self.server
        repl = server.replication
        self.stats["elections"] += 1
        statuses = []
        for url, tr in server.peer_transports().items():
            try:
                st = await _peer_json(tr, server.opts.replica_user,
                                      "GET", "/replication/status")
            except Exception:
                continue  # dead or across the partition: no vote
            statuses.append((url, st))
        # someone already won a newer incarnation: adopt them
        for url, st in statuses:
            if (st.get("role") == "leader"
                    and int(st.get("incarnation", 0) or 0)
                    > repl.max_incarnation
                    and st.get("fenced_by") is None):
                server.repoint_leader(url)
                self.stats["repoints"] += 1
                logger.warning("leader loss: repointed to promoted peer "
                               "%s", url)
                return "repointed"
        mine = (-repl.store.revision, repl.replica_id)
        for url, st in statuses:
            if st.get("role") != "follower":
                continue
            cand = (-int(st.get("revision", 0) or 0),
                    str(st.get("replica_id") or url))
            if cand < mine:
                # a better candidate exists (higher revision, or equal
                # revision and smaller id): let it promote, repoint on
                # a later pass when it shows up as leader
                self.stats["deferrals"] += 1
                return "deferred"
        await promote_follower(server)
        self.stats["promotions"] += 1
        return "promoted"


# -- fence monitor (leader side) --------------------------------------------


class FenceMonitor:
    """Leader-side peer probe: a (possibly resurrected) leader checks
    its peers for a newer incarnation — at startup BEFORE the listener
    opens, then periodically — and demotes itself into a follower of
    the new leader instead of split-braining.  Header-exchange fencing
    (ReplicationHub.observe_poll_headers) feeds the same `fenced_by`
    state, so a follower's stray poll fences a stale leader even
    between probe ticks; the server refuses update verbs the moment
    `fenced_by` is set, independent of this monitor."""

    def __init__(self, server, interval_s: float = 2.0):
        self.server = server
        self.interval_s = interval_s
        self.stats = {"probes": 0, "demotions": 0}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                if await self.check_once() in ("demoted", "not_leader"):
                    return
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("fence monitor pass failed")

    async def check_once(self) -> str:
        server = self.server
        hub = server.replication_hub
        if hub is None:
            return "not_leader"
        self.stats["probes"] += 1
        statuses = []
        for url, tr in server.peer_transports().items():
            try:
                st = await _peer_json(tr, server.opts.replica_user,
                                      "GET", "/replication/status")
            except Exception:
                continue
            statuses.append((url, tr, st))
            inc = int(st.get("incarnation", 0) or 0)
            lid = st.get("leader_id", "") or ""
            # epoch ties break on the LARGER leader id ((incarnation,
            # leader_id) total order): of two simultaneously-promoted
            # leaders exactly one fences, never both
            if inc > hub.incarnation or (
                    inc == hub.incarnation and lid
                    and lid > hub.leader_id
                    and st.get("role") == "leader"):
                hub.note_fenced(inc, lid)
        if hub.fenced_by is None:
            return "leading"
        want = hub.fenced_by["incarnation"]
        for url, tr, st in statuses:
            if (st.get("role") == "leader"
                    and int(st.get("incarnation", 0) or 0) >= want
                    and st.get("fenced_by") is None):
                await demote_and_rejoin(server, url, tr)
                self.stats["demotions"] += 1
                return "demoted"
        # fenced but the new leader is not among our peers (or not yet
        # reachable): update verbs stay refused, keep probing
        return "fenced"
