"""Dispatch timeline profiler internals (utils/timeline.py): ring-bound
eviction under churn, chrome-trace schema validity (Perfetto contract),
overlap-ratio math on synthetic interleavings, derived telemetry
(stalls, bandwidth, roofline, worst dispatch), the kernel-span hook,
zero-allocation behavior behind the Timeline gate, and the end-to-end
jax:// pipeline emitting every stage."""

import asyncio
import threading

import pytest

from spicedb_kubeapi_proxy_tpu.utils import metrics as m
from spicedb_kubeapi_proxy_tpu.utils import timeline, tracing
from spicedb_kubeapi_proxy_tpu.utils.features import GATES
from spicedb_kubeapi_proxy_tpu.utils.timeline import (
    Timeline,
    TimelineEvent,
    overlap_stats,
)


def make_timeline(**kw):
    """Isolated instance: fresh registry so metric registration never
    collides with the module singleton's."""
    kw.setdefault("registry", m.Registry())
    return Timeline(**kw)


def ev(stage, start, end, batch=None, track="device", nbytes=0):
    return TimelineEvent(stage, track, start, end, 0, batch, None,
                         nbytes, None)


# -- overlap-ratio math on synthetic interleavings ----------------------------


class TestOverlapMath:
    def test_no_events_is_none(self):
        assert overlap_stats([]) is None

    def test_no_transfer_time_is_none(self):
        assert overlap_stats([ev("kernel", 0.0, 1.0, batch=1)]) is None

    def test_partial_overlap(self):
        # transfer of batch 1 spans [0, 10]; batch 2's kernel covers
        # [2, 6] of it -> 4/10
        st = overlap_stats([ev("transfer", 0.0, 10.0, batch=1),
                            ev("kernel", 2.0, 6.0, batch=2)])
        assert st["ratio"] == pytest.approx(0.4)
        assert st["transfer_s"] == pytest.approx(10.0)
        assert st["overlap_s"] == pytest.approx(4.0)

    def test_same_batch_kernel_is_serialization_not_overlap(self):
        st = overlap_stats([ev("transfer", 0.0, 10.0, batch=1),
                            ev("kernel", 0.0, 10.0, batch=1)])
        assert st["ratio"] == 0.0

    def test_overlapping_kernels_not_double_counted(self):
        # kernels [2,6] and [4,8] union to [2,8] -> 6/10, not 8/10
        st = overlap_stats([ev("transfer", 0.0, 10.0, batch=1),
                            ev("kernel", 2.0, 6.0, batch=2),
                            ev("kernel", 4.0, 8.0, batch=3)])
        assert st["ratio"] == pytest.approx(0.6)

    def test_transpose_counts_as_transfer_side(self):
        st = overlap_stats([ev("transpose", 0.0, 4.0, batch=1),
                            ev("kernel", 0.0, 4.0, batch=2)])
        assert st["ratio"] == pytest.approx(1.0)

    def test_perfect_double_buffer_scores_one(self):
        # batch N's transfer fully hidden behind batch N+1's kernel
        events = []
        for n in range(4):
            t0 = float(n)
            events.append(ev("kernel", t0, t0 + 0.8, batch=n))
            events.append(ev("transfer", t0 + 1.0, t0 + 1.5, batch=n))
        # shift kernels to cover the previous batch's transfer window
        events += [ev("kernel", n + 1.0, n + 1.8, batch=n + 1)
                   for n in range(4)]
        st = overlap_stats(events)
        assert st["ratio"] == pytest.approx(1.0)


# -- ring bounds under churn --------------------------------------------------


class TestRingBounds:
    def test_eviction_under_threaded_churn(self):
        tl = make_timeline(capacity=64)
        errors = []

        def writer(i):
            try:
                for k in range(200):
                    t0 = timeline.now()
                    tl.record("pack", "host", t0, t0 + 1e-6,
                              batch=i * 1000 + k, nbytes=64)
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        def reader():
            try:
                for _ in range(50):
                    tl.summary()
                    tl.chrome_trace()
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)

        threads = ([threading.Thread(target=writer, args=(i,))
                    for i in range(8)]
                   + [threading.Thread(target=reader) for _ in range(2)])
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(tl.events()) == 64  # bounded: oldest evicted
        assert tl.snapshot()["events_total"] == 8 * 200
        # the retained events are the NEWEST per writer
        batches = sorted(e.batch for e in tl.events())
        assert batches[0] >= 100  # every writer's early events evicted

    def test_since_filter(self):
        tl = make_timeline(capacity=16)
        tl.record("pack", "host", 1.0, 2.0)
        tl.record("pack", "host", 10.0, 11.0)
        assert len(tl.events(since=5.0)) == 1
        assert len(tl.events()) == 2


# -- chrome-trace schema ------------------------------------------------------


def assert_valid_chrome_trace(trace):
    """Every event has ph/ts/pid/tid; X events carry dur; B/E pairs
    balance per (pid, tid).  Independent hand-kept copy of
    scripts/devtel_smoke.py's validator (that script's module level
    sets env vars and imports jax, so it must not be imported here);
    schema changes must land in both."""
    assert isinstance(trace["traceEvents"], list)
    depth = {}
    for e in trace["traceEvents"]:
        for field in ("ph", "ts", "pid", "tid"):
            assert field in e, f"event missing {field}: {e}"
        if e["ph"] == "X":
            assert "dur" in e and e["dur"] >= 0
        elif e["ph"] == "B":
            depth[(e["pid"], e["tid"])] = (
                depth.get((e["pid"], e["tid"]), 0) + 1)
        elif e["ph"] == "E":
            key = (e["pid"], e["tid"])
            depth[key] = depth.get(key, 0) - 1
            assert depth[key] >= 0, "E without open B"
    assert not any(depth.values()), f"unbalanced B/E: {depth}"


class TestChromeTrace:
    def test_schema_and_tracks(self):
        tl = make_timeline(capacity=32)
        b = tl.next_batch()
        t0 = timeline.now()
        tl.record("pack", "host", t0, t0 + 0.001, batch=b, bucket=64,
                  nbytes=256)
        tl.record("kernel", "device", t0, t0 + 0.01, batch=b, bucket=64,
                  nbytes=1 << 20)
        tl.record("rebuild", "rebuild", t0, t0 + 0.5, nbytes=1 << 24)
        tl.record("fused", "dispatcher", t0, t0 + 0.02, bucket=8)
        trace = tl.chrome_trace()
        assert_valid_chrome_trace(trace)
        import json
        json.dumps(trace)  # JSON-serializable end to end
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        assert {"pack", "kernel", "rebuild", "fused"} <= names
        # named tracks: metadata rows for host/dispatcher/device/rebuild
        threads = {e["args"]["name"] for e in events
                   if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"host", "dispatcher", "device", "rebuild"} <= threads
        # rebuild exports as a B/E pair, pipeline stages as X
        phs = {e["name"]: e["ph"] for e in events if e["ph"] != "M"}
        assert phs["rebuild"] == "E"  # last rebuild record is the E
        assert phs["pack"] == "X"
        # args carry the correlation ids
        kernel = next(e for e in events
                      if e["ph"] == "X" and e["name"] == "kernel")
        assert kernel["args"]["batch"] == b
        assert kernel["args"]["bucket"] == 64
        assert kernel["args"]["bytes"] == 1 << 20

    def test_summary_rides_other_data(self):
        tl = make_timeline(capacity=8)
        tl.record("pack", "host", 0.0, 1.0)
        od = tl.chrome_trace()["otherData"]
        assert od["summary"]["events"] == 1
        assert od["capacity"] == 8


# -- derived telemetry --------------------------------------------------------


class TestDerivedTelemetry:
    def test_stall_attribution_and_counters(self):
        reg = m.Registry()
        tl = make_timeline(capacity=32, registry=reg)
        tl.record("pack", "host", 0.0, 0.5)
        tl.record("transpose", "device", 0.0, 0.25)
        tl.record("rebuild", "rebuild", 0.0, 2.0)
        tl.record("compact", "rebuild", 0.0, 1.0)   # rebuild-family
        tl.record("warm_start", "rebuild", 0.0, 4.0)
        tl.record("compile", "device", 0.0, 0.125)
        tl.record("kernel", "device", 0.0, 9.0)     # NOT a stall
        s = tl.summary()
        assert s["stall_s"]["pack"] == pytest.approx(0.5)
        assert s["stall_s"]["transpose"] == pytest.approx(0.25)
        assert s["stall_s"]["rebuild"] == pytest.approx(7.0)
        assert s["stall_s"]["compile"] == pytest.approx(0.125)
        assert "kernel" not in s["stall_s"]
        c = reg.get("authz_dispatch_stall_seconds")
        assert c.value(cause="rebuild") == pytest.approx(7.0)
        assert c.value(cause="pack") == pytest.approx(0.5)

    def test_bandwidth_and_roofline(self):
        tl = make_timeline(capacity=32, hbm_peak_gbps=1.0)  # 1 GB/s peak
        # 0.5 GB moved in 1s on the kernel stage -> 0.5 of peak
        tl.record("kernel", "device", 0.0, 1.0, batch=1,
                  nbytes=500_000_000)
        s = tl.summary()
        assert s["bandwidth_bytes_per_s"]["kernel"] == pytest.approx(5e8)
        assert s["roofline_fraction"] == pytest.approx(0.5)
        assert s["hbm_peak_gbps"] == pytest.approx(1.0)

    def test_roofline_none_without_peak(self):
        tl = make_timeline(capacity=8)
        tl._hbm_peak_detected = 0.0  # force "unknown platform"
        tl.record("kernel", "device", 0.0, 1.0, nbytes=1000)
        assert tl.summary()["roofline_fraction"] is None

    def test_no_platform_detection_before_any_device_event(self):
        # summary()/scrapes on a jax-less server must never trigger
        # platform detection (jax import + jax.devices() would stall
        # the event loop on backend init); detection arms only once a
        # device-track event proves the backend is already up
        tl = make_timeline(capacity=8)
        tl.record("pack", "host", 0.0, 1.0, nbytes=64)  # host-only load
        assert tl.hbm_peak_bytes_per_s() == 0.0
        assert tl._hbm_peak_detected is None  # detection never ran
        tl.summary()
        tl.chrome_trace()
        assert tl._hbm_peak_detected is None
        tl.record("kernel", "device", 0.0, 1.0, nbytes=64)
        tl.hbm_peak_bytes_per_s()
        assert tl._hbm_peak_detected is not None  # armed by the event

    def test_worst_dispatch_exemplar(self):
        tl = make_timeline(capacity=32)
        tl.record("pack", "host", 0.0, 0.1, batch=1)
        tl.record("kernel", "device", 0.1, 0.2, batch=1)
        tl.record("pack", "host", 0.0, 0.1, batch=2)
        tl.record("kernel", "device", 0.1, 3.0, batch=2)  # the slow one
        w = tl.summary()["worst_dispatch"]
        assert w["batch"] == 2
        assert w["stages_ms"]["kernel"] == pytest.approx(2900.0)
        assert w["total_ms"] == pytest.approx(3000.0)

    def test_time_first_call_records_one_compile(self):
        tl = make_timeline(capacity=8)
        calls = []
        wrapped = tl.time_first_call(lambda x: calls.append(x) or x + 1,
                                     bucket=64)
        assert wrapped(1) == 2 and wrapped(2) == 3 and wrapped(3) == 4
        compiles = [e for e in tl.events() if e.stage == "compile"]
        assert len(compiles) == 1
        assert compiles[0].bucket == 64
        assert calls == [1, 2, 3]

    def test_time_first_call_per_static_key(self):
        # jit static_argnums: every NEW static prefix recompiles and
        # must record its own compile slice (a lookup kernel compiles
        # per (slot_offset, slot_length), not just once ever)
        tl = make_timeline(capacity=16)
        wrapped = tl.time_first_call(lambda off, ln, x: x, static_args=2)
        for off, ln in ((0, 10), (0, 10), (5, 20), (0, 10), (5, 20),
                        (7, 3)):
            wrapped(off, ln, "q")
        compiles = [e for e in tl.events() if e.stage == "compile"]
        assert len(compiles) == 3  # (0,10), (5,20), (7,3)

    def test_compile_contaminated_kernel_excluded_from_roofline(self):
        # the first execution of a fresh bucket compiles INSIDE the
        # kernel span: that kernel event is tagged and must not feed
        # bandwidth/roofline with a compile-inflated duration
        tl = make_timeline(capacity=16, hbm_peak_gbps=1.0)
        t0 = 100.0
        tl.record("compile", "device", t0 + 0.1, t0 + 5.0)
        # kernel window [t0, t0+6] contains the compile slice
        tl.record("kernel", "device", t0, t0 + 6.0, batch=1,
                  nbytes=1_000_000)
        evs = tl.events()
        contaminated = [e for e in evs if e.stage == "kernel"]
        assert contaminated[0].attrs.get("compile") is True
        s = tl.summary()
        assert "kernel" not in s["bandwidth_bytes_per_s"]
        assert s["roofline_fraction"] is None
        # a clean kernel event afterwards feeds them again
        tl.record("kernel", "device", t0 + 10.0, t0 + 11.0, batch=2,
                  nbytes=500_000_000)
        s = tl.summary()
        assert s["bandwidth_bytes_per_s"]["kernel"] == pytest.approx(5e8)
        assert s["roofline_fraction"] == pytest.approx(0.5)

    def test_rebuild_bytes_are_not_a_bandwidth(self):
        reg = m.Registry()
        tl = make_timeline(capacity=8, registry=reg)
        tl.record("rebuild", "rebuild", 0.0, 2.0, nbytes=1 << 30)
        assert "rebuild" not in tl.summary()["bandwidth_bytes_per_s"]
        g = reg.get("authz_dispatch_bandwidth_bytes_per_sec")
        assert 'stage="rebuild"' not in "\n".join(g.render())


# -- the tracing.kernel_span hook --------------------------------------------


class TestKernelSpanHook:
    def test_kernel_span_lands_on_device_track(self):
        mark = timeline.now()
        with tracing.kernel_span("kernel.device", kind="check",
                                 bucket=64) as a:
            a["batch_id"] = 424242
            a["nbytes"] = 4096
        evs = [e for e in timeline.TIMELINE.events(since=mark)
               if e.batch == 424242]
        assert len(evs) == 1
        assert evs[0].stage == "kernel" and evs[0].track == "device"
        assert evs[0].nbytes == 4096 and evs[0].bucket == 64

    def test_timeline_stage_override(self):
        mark = timeline.now()
        with tracing.kernel_span("kernel.transfer", kind="lookup") as a:
            a["timeline_stage"] = "transpose"
            a["batch_id"] = 434343
        evs = [e for e in timeline.TIMELINE.events(since=mark)
               if e.batch == 434343]
        assert [e.stage for e in evs] == ["transpose"]

    def test_unmapped_kernel_span_is_ignored(self):
        mark = timeline.now()
        with tracing.kernel_span("kernel.oracle", kind="check"):
            pass
        assert [e for e in timeline.TIMELINE.events(since=mark)
                if e.stage == "kernel.oracle"] == []


# -- gate off: zero allocation ------------------------------------------------


class TestGateOff:
    def test_gated_off_records_nothing_and_allocates_no_spans(self):
        tl = make_timeline(capacity=8)
        tl.record("pack", "host", 0.0, 1.0)
        GATES.set("Timeline", False)
        try:
            before = tl.snapshot()
            n = len(tl.events())
            for _ in range(100):
                tl.record("pack", "host", 0.0, 1.0, nbytes=1 << 20)
            # span() hands back ONE shared null context: no per-call
            # event/generator allocation while gated off
            s1 = tl.span("pack", "host")
            s2 = tl.span("kernel", "device", nbytes=5)
            assert s1 is s2
            with s1 as attrs:
                attrs2 = attrs
            assert attrs2 == {}
            assert len(tl.events()) == n
            assert tl.snapshot() == before
        finally:
            GATES.set("Timeline", True)
        # back on: recording resumes
        tl.record("pack", "host", 0.0, 1.0)
        assert len(tl.events()) == n + 1

    def test_gated_off_chrome_trace_still_valid(self):
        tl = make_timeline(capacity=8)
        GATES.set("Timeline", False)
        try:
            assert_valid_chrome_trace(tl.chrome_trace())
        finally:
            GATES.set("Timeline", True)


# -- end to end: the jax:// pipeline emits every stage ------------------------


SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""


class TestEndpointPipeline:
    def test_lookup_and_check_emit_pipeline_stages(self):
        from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
            Bootstrap, create_endpoint)
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            CheckRequest, ObjectRef, SubjectRef, parse_relationship)

        ep = create_endpoint("jax://?dispatch=direct",
                             Bootstrap(schema_text=SCHEMA))
        ep.store.bulk_load([parse_relationship(f"doc:d{i}#viewer@user:u1")
                            for i in range(8)])
        mark = timeline.now()

        async def go():
            await ep.check_bulk_permissions([CheckRequest(
                ObjectRef("doc", "d0"), "view", SubjectRef("user", "u1"))])
            return await ep.lookup_resources_batch(
                "doc", "view", [SubjectRef("user", "u1"),
                                SubjectRef("user", "u2")])

        results = asyncio.run(go())
        assert sorted(results[0]) == [f"d{i}" for i in range(8)]
        evs = timeline.TIMELINE.events(since=mark)
        stages = {e.stage for e in evs}
        # host pack + device kernel + host extract on both verbs; the
        # packed lookup's result movement shows as transfer/transpose;
        # the fresh graph's first kernel calls record compile slices;
        # the initial graph build records a rebuild-track span
        assert {"pack", "kernel", "extract", "compile"} <= stages
        assert stages & {"transfer", "transpose"}
        assert stages & {"rebuild", "compact"}
        # fused-batch ids correlate one dispatch's slices across tracks
        kernel_batches = {e.batch for e in evs if e.stage == "kernel"}
        pack_batches = {e.batch for e in evs if e.stage == "pack"}
        assert kernel_batches and kernel_batches <= pack_batches
        # and the whole thing renders as a loadable chrome trace
        assert_valid_chrome_trace(timeline.chrome_trace(since=mark))
        s = timeline.summary(since=mark)
        assert s["events"] == len(evs)
        assert s["worst_dispatch"] is not None
        assert "pack" in s["stall_s"]


# -- flight-recorder evidence links ------------------------------------------


class TestFlightEvidenceLinks:
    def test_window_embeds_slow_traces_and_timeline(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.utils import devtel

        # isolated recorder: the global one retains the 32 SLOWEST
        # traces of the whole suite run, which would starve this test's
        # microsecond trace out of the exemplar heap
        monkeypatch.setattr(tracing, "RECORDER",
                            tracing.SlowTraceRecorder(capacity=8))
        fr = devtel.FlightRecorder(window_s=0.05, capacity=4,
                                   registry=m.REGISTRY)
        tr = tracing.Trace(op="evidence")
        tr.finish()
        tracing.RECORDER.record(tr)
        timeline.record("pack", "host", timeline.now() - 0.001)
        snap = fr.capture()
        assert any(x["trace_id"] == tr.trace_id
                   for x in snap["slow_traces"])
        assert snap["timeline"] is not None
        assert snap["timeline"]["events"] >= 1
        # the internal SLO tallies stay private; the evidence links are
        # served at /debug/flight
        served = fr.snapshots()[0]
        assert "slow_traces" in served and "timeline" in served

    def test_window_timeline_none_when_gate_off(self):
        from spicedb_kubeapi_proxy_tpu.utils import devtel

        fr = devtel.FlightRecorder(window_s=0.05, capacity=4,
                                   registry=m.REGISTRY)
        GATES.set("Timeline", False)
        try:
            snap = fr.capture()
            assert snap["timeline"] is None
        finally:
            GATES.set("Timeline", True)

    def test_exemplars_filter_by_start(self):
        rec = tracing.SlowTraceRecorder(capacity=8)
        t_old = tracing.Trace()
        t_old.wall_start -= 1000.0  # started long ago
        t_old.finish()
        rec.record(t_old)
        t_new = tracing.Trace()
        t_new.finish()
        rec.record(t_new)
        import time
        recent = rec.exemplars(k=5, since_unix=time.time() - 60)
        assert [x["trace_id"] for x in recent] == [t_new.trace_id]
        assert len(rec.exemplars(k=5)) == 2
        assert len(rec.exemplars(k=1)) == 1
