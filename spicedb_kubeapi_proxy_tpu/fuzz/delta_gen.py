"""Random delta-stream generator + the shared fake clock.

A delta stream is a JSON-serializable list of BURSTS; each burst is one
atomic store operation:

    {"kind": "write",   "ops": [{"op": "touch"|"delete", "rel": "..."}]}
    {"kind": "dbf",     "resource_type": t, "relation": r, "resource_id": i}
    {"kind": "bulk",    "rels": ["...", ...]}
    {"kind": "advance", "dt": seconds}

Relationships serialize as `rel_string()` and round-trip through
`parse_relationship`, so a repro artifact is a plain-text description
of the exact store history.

Time is FAKE: every store in a fuzz run shares one `FakeClock`, and
the only way it moves is an explicit `advance` burst — so short-TTL
expiring tuples (the PAuth ephemeral-grant shape) are deterministic:
a tuple expiring 5 fake-seconds out is live until the stream says
otherwise, on the leader and on every replica, in the kernels, the
decision cache, and the oracle alike.

Pathological shapes generated on purpose:

- wildcard flips: `user:*` TOUCHed then DELETEd (graph rebuild paths);
- plane-less caveats: the first caveated tuple on a pair whose graph
  was built caveat-free (quarantine/rebuild under AsyncRebuild);
- already-expired writes (lazy expiry-heap delete path) and short-TTL
  writes crossed by later `advance` bursts (heap + cache invalidation);
- brand-new object ids (spare-pool assignment path);
- delete_by_filter wiping a whole (type, relation) slice;
- mid-stream bulk loads (reset listeners; replica re-bootstrap).
"""

from __future__ import annotations

import random

from ..spicedb import schema as sch
from ..spicedb.types import (
    CaveatRef,
    ObjectRef,
    Relationship,
    SubjectRef,
)

EPOCH = 1_700_000_000.0  # fuzz time zero (arbitrary, stable)


class FakeClock:
    """Deterministic time source shared by every store in a fuzz run."""

    def __init__(self, t0: float = EPOCH):
        self.t = float(t0)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class DeltaBias:
    """Stream-shape knobs the scenario profiles turn."""

    def __init__(self, delete=0.3, new_id=0.15, short_ttl=0.25,
                 expired=0.2, wildcard_boost=1.0, caveat_boost=1.0,
                 dbf=0.12, bulk=0.08, advance=0.22):
        self.delete = delete
        self.new_id = new_id
        self.short_ttl = short_ttl
        self.expired = expired
        self.wildcard_boost = wildcard_boost
        self.caveat_boost = caveat_boost
        self.dbf = dbf
        self.bulk = bulk
        self.advance = advance


DEFAULT_DELTA_BIAS = DeltaBias()


def id_universe(schema: sch.Schema, rng: random.Random) -> dict:
    """type -> list of object ids (small, so closures entangle)."""
    out = {}
    for tname in schema.definitions:
        n = rng.randint(3, 8)
        out[tname] = [f"{tname[:2]}{i}" for i in range(n)]
    return out


def _pick_id(rng: random.Random, ids: dict, tname: str,
             new_id_rate: float) -> str:
    pool = ids.get(tname, [tname[:2] + "0"])
    if rng.random() < new_id_rate:
        return f"{tname[:2]}{rng.randrange(10 * len(pool))}x"
    return rng.choice(pool)


def _caveat_context(rng: random.Random, caveat: sch.Caveat) -> dict:
    """Decided-true / decided-false / undecidable / empty contexts."""
    roll = rng.random()
    params = [name for name, _ in caveat.params]
    if roll < 0.25:
        return {}  # fully context-dependent (undecidable)
    ctx = {name: rng.randrange(6) for name in params}
    if roll < 0.5 and len(params) > 1:
        del ctx[rng.choice(params)]  # partially missing (undecidable)
    return ctx


def random_tuple(schema: sch.Schema, rng: random.Random, clock: FakeClock,
                 ids: dict, bias: DeltaBias) -> Relationship:
    """One schema-valid relationship, biased toward the nasty shapes."""
    # weighted (definition, relation, ref) choice: wildcard / caveated /
    # expiring annotations get their boost here
    choices = []
    for tname, d in schema.definitions.items():
        if tname == "user":
            continue
        for rname, refs in d.relations.items():
            for ref in refs:
                w = 1.0
                if ref.wildcard:
                    w *= 1.5 * bias.wildcard_boost
                if any(t != "expiration" for t in ref.traits):
                    w *= 1.5 * bias.caveat_boost
                if "expiration" in ref.traits:
                    w *= 1.3
                choices.append((w, tname, rname, ref))
    total = sum(c[0] for c in choices)
    x = rng.random() * total
    for w, tname, rname, ref in choices:
        x -= w
        if x <= 0:
            break
    resource = ObjectRef(tname, _pick_id(rng, ids, tname, bias.new_id))
    if ref.wildcard:
        subject = SubjectRef(ref.type, "*")
    elif ref.relation:
        subject = SubjectRef(ref.type, _pick_id(rng, ids, ref.type, 0.0),
                             ref.relation)
    else:
        subject = SubjectRef(ref.type,
                             _pick_id(rng, ids, ref.type, bias.new_id))
    caveat = None
    expires_at = None
    for trait in ref.traits:
        if trait == "expiration":
            roll = rng.random()
            if roll < bias.expired:
                expires_at = clock.now() - 3600.0  # already expired
            elif roll < bias.expired + bias.short_ttl:
                expires_at = clock.now() + rng.randint(3, 25)  # short TTL
            else:
                expires_at = clock.now() + 86400.0
        else:
            caveat = CaveatRef.make(
                trait, _caveat_context(rng, schema.caveats[trait]))
    return Relationship(resource=resource, relation=rname, subject=subject,
                        expires_at=expires_at, caveat=caveat)


def initial_rels(schema: sch.Schema, rng: random.Random, clock: FakeClock,
                 ids: dict, bias: DeltaBias, n: int) -> list:
    """Seed tuples: no brand-new ids (the pool path is for the stream)."""
    seed_bias = DeltaBias(new_id=0.0, short_ttl=bias.short_ttl,
                          expired=bias.expired,
                          wildcard_boost=bias.wildcard_boost,
                          caveat_boost=bias.caveat_boost)
    rels = {}
    for _ in range(n):
        rel = random_tuple(schema, rng, clock, ids, seed_bias)
        rels[rel.rel_string()] = rel
    return sorted(rels)


def generate_bursts(schema: sch.Schema, rng: random.Random,
                    clock: FakeClock, ids: dict, bias: DeltaBias,
                    n_bursts: int) -> list:
    """The delta stream (list of serialized bursts).  Clock is advanced
    HERE as the stream is generated so TTLs embed the right instants;
    replay re-applies the same advances in order."""
    bursts = []
    for _ in range(n_bursts):
        roll = rng.random()
        if roll < bias.advance:
            dt = rng.choice((1.0, 5.0, 12.0, 40.0, 3600.0))
            clock.advance(dt)
            bursts.append({"kind": "advance", "dt": dt})
        elif roll < bias.advance + bias.dbf:
            tname = rng.choice([t for t in schema.definitions
                                if t != "user"])
            d = schema.definitions[tname]
            relation = (rng.choice(sorted(d.relations))
                        if d.relations and rng.random() < 0.7 else "")
            rid = (_pick_id(rng, ids, tname, 0.0)
                   if rng.random() < 0.5 else "")
            bursts.append({"kind": "dbf", "resource_type": tname,
                           "relation": relation, "resource_id": rid})
        elif roll < bias.advance + bias.dbf + bias.bulk:
            rels = initial_rels(schema, rng, clock, ids, bias,
                                rng.randint(3, 10))
            bursts.append({"kind": "bulk", "rels": rels})
        else:
            ops = []
            for _ in range(rng.randint(1, 6)):
                rel = random_tuple(schema, rng, clock, ids, bias)
                if rng.random() < bias.delete:
                    # deletes key on identity: strip caveat/expiry attrs
                    ops.append({"op": "delete",
                                "rel": rel.rel_string().split("[")[0]})
                else:
                    ops.append({"op": "touch", "rel": rel.rel_string()})
            bursts.append({"kind": "write", "ops": ops})
    return bursts
