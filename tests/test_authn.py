"""Authenticator tests: front-proxy (request-header) CA trust and OIDC
static-JWKS bearer validation (VERDICT r2 item 5; reference
pkg/proxy/authn.go:17-53,121-153).

The critical property: a spoofed `X-Remote-User` header with no verified
front-proxy certificate — or one signed by the WRONG CA — authenticates as
nobody.
"""

import base64
import datetime
import json
import time

import pytest

# collection must degrade gracefully where cryptography is absent (the
# module is a dev requirement, requirements-dev.txt): skip, don't error
pytest.importorskip(
    "cryptography",
    reason="cryptography not installed (see requirements-dev.txt)")
from cryptography import x509  # noqa: E402
from cryptography.hazmat.primitives import hashes, serialization  # noqa: E402
from cryptography.hazmat.primitives.asymmetric import ec, rsa  # noqa: E402
from cryptography.x509.oid import NameOID  # noqa: E402

from spicedb_kubeapi_proxy_tpu.proxy.authn import (
    AuthenticatorChain,
    OIDCAuthenticator,
    RequestHeaderAuthenticator)
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import Headers, Request


# -- cert fixtures ------------------------------------------------------------

def make_ca(cn: str):
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    return key, cert


def issue_client_cert(ca_key, ca_cert, cn: str, not_after_minutes=60):
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, cn)]))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(
                minutes=abs(not_after_minutes) + 60))
            .not_valid_after(now + datetime.timedelta(
                minutes=not_after_minutes))
            .sign(ca_key, hashes.SHA256()))
    return cert.public_bytes(serialization.Encoding.DER)


@pytest.fixture(scope="module")
def front_proxy_pki(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("pki")
    ca_key, ca_cert = make_ca("front-proxy-ca")
    ca_path = tmp / "front-proxy-ca.pem"
    ca_path.write_bytes(ca_cert.public_bytes(serialization.Encoding.PEM))
    rogue_key, rogue_cert = make_ca("rogue-ca")
    return {
        "ca_path": str(ca_path),
        "good_der": issue_client_cert(ca_key, ca_cert, "front-proxy-client"),
        "wrong_cn_der": issue_client_cert(ca_key, ca_cert, "impostor"),
        "rogue_der": issue_client_cert(rogue_key, rogue_cert,
                                       "front-proxy-client"),
        "expired_der": issue_client_cert(ca_key, ca_cert,
                                         "front-proxy-client",
                                         not_after_minutes=-10),
    }


def req_with(der=None, user="alice", groups=(), extra=()):
    headers = Headers()
    if user:
        headers.add("X-Remote-User", user)
    for g in groups:
        headers.add("X-Remote-Group", g)
    for k, v in extra:
        headers.add(k, v)
    return Request(method="GET", target="/api/v1/pods", headers=headers,
                   peer_cert_der=der)


class TestRequestHeaderAuthenticator:
    def test_verified_front_proxy_trusted(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(
            front_proxy_pki["ca_path"],
            allowed_names=("front-proxy-client",))
        user = a.authenticate(req_with(
            front_proxy_pki["good_der"], groups=["admins", "devs"],
            extra=[("X-Remote-Extra-Scopes", "view")]))
        assert user is not None
        assert user.name == "alice"
        assert user.groups == ["admins", "devs"]
        assert user.extra == {"scopes": ["view"]}

    def test_spoofed_header_without_cert_rejected(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(front_proxy_pki["ca_path"])
        assert a.authenticate(req_with(None, user="system:admin")) is None

    def test_cert_from_wrong_ca_rejected(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(front_proxy_pki["ca_path"])
        # signed by a rogue CA with the RIGHT CN — must still fail
        assert a.authenticate(req_with(
            front_proxy_pki["rogue_der"], user="system:admin")) is None

    def test_cn_not_in_allowed_names_rejected(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(
            front_proxy_pki["ca_path"],
            allowed_names=("front-proxy-client",))
        assert a.authenticate(req_with(
            front_proxy_pki["wrong_cn_der"])) is None

    def test_any_cn_ok_when_no_allowed_names(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(front_proxy_pki["ca_path"])
        assert a.authenticate(req_with(
            front_proxy_pki["wrong_cn_der"])).name == "alice"

    def test_expired_cert_rejected(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(front_proxy_pki["ca_path"])
        assert a.authenticate(req_with(
            front_proxy_pki["expired_der"])) is None

    def test_garbage_der_rejected(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(front_proxy_pki["ca_path"])
        assert a.authenticate(req_with(b"\x30\x03notacert")) is None

    def test_no_username_header(self, front_proxy_pki):
        a = RequestHeaderAuthenticator(front_proxy_pki["ca_path"])
        assert a.authenticate(req_with(
            front_proxy_pki["good_der"], user="")) is None

    def test_chain_does_not_fall_through_to_plain_headers(
            self, front_proxy_pki):
        """Serving-mode chain must NOT contain the embedded-mode
        HeaderAuthenticator; with only requestheader configured, a spoofed
        header + no cert yields anonymous/nothing."""
        chain = AuthenticatorChain([RequestHeaderAuthenticator(
            front_proxy_pki["ca_path"])])
        assert chain.authenticate(req_with(None, user="root")) is None


# -- OIDC ---------------------------------------------------------------------

def b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def make_jwt(key, kid: str, alg: str, claims: dict,
             tamper: bool = False) -> str:
    header = {"alg": alg, "kid": kid, "typ": "JWT"}
    h = b64url(json.dumps(header).encode())
    p = b64url(json.dumps(claims).encode())
    signing_input = f"{h}.{p}".encode()
    if alg == "RS256":
        from cryptography.hazmat.primitives.asymmetric import padding
        sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    else:  # ES256: raw r||s
        from cryptography.hazmat.primitives.asymmetric.utils import (
            decode_dss_signature,
        )
        der = key.sign(signing_input, ec.ECDSA(hashes.SHA256()))
        r, s = decode_dss_signature(der)
        sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    if tamper:
        p = b64url(json.dumps({**claims, "sub": "evil"}).encode())
    return f"{h}.{p}.{b64url(sig)}"


def jwk_of(key, kid: str) -> dict:
    pub = key.public_key()
    if isinstance(key, rsa.RSAPrivateKey):
        nums = pub.public_numbers()
        byte_len = (nums.n.bit_length() + 7) // 8
        return {"kty": "RSA", "kid": kid, "alg": "RS256",
                "n": b64url(nums.n.to_bytes(byte_len, "big")),
                "e": b64url(nums.e.to_bytes(3, "big"))}
    nums = pub.public_numbers()
    return {"kty": "EC", "crv": "P-256", "kid": kid, "alg": "ES256",
            "x": b64url(nums.x.to_bytes(32, "big")),
            "y": b64url(nums.y.to_bytes(32, "big"))}


ISSUER = "https://issuer.test"
CLIENT_ID = "kube-proxy"


@pytest.fixture(scope="module")
def oidc(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("oidc")
    rsa_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    ec_key = ec.generate_private_key(ec.SECP256R1())
    rogue = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    jwks_path = tmp / "jwks.json"
    jwks_path.write_text(json.dumps({
        "keys": [jwk_of(rsa_key, "rsa1"), jwk_of(ec_key, "ec1")]}))
    auth = OIDCAuthenticator(ISSUER, CLIENT_ID, str(jwks_path))
    return {"auth": auth, "rsa": rsa_key, "ec": ec_key, "rogue": rogue}


def bearer_req(token: str) -> Request:
    h = Headers()
    h.add("Authorization", f"Bearer {token}")
    return Request(method="GET", target="/api/v1/pods", headers=h)


def good_claims(**over):
    now = time.time()
    claims = {"iss": ISSUER, "aud": CLIENT_ID, "sub": "alice",
              "groups": ["devs"], "exp": now + 300, "nbf": now - 60}
    claims.update(over)
    return claims


class TestOIDCAuthenticator:
    @pytest.mark.parametrize("keyname,kid,alg", [
        ("rsa", "rsa1", "RS256"), ("ec", "ec1", "ES256")])
    def test_valid_token(self, oidc, keyname, kid, alg):
        tok = make_jwt(oidc[keyname], kid, alg, good_claims())
        user = oidc["auth"].authenticate(bearer_req(tok))
        assert user is not None and user.name == "alice"
        assert user.groups == ["devs"]

    def test_aud_as_list(self, oidc):
        tok = make_jwt(oidc["rsa"], "rsa1", "RS256",
                       good_claims(aud=["other", CLIENT_ID]))
        assert oidc["auth"].authenticate(bearer_req(tok)).name == "alice"

    def test_rogue_key_rejected(self, oidc):
        tok = make_jwt(oidc["rogue"], "rsa1", "RS256", good_claims())
        assert oidc["auth"].authenticate(bearer_req(tok)) is None

    def test_tampered_payload_rejected(self, oidc):
        tok = make_jwt(oidc["rsa"], "rsa1", "RS256", good_claims(),
                       tamper=True)
        assert oidc["auth"].authenticate(bearer_req(tok)) is None

    @pytest.mark.parametrize("bad", [
        {"iss": "https://evil.test"},
        {"aud": "someone-else"},
        {"exp": time.time() - 3600},
        {"nbf": time.time() + 3600},
        {"sub": ""},
    ])
    def test_bad_claims_rejected(self, oidc, bad):
        tok = make_jwt(oidc["rsa"], "rsa1", "RS256", good_claims(**bad))
        assert oidc["auth"].authenticate(bearer_req(tok)) is None

    def test_alg_none_rejected(self, oidc):
        h = b64url(json.dumps({"alg": "none"}).encode())
        p = b64url(json.dumps(good_claims()).encode())
        assert oidc["auth"].authenticate(bearer_req(f"{h}.{p}.")) is None

    def test_alg_confusion_rejected(self, oidc):
        """An RS256 kid must not verify an ES256-signed blob and vice
        versa (kty is matched to the declared alg)."""
        tok = make_jwt(oidc["ec"], "rsa1", "ES256", good_claims())
        # kid points at the RSA key; kty mismatch -> no candidates
        user = oidc["auth"].authenticate(bearer_req(tok))
        assert user is None

    def test_malformed_tokens(self, oidc):
        for tok in ("", "a.b", "a.b.c.d", "!!!.???.###",
                    "Zm9v.YmFy.YmF6"):
            assert oidc["auth"].authenticate(bearer_req(tok)) is None

    def test_groups_string_normalized(self, oidc):
        tok = make_jwt(oidc["rsa"], "rsa1", "RS256",
                       good_claims(groups="admins"))
        assert oidc["auth"].authenticate(bearer_req(tok)).groups == \
            ["admins"]

    def test_username_prefix_and_claim(self, oidc, tmp_path):
        jwks = tmp_path / "jwks.json"
        jwks.write_text(json.dumps({"keys": [jwk_of(oidc["rsa"], "rsa1")]}))
        a = OIDCAuthenticator(ISSUER, CLIENT_ID, str(jwks),
                              username_claim="email",
                              username_prefix="oidc:")
        tok = make_jwt(oidc["rsa"], "rsa1", "RS256",
                       good_claims(email="a@b.co"))
        assert a.authenticate(bearer_req(tok)).name == "oidc:a@b.co"

    def test_non_bearer_ignored(self, oidc):
        h = Headers()
        h.add("Authorization", "Basic dXNlcjpwYXNz")
        assert oidc["auth"].authenticate(
            Request(method="GET", target="/", headers=h)) is None


# -- front-proxy over real TLS end-to-end -------------------------------------

class TestFrontProxyTLSEndToEnd:
    """CLI flags -> ProxyServer over real TLS: a front proxy presenting its
    client certificate can set X-Remote-*; the same headers WITHOUT the
    certificate are 401 (this is the spoof the requestheader CA exists to
    stop)."""

    def test_requestheader_over_tls(self, tmp_path):
        import asyncio
        import ssl as ssl_mod

        from spicedb_kubeapi_proxy_tpu import cli
        from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
            H11Transport,
            Response,
            Transport,
        )
        from spicedb_kubeapi_proxy_tpu.proxy.server import ProxyServer

        ca_key, ca_cert = make_ca("front-proxy-ca")
        ca_path = tmp_path / "fp-ca.pem"
        ca_path.write_bytes(ca_cert.public_bytes(
            serialization.Encoding.PEM))
        # front-proxy leaf, PEM pair for the TLS client
        fp_key = ec.generate_private_key(ec.SECP256R1())
        now = datetime.datetime.now(datetime.timezone.utc)
        fp_cert = (x509.CertificateBuilder()
                   .subject_name(x509.Name([x509.NameAttribute(
                       NameOID.COMMON_NAME, "front-proxy-client")]))
                   .issuer_name(ca_cert.subject)
                   .public_key(fp_key.public_key())
                   .serial_number(x509.random_serial_number())
                   .not_valid_before(now - datetime.timedelta(minutes=5))
                   .not_valid_after(now + datetime.timedelta(hours=1))
                   .sign(ca_key, hashes.SHA256()))
        cert_pem = tmp_path / "fp.pem"
        cert_pem.write_bytes(fp_cert.public_bytes(
            serialization.Encoding.PEM))
        key_pem = tmp_path / "fp-key.pem"
        key_pem.write_bytes(fp_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption()))

        rules = tmp_path / "rules.yaml"
        rules.write_text("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
""")

        class Upstream(Transport):
            async def round_trip(self, req):
                return Response(status=200, body=json.dumps({
                    "kind": "Namespace", "apiVersion": "v1",
                    "metadata": {"name": "ns1"}}).encode())

        args = cli.build_parser().parse_args(cli._normalize_argv([
            "--rule-config", str(rules),
            "--cert-dir", str(tmp_path / "certs"),
            "--requestheader-client-ca-file", str(ca_path),
            "--requestheader-allowed-names", "front-proxy-client",
            "--use-in-cluster-config"]))
        completed = cli.complete(args, upstream_transport=Upstream())

        async def run():
            from spicedb_kubeapi_proxy_tpu.spicedb.types import (
                RelationshipUpdate,
                UpdateOp,
                parse_relationship,
            )
            server = ProxyServer(completed.server_options)
            await server.endpoint.write_relationships([RelationshipUpdate(
                op=UpdateOp.TOUCH,
                rel=parse_relationship("namespace:ns1#viewer@user:alice"))])
            port = await server.start("127.0.0.1", 0)
            try:
                def client_ctx(with_cert):
                    c = ssl_mod.create_default_context()
                    c.check_hostname = False
                    c.verify_mode = ssl_mod.CERT_NONE
                    if with_cert:
                        c.load_cert_chain(str(cert_pem), str(key_pem))
                    return c

                req = Request(
                    method="GET", target="/api/v1/namespaces/ns1",
                    headers=Headers([("X-Remote-User", "alice"),
                                     ("Accept", "application/json")]))
                with_cert = await H11Transport(
                    f"https://127.0.0.1:{port}",
                    ssl_context=client_ctx(True)).round_trip(req)
                spoofed = await H11Transport(
                    f"https://127.0.0.1:{port}",
                    ssl_context=client_ctx(False)).round_trip(req)
                return with_cert, spoofed
            finally:
                await server.stop()

        with_cert, spoofed = asyncio.run(run())
        assert with_cert.status == 200
        assert json.loads(with_cert.body)["metadata"]["name"] == "ns1"
        assert spoofed.status == 401
