"""Revision-keyed decision cache with relation-scoped invalidation.

Zanzibar-class deployments get their production throughput from
consistency-aware result caching layered over the evaluator: the hot path
is dominated by REPEATED identical queries (the same user re-listing the
same 10k pods), and the kernel — however fast — re-derives an identical
frontier every time.  This module caches two decision shapes in front of
any store-backed endpoint (`jax://`, `embedded://`):

- **LookupResources frontiers**: the allowed-object id list per
  (resource_type, permission, subject) — the warm repeat-list skips
  device dispatch entirely;
- **check verdicts**: the tri-state permissionship per
  (resource, permission, subject).

Consistency model (docs/performance.md "Decision cache"):

- Every entry records, at fill time, the **epoch** of each relation in
  the query's compiled footprint (`ops/graph_compile.relation_footprint`
  — the set of (type, relation) pairs whose tuples can influence the
  result).  A committed store delta bumps the epoch only of the
  relations it touches (the delta listener runs synchronously under the
  store lock, so no query can observe the new store state before the
  epochs reflect it).
- A hit is served only when every footprint epoch is unchanged — in that
  case no tuple that could change the result has been written since the
  fill, so the cached result IS the fully-consistent result at the
  current revision.  Entries whose footprint epochs are unchanged stay
  valid across unrelated writes instead of being flushed wholesale.
- Mass changes (bulk_load / delete_all) and schema-independent events
  bump a global epoch: everything invalidates.
- Tuples with expirations invalidate without a delta: the cache keeps an
  expiry heap ((expires_at, relation)) fed from deltas and — lazily,
  after a reset — from `TupleStore.expiry_schedule()`, and advances it
  against the STORE clock before every probe/fill.
- The fill-time epoch snapshot is captured BEFORE the inner evaluation
  starts, so a write racing the evaluation can only make the new entry
  immediately invalid (a wasted fill), never silently stale.

Bounded: LRU over a bytes-accounted OrderedDict (`max_bytes`,
`max_entries`); evictions and resident bytes are exported as
`authz_decision_cache_*` metrics with bounded labels (M001-clean).

`?explain=1` witnesses bypass the cache entirely (explain_check is a
pass-through), exactly like they bypass the fused dispatch queue — an
explain must re-derive the decision, not quote a cache line.
"""

from __future__ import annotations

import heapq
import threading
from collections import OrderedDict
from typing import Iterable

from ..ops.graph_compile import relation_footprint
from ..utils import tracing, workload
from .endpoints import PermissionsEndpoint
from .store import Watcher
from .types import (
    AnnotatedIds,
    CheckRequest,
    CheckResult,
    Precondition,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
    WatchUpdate,
)

SOURCE_CACHE = "cache"

DEFAULT_MAX_BYTES = 128 << 20  # 128 MiB of cached frontiers
DEFAULT_MAX_ENTRIES = 65536

_MISS = object()


class _Entry:
    __slots__ = ("value", "global_epoch", "epochs", "nbytes")

    def __init__(self, value, global_epoch: int, epochs: tuple, nbytes: int):
        self.value = value
        self.global_epoch = global_epoch
        self.epochs = epochs  # ((relkey, epoch), ...)
        self.nbytes = nbytes


def _ids_nbytes(ids: list) -> int:
    """Approximate resident cost of a cached frontier: id characters plus
    per-element list overhead plus a fixed entry header."""
    return 96 + 8 * len(ids) + sum(len(s) for s in ids)


class DecisionCache:
    """Bounded bytes-accounted LRU keyed by query, validated by relation
    epochs.  Thread-safe: probes/fills run from executor threads and the
    event loop; epoch bumps run from writer threads under the store lock.
    """

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 max_entries: int = DEFAULT_MAX_ENTRIES):
        if max_bytes < 1 or max_entries < 1:
            raise ValueError("decision cache bounds must be >= 1")
        self.max_bytes = max_bytes
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._epochs: dict = {}  # (type, relation) -> int
        self._global_epoch = 0
        self._bytes = 0
        self._expiry_heap: list = []  # (expires_at, relkey)
        self.stats = {"hits": 0, "misses": 0, "invalidations": 0,
                      "evictions": 0, "fills": 0}

    # -- epoch plumbing (called under the store lock: must stay cheap) -------

    def bump(self, relkeys: Iterable[tuple]) -> None:
        with self._lock:
            for rk in relkeys:
                self._epochs[rk] = self._epochs.get(rk, 0) + 1

    def bump_all(self) -> None:
        """Wholesale invalidation (bulk_load / delete_all / rebuild-class
        events): one global epoch bump; resident entries are dropped
        eagerly so their bytes release immediately."""
        with self._lock:
            self._global_epoch += 1
            self.stats["invalidations"] += len(self._entries)
            self._entries.clear()
            self._bytes = 0

    def schedule_expiry(self, expires_at: float, relkey: tuple) -> None:
        with self._lock:
            heapq.heappush(self._expiry_heap, (expires_at, relkey))

    def _advance_expiry_locked(self, now: float) -> None:
        heap = self._expiry_heap
        while heap and heap[0][0] <= now:
            _, rk = heapq.heappop(heap)
            self._epochs[rk] = self._epochs.get(rk, 0) + 1

    # -- probe / fill --------------------------------------------------------

    def snapshot_epochs(self, footprint: frozenset, now: float) -> tuple:
        """Validation token for a fill: (global_epoch, ((relkey, epoch)...))
        captured BEFORE the inner evaluation reads the store, so a write
        racing the evaluation invalidates the resulting entry instead of
        being silently absorbed into it."""
        with self._lock:
            self._advance_expiry_locked(now)
            return (self._global_epoch,
                    tuple((rk, self._epochs.get(rk, 0))
                          for rk in sorted(footprint)))

    def get(self, key: tuple, now: float):
        with self._lock:
            self._advance_expiry_locked(now)
            e = self._entries.get(key)
            if e is None:
                self.stats["misses"] += 1
                return _MISS
            if (e.global_epoch != self._global_epoch
                    or any(self._epochs.get(rk, 0) != v
                           for rk, v in e.epochs)):
                del self._entries[key]
                self._bytes -= e.nbytes
                self.stats["invalidations"] += 1
                self.stats["misses"] += 1
                return _MISS
            self._entries.move_to_end(key)
            self.stats["hits"] += 1
            return e.value

    def put(self, key: tuple, value, token: tuple, nbytes: int) -> None:
        global_epoch, epochs = token
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _Entry(value, global_epoch, epochs, nbytes)
            self._bytes += nbytes
            self.stats["fills"] += 1
            while (self._entries and
                   (self._bytes > self.max_bytes
                    or len(self._entries) > self.max_entries)):
                _, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                self.stats["evictions"] += 1

    # -- introspection -------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def contains_valid(self, key: tuple) -> bool:
        """Non-LRU-touching, non-stat-counting validity probe (tests and
        introspection only)."""
        with self._lock:
            e = self._entries.get(key)
            return (e is not None
                    and e.global_epoch == self._global_epoch
                    and all(self._epochs.get(rk, 0) == v
                            for rk, v in e.epochs))


# gate-off = this wrapper is never constructed (create_endpoint checks
# the DecisionCache gate/flag), so its call sites need no re-check
class DecisionCacheEndpoint(PermissionsEndpoint):  # noqa: A004(built behind gate)
    """Decision-cache layer wrapping a store-backed endpoint (the wrapper
    sits ABOVE the cross-request dispatcher: a hit never enqueues, so a
    warm repeat-list skips device dispatch entirely; misses flow through
    the fused/singleflight path underneath and fill on return)."""

    decision_cache_enabled = True

    def __init__(self, inner: PermissionsEndpoint,
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 max_entries: int = DEFAULT_MAX_ENTRIES,
                 registry=None):
        self.inner = inner
        self.schema = inner.schema
        self.store = inner.store
        self.cache = DecisionCache(max_bytes=max_bytes,
                                   max_entries=max_entries)
        self._footprints: dict = {}  # (type, perm) -> frozenset
        # pre-existing bootstrap data may carry expirations the delta
        # listener never saw: seed the heap lazily, outside the store lock
        self._need_expiry_rescan = True
        self.store.add_delta_listener(self._on_delta)
        self.store.add_reset_listener(self._on_reset)
        if registry is None:
            from ..utils import metrics as m
            registry = m.REGISTRY
        self._hits = registry.counter(
            "authz_decision_cache_hits_total",
            "Decision-cache hits (served without touching the backend)",
            labels=("verb",))
        self._misses = registry.counter(
            "authz_decision_cache_misses_total",
            "Decision-cache misses (forwarded to the backend)",
            labels=("verb",))
        self._invalidations = registry.counter(
            "authz_decision_cache_invalidations_total",
            "Cached decisions dropped because a footprint relation epoch "
            "moved (writes, expirations, resets)")
        self._evictions = registry.counter(
            "authz_decision_cache_evictions_total",
            "Cached decisions evicted by the LRU bytes/entry bound")
        # weakref so the registry-held gauge callbacks never pin a
        # replaced/closed cache layer alive (same discipline as
        # InstrumentedEndpoint's backend-stat gauges)
        import weakref
        ref = weakref.ref(self.cache)
        registry.gauge(
            "authz_decision_cache_bytes",
            "Resident bytes of cached decisions",
            callback=lambda: float(getattr(ref(), "resident_bytes", 0) or 0))
        registry.gauge(
            "authz_decision_cache_entries",
            "Resident cached decisions",
            callback=lambda: float(len(ref() or ())))
        self._last_counts = dict(self.cache.stats)

    # -- store listeners (run under the store lock: no cache lock order
    # inversions — DecisionCache uses its own private lock only) ------------

    def _on_delta(self, update: WatchUpdate) -> None:
        relkeys = set()
        for u in update.updates:
            relkeys.add((u.rel.resource.type, u.rel.relation))
            if u.rel.expires_at is not None:
                self.cache.schedule_expiry(
                    u.rel.expires_at, (u.rel.resource.type, u.rel.relation))
        if relkeys:
            self.cache.bump(relkeys)

    def _on_reset(self) -> None:
        self.cache.bump_all()
        self._need_expiry_rescan = True

    def _maybe_rescan_expiry(self) -> None:
        if not self._need_expiry_rescan:
            return
        self._need_expiry_rescan = False
        for exp, relkey in self.store.expiry_schedule():
            self.cache.schedule_expiry(exp, relkey)

    # -- keys / footprints ---------------------------------------------------

    def _footprint(self, resource_type: str, permission: str) -> frozenset:
        fp = self._footprints.get((resource_type, permission))
        if fp is None:
            fp = relation_footprint(self.schema, resource_type, permission)
            self._footprints[(resource_type, permission)] = fp
        return fp

    def _sync_counters(self) -> None:
        """Mirror the cache's int counters into the Prometheus metrics
        (delta-based so concurrent syncs never double-count much; the
        ints remain the source of truth for tests)."""
        cur = dict(self.cache.stats)
        last, self._last_counts = self._last_counts, cur
        d = cur["invalidations"] - last.get("invalidations", 0)
        if d > 0:
            self._invalidations.inc(d)
        d = cur["evictions"] - last.get("evictions", 0)
        if d > 0:
            self._evictions.inc(d)

    # -- check verbs ---------------------------------------------------------

    async def check_permission(self, req: CheckRequest) -> CheckResult:
        return (await self.check_bulk_permissions([req]))[0]

    async def check_bulk_permissions(self, reqs: list) -> list:
        if not reqs:
            return []
        self._maybe_rescan_expiry()
        now = self.store.now()
        results: list = [None] * len(reqs)
        miss_rows: list = []
        tokens: dict = {}  # row -> (key, token)
        hits = 0
        pair_stats: dict = {}  # (type, permission) -> [hits, misses]
        with tracing.span("cache_lookup", phase=True, verb="check") as attrs:
            for i, r in enumerate(reqs):
                key = ("chk", r.resource.type, r.resource.id,
                       r.permission, r.subject)
                st = pair_stats.setdefault(
                    (r.resource.type, r.permission), [0, 0])
                cached = self.cache.get(key, now)
                if cached is not _MISS:
                    perm, at = cached
                    results[i] = CheckResult(permissionship=perm,
                                             checked_at=at,
                                             source=SOURCE_CACHE)
                    hits += 1
                    st[0] += 1
                    continue
                fp = self._footprint(r.resource.type, r.permission)
                tokens[i] = (key, self.cache.snapshot_epochs(fp, now))
                miss_rows.append(i)
                st[1] += 1
            attrs["hits"] = hits
            attrs["misses"] = len(miss_rows)
        for (rt, p), (h, ms) in pair_stats.items():
            workload.WORKLOAD.note_cache(rt, p, h, ms)
        if hits:
            self._hits.inc(hits, verb="check")
        if miss_rows:
            self._misses.inc(len(miss_rows), verb="check")
            inner_res = await self.inner.check_bulk_permissions(
                [reqs[i] for i in miss_rows])
            for i, res in zip(miss_rows, inner_res):
                key, token = tokens[i]
                self.cache.put(key, (res.permissionship, res.checked_at),
                               token, 128)
                results[i] = res
        self._sync_counters()
        return results

    # -- lookup verbs --------------------------------------------------------

    async def lookup_resources(self, resource_type: str, permission: str,
                               subject: SubjectRef) -> list:
        out = await self.lookup_resources_batch(resource_type, permission,
                                                [subject])
        return out[0]

    async def lookup_resources_batch(self, resource_type: str,
                                     permission: str, subjects: list) -> list:
        if not subjects:
            return []
        self._maybe_rescan_expiry()
        now = self.store.now()
        results: list = [None] * len(subjects)
        miss_rows: list = []
        tokens: dict = {}
        hits = 0
        with tracing.span("cache_lookup", phase=True, verb="lookup") as attrs:
            fp = self._footprint(resource_type, permission)
            for i, s in enumerate(subjects):
                key = ("lr", resource_type, permission, s)
                cached = self.cache.get(key, now)
                if cached is not _MISS:
                    results[i] = cached  # AnnotatedIds(source="cache")
                    hits += 1
                    continue
                tokens[i] = (key, self.cache.snapshot_epochs(fp, now))
                miss_rows.append(i)
            attrs["hits"] = hits
            attrs["misses"] = len(miss_rows)
        workload.WORKLOAD.note_cache(resource_type, permission, hits,
                                     len(miss_rows))
        if hits:
            self._hits.inc(hits, verb="lookup")
        if miss_rows:
            self._misses.inc(len(miss_rows), verb="lookup")
            if len(miss_rows) == 1:
                inner_res = [await self.inner.lookup_resources(
                    resource_type, permission, subjects[miss_rows[0]])]
            else:
                inner_res = await self.inner.lookup_resources_batch(
                    resource_type, permission,
                    [subjects[i] for i in miss_rows])
            for i, ids in zip(miss_rows, inner_res):
                key, token = tokens[i]
                # the stored value is a fresh AnnotatedIds pre-marked
                # "cache" so every future hit returns it without a copy;
                # THIS call returns the inner list with its true source
                self.cache.put(key, AnnotatedIds(ids, source=SOURCE_CACHE),
                               token, _ids_nbytes(ids))
                results[i] = ids
        self._sync_counters()
        return results

    # lookup_resources_stream is inherited from PermissionsEndpoint and
    # wraps self.lookup_resources, so streamed consumers (the prefilter)
    # hit the cache too.

    # -- passthrough verbs ---------------------------------------------------

    def explain_check(self, resource, permission, subject):
        """Witness capture bypasses the cache: an explain must re-derive
        the decision through the real evaluator path, not quote a cache
        line (same contract as the dispatch queue's explain bypass)."""
        fn = getattr(self.inner, "explain_check", None)
        if fn is not None:
            return fn(resource, permission, subject)
        from ..authz.explain import witness_for
        return witness_for(self.inner, resource, permission, subject)

    async def read_relationships(self, flt: RelationshipFilter) -> list:
        return await self.inner.read_relationships(flt)

    async def write_relationships(self, updates: Iterable[RelationshipUpdate],
                                  preconditions: Iterable[Precondition] = ()) -> int:
        return await self.inner.write_relationships(updates, preconditions)

    async def delete_relationships(self, flt: RelationshipFilter,
                                   preconditions: Iterable[Precondition] = ()) -> int:
        return await self.inner.delete_relationships(flt, preconditions)

    def watch(self, object_types=None) -> Watcher:
        return self.inner.watch(object_types)

    async def close(self) -> None:
        self.store.remove_delta_listener(self._on_delta)
        self.store.remove_reset_listener(self._on_reset)
        await self.inner.close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
