"""Replication correctness suite (ISSUE 9): WAL-shipping leader/follower
over in-process transports, fully deterministic (the follower's
`sync_once()` is the test-driven unit; `run()` just loops it).

Covers:
- leader/follower parity referee under write churn (every follower
  answer identical to the leader oracle at the request's pinned
  revision);
- torn/missing segment handling (follower re-bootstraps from the
  checkpoint instead of diverging);
- leader restart mid-tail;
- ZedToken wait-vs-forward paths (X-Authz-Min-Revision honored: wait,
  forward, or 503 — never a stale answer below min-revision);
- follower write rejection/forwarding;
- the Replication gate-off tripwire (single-node behavior exactly);
- frame-parser torn-tail tolerance (persist.wal.parse_frames).
"""

import asyncio
import json
import shutil
import tempfile

import pytest

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.persist.wal import (
    SEGMENT_MAGIC,
    TornFrameError,
    parse_frames,
)
from spicedb_kubeapi_proxy_tpu.spicedb.replication import (
    MIN_REVISION_HEADER,
    REVISION_HEADER,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils.features import GATES

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
definition pod {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources: {tpl: "namespace:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-pods}
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
lock: Optimistic
check: [{tpl: "namespace:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"
"""

N_NS = 12


@pytest.fixture(autouse=True)
def reset_gates():
    yield
    GATES.reset()


class LeaderLink:
    """In-process leader transport resolving the proxy's CURRENT handler
    on every call (enable_dual_writes rebuilds the chain) and swappable
    to a new incarnation for the leader-restart tests."""

    def __init__(self, proxy):
        self.proxy = proxy

    async def round_trip(self, req):
        return await self.proxy.handler(req)

    def set_leader(self, proxy):
        self.proxy = proxy


def make_leader(tmp, seed_ns=True, **opt_kw):
    kube = FakeKubeApiServer()
    for i in range(N_NS):
        kube.seed("", "v1", "namespaces", {"metadata": {"name": f"ns{i}"}})
    leader = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        data_dir=tmp, wal_fsync="never", **opt_kw))
    if seed_ns:
        leader.endpoint.store.bulk_load([
            parse_relationship(f"namespace:ns{i}#creator@user:alice")
            for i in range(0, N_NS, 2)])
    return leader, kube


def make_follower(leader, kube=None, **opt_kw):
    transport = LeaderLink(leader)
    follower = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube or FakeKubeApiServer()),
        replicate_from="http://leader.test",
        leader_transport=transport, **opt_kw))
    return follower, transport


def churn(leader, i):
    op = UpdateOp.DELETE if i % 3 == 2 else UpdateOp.TOUCH
    rel = parse_relationship(
        f"namespace:ns{i % N_NS}#viewer@user:u{i % 5}")
    return leader.endpoint.write_relationships(
        [RelationshipUpdate(op, rel)])


async def list_ns(proxy, user, headers=None):
    client = proxy.get_embedded_client(user)
    resp = await client.get("/api/v1/namespaces", headers=headers or [])
    return resp, (sorted(i["metadata"]["name"]
                         for i in json.loads(resp.body).get("items", []))
                  if resp.status == 200 else None)


@pytest.fixture
def tmp():
    d = tempfile.mkdtemp(prefix="repl-test-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


def test_parity_referee_under_churn(tmp):
    """At every quiescent point (leader pinned at revision R, follower
    synced to exactly R), the follower's filtered list and check answers
    are identical to the leader's for every user — zero divergences."""
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube)
    repl = follower.replication
    users = ["alice", "u0", "u1", "u2", "u3", "u4", "nobody"]

    async def go():
        await repl.sync_once()
        for round_i in range(8):
            for j in range(5):
                await churn(leader, round_i * 5 + j)
            pinned = leader.endpoint.store.revision
            await repl.sync_once()
            assert repl.store.revision == pinned
            for user in users:
                lr, l_items = await list_ns(leader, user)
                fr, f_items = await list_ns(follower, user)
                assert lr.status == fr.status == 200
                assert f_items == l_items, (
                    f"divergence at revision {pinned} for {user}: "
                    f"follower {f_items} != leader {l_items}")
                # the answer is stamped with the revision it reflects
                assert int(fr.headers.get(REVISION_HEADER)) == pinned

    asyncio.run(go())


def test_bootstrap_from_checkpoint_plus_tail(tmp):
    """A follower arriving late bootstraps from the newest checkpoint
    and replays only the WAL tail past its watermark."""
    leader, kube = make_leader(tmp)

    async def go():
        for i in range(6):
            await churn(leader, i)
        leader.persistence.checkpoint()
        for i in range(6, 10):
            await churn(leader, i)
        follower, _ = make_follower(leader, kube)
        repl = follower.replication
        await repl.sync_once()
        assert repl.store.revision == leader.endpoint.store.revision
        assert repl.bootstrapped
        _, l_items = await list_ns(leader, "u1")
        _, f_items = await list_ns(follower, "u1")
        assert f_items == l_items
        # /readyz is 200 once bootstrapped
        resp = await follower.get_embedded_client("alice").get("/readyz")
        assert resp.status == 200

    asyncio.run(go())


def test_readyz_not_ready_before_bootstrap(tmp):
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube)

    async def go():
        resp = await follower.get_embedded_client("alice").get("/readyz")
        assert resp.status == 503
        assert b"bootstrapping" in resp.body
        await follower.replication.sync_once()
        resp = await follower.get_embedded_client("alice").get("/readyz")
        assert resp.status == 200

    asyncio.run(go())


def test_reclaimed_segment_triggers_rebootstrap(tmp):
    """A checkpoint on the leader reclaims segments out from under a
    lagging follower: the follower re-bootstraps from the checkpoint
    instead of diverging, and ends revision-identical."""
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube)
    repl = follower.replication

    async def go():
        await repl.sync_once()
        for i in range(10):
            await churn(leader, i)
        # checkpoint + reclaim while the follower is mid-tail in seg 1
        leader.persistence.checkpoint()
        for i in range(10, 14):
            await churn(leader, i)
        await repl.sync_once()
        assert repl.stats["rebootstraps"] == 1
        assert repl.store.revision == leader.endpoint.store.revision
        _, l_items = await list_ns(leader, "u2")
        _, f_items = await list_ns(follower, "u2")
        assert f_items == l_items
        # a re-bootstrap must never hard-fail readiness: with state
        # already adopted, a mid-re-bootstrap follower reports
        # degraded-but-200 (hard 503 is reserved for the FIRST
        # adoption) — otherwise a leader restart ejects every replica
        # from the load balancer at once
        assert repl.ever_bootstrapped
        repl.bootstrapped = False  # as during an in-flight re-bootstrap
        resp = await follower.get_embedded_client("x").get("/readyz")
        assert resp.status == 200 and b"re-bootstrapping" in resp.body
        repl.bootstrapped = True

    asyncio.run(go())


def test_leader_restart_mid_tail(tmp):
    """The leader restarts (same data dir) while the follower tails:
    pointing the follower at the new incarnation catches it up with no
    divergence — recovery + replication agree because both replay the
    same log."""
    leader, kube = make_leader(tmp)
    follower, transport = make_follower(leader, kube)
    repl = follower.replication

    async def go():
        for i in range(7):
            await churn(leader, i)
        await repl.sync_once()
        # clean leader shutdown (final checkpoint), then a new incarnation
        await leader.persistence.stop()
        leader2, _ = make_leader(tmp, seed_ns=False)
        transport.set_leader(leader2)
        for i in range(7, 12):
            await churn(leader2, i)
        await repl.sync_once()
        assert repl.store.revision == leader2.endpoint.store.revision
        _, l_items = await list_ns(leader2, "u1")
        _, f_items = await list_ns(follower, "u1")
        assert f_items == l_items

    asyncio.run(go())


def test_zedtoken_wait_path(tmp):
    """A read carrying a min-revision ahead of the tail WAITS for the
    tail (when it arrives within --replica-wait-ms) and then serves
    locally — no forward, no stale answer."""
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube)
    repl = follower.replication

    async def go():
        await repl.sync_once()
        rev = await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns1#viewer@user:zed"))])

        async def late_sync():
            await asyncio.sleep(0.05)
            await repl.sync_once()

        sync_task = asyncio.ensure_future(late_sync())
        resp, items = await list_ns(
            follower, "zed", headers=[(MIN_REVISION_HEADER, str(rev))])
        await sync_task
        assert resp.status == 200
        assert resp.headers.get("X-Authz-Forwarded-To") == ""
        assert items == ["ns1"]  # the write is visible: never stale
        assert int(resp.headers.get(REVISION_HEADER)) >= rev

    asyncio.run(go())


def test_zedtoken_forward_and_503_paths(tmp):
    leader, kube = make_leader(tmp)

    async def go():
        # forwarding on: a token the replica cannot reach within the
        # wait forwards to the leader and returns the fresh answer
        follower, _ = make_follower(leader, kube, replica_wait_ms=30.0)
        repl = follower.replication
        await repl.sync_once()
        rev = await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns3#viewer@user:zed2"))])
        resp, items = await list_ns(
            follower, "zed2", headers=[(MIN_REVISION_HEADER, str(rev))])
        assert resp.status == 200
        assert resp.headers.get("X-Authz-Forwarded-To") == "leader"
        assert items == ["ns3"]

        # forwarding off: 503 Status naming the leader, never stale data
        f2, _ = make_follower(leader, kube, replica_wait_ms=30.0,
                              replica_forward=False)
        await f2.replication.sync_once()
        rev2 = await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns4#viewer@user:zed2"))])
        resp, _ = await list_ns(
            f2, "zed2", headers=[(MIN_REVISION_HEADER, str(rev2))])
        assert resp.status == 503
        body = json.loads(resp.body)
        assert body["reason"] == "ServiceUnavailable"
        assert body["details"]["leader"] == "http://leader.test"

        # malformed token: 400, not a stale 200
        resp, _ = await list_ns(
            follower, "zed2", headers=[(MIN_REVISION_HEADER, "banana")])
        assert resp.status == 400

    asyncio.run(go())


def test_follower_write_forwarding_and_rejection(tmp):
    leader, kube = make_leader(tmp)

    async def go():
        follower, _ = make_follower(leader, kube)
        repl = follower.replication
        await repl.sync_once()
        leader.enable_dual_writes()
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p1", "namespace": "ns0"}}
        client = follower.get_embedded_client("alice")
        resp = await client.post("/api/v1/namespaces/ns0/pods", pod)
        assert resp.status in (200, 201), resp.body
        assert resp.headers.get("X-Authz-Forwarded-To") == "leader"
        # the dual-write landed on the LEADER's store and kube...
        assert leader.endpoint.store.has_exact(parse_relationship(
            "pod:ns0/p1#creator@user:alice"))
        # ...and replicates to the follower
        await repl.sync_once()
        assert follower.replication.store.has_exact(parse_relationship(
            "pod:ns0/p1#creator@user:alice"))

        # forwarding disabled: update verbs are rejected 503
        f2, _ = make_follower(leader, kube, replica_forward=False)
        await f2.replication.sync_once()
        resp = await f2.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", dict(
                pod, metadata={"name": "p2", "namespace": "ns0"}))
        assert resp.status == 503
        assert json.loads(resp.body)["details"][
            "leader"] == "http://leader.test"

    asyncio.run(go())


def test_leader_outage_degrades_but_serves(tmp):
    """kill the leader link: the follower keeps serving bounded-staleness
    reads, /readyz degrades (still 200), and forwarded paths 503."""
    leader, kube = make_leader(tmp)
    follower, transport = make_follower(leader, kube)
    repl = follower.replication

    class DeadTransport:
        async def round_trip(self, req):
            raise ConnectionError("leader is gone")

    async def go():
        for i in range(4):
            await churn(leader, i)
        await repl.sync_once()
        pinned = repl.store.revision
        _, before = await list_ns(follower, "u1")
        # sever the link (both the tail and the forward path)
        follower._leader_transport = DeadTransport()
        repl.transport = follower._leader_transport
        with pytest.raises(Exception):
            await repl.sync_once()
        repl.state = "degraded"  # run() would set this; sync_once raises
        resp, after = await list_ns(follower, "u1")
        assert resp.status == 200 and after == before
        assert int(resp.headers.get(REVISION_HEADER)) == pinned
        ready = await follower.get_embedded_client("x").get("/readyz")
        assert ready.status == 200 and b"degraded" in ready.body
        # updates now fail loudly instead of silently writing locally
        resp = await follower.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods",
            {"metadata": {"name": "px", "namespace": "ns0"}})
        assert resp.status == 503

    asyncio.run(go())


def test_replica_lag_shedding(tmp):
    """A stale replica sheds read-only traffic (429) before serving
    garbage once --shed-replica-lag is crossed."""
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube, shed_replica_lag_s=0.05)
    repl = follower.replication

    async def go():
        await repl.sync_once()
        resp, _ = await list_ns(follower, "u1")
        assert resp.status == 200  # caught up: no shedding
        # fall behind: leader advances, follower does not sync
        await churn(leader, 0)
        await repl._fetch_manifest(wait=False)  # sees the lag
        repl._caught_up_at -= 10.0  # stale for "10 seconds"
        assert repl.lag_seconds() > 0.05
        resp, _ = await list_ns(follower, "u1")
        assert resp.status == 429
        assert "replica_lag" in json.loads(resp.body)["message"]

    asyncio.run(go())


def test_gate_off_is_single_node_exactly(tmp):
    """Replication killswitch tripwire: gate off, a configured
    --replicate-from is inert (no follower objects, no interception) and
    the leader's data dir is NOT served at /replication/*."""
    GATES.set("Replication", False)
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube)
    assert follower.replication is None
    assert leader.replication_hub is None

    async def go():
        # /replication answers 503 "not served here", not leader data
        resp = await leader.get_embedded_client("alice").get(
            "/replication/manifest")
        assert resp.status == 503
        # no revision stamping anywhere (exact single-node responses)
        resp, items = await list_ns(leader, "alice")
        assert resp.status == 200
        assert resp.headers.get(REVISION_HEADER) == ""
        # the "follower" serves from its own (empty) store like any
        # single-node proxy: nothing replicated, no forwarding
        resp, items = await list_ns(follower, "alice")
        assert resp.status == 200 and items == []

    asyncio.run(go())


def test_replication_api_requires_auth_and_safe_names(tmp):
    leader, _ = make_leader(tmp)

    async def go():
        anon = leader.get_embedded_client("")  # no identity headers
        resp = await anon.get("/replication/manifest")
        assert resp.status == 401
        client = leader.get_embedded_client("alice")
        for name in ("../MANIFEST.json", "..%2fMANIFEST.json",
                     "seg-1.wal", "ckpt-1.npz", "etc/passwd"):
            resp = await client.get(f"/replication/segment/{name}")
            assert resp.status == 400, name
        man = json.loads((await client.get("/replication/manifest")).body)
        assert man["revision"] == leader.endpoint.store.revision
        assert man["segments"], "live segment should be listed"

    asyncio.run(go())


def test_longpoll_manifest_wakes_on_commit(tmp):
    leader, _ = make_leader(tmp)
    hub = leader.replication_hub

    async def go():
        rev = leader.endpoint.store.revision

        async def poke():
            await asyncio.sleep(0.05)
            await churn(leader, 99)

        task = asyncio.ensure_future(poke())
        ok = await hub.wait_for_revision(rev, timeout_s=5.0)
        await task
        assert ok and leader.endpoint.store.revision > rev
        # and an already-satisfied wait returns immediately
        assert await hub.wait_for_revision(rev, timeout_s=0.0)

    asyncio.run(go())


def test_parse_frames_torn_and_damaged():
    """The shared frame decoder tolerates a torn tail (partial frame)
    and refuses a damaged mid-stream frame."""
    import json as _json
    import struct
    import zlib

    def frame(rec):
        payload = _json.dumps(rec).encode()
        return struct.pack("<II", len(payload),
                           zlib.crc32(payload)) + payload

    a, b = frame({"k": "d", "r": 1}), frame({"k": "d", "r": 2})
    recs, consumed = parse_frames(a + b)
    assert [r["r"] for r in recs] == [1, 2] and consumed == len(a + b)
    # torn tail: second frame cut short -> first parses, rest waits
    recs, consumed = parse_frames(a + b[:-3])
    assert [r["r"] for r in recs] == [1] and consumed == len(a)
    # damaged mid-stream frame (bad crc, more data follows) -> error
    bad = bytearray(a)
    bad[-1] ^= 0xFF
    with pytest.raises(TornFrameError):
        parse_frames(bytes(bad) + b)
    # magic offset handling mirrors segment layout
    recs, consumed = parse_frames(SEGMENT_MAGIC + a, len(SEGMENT_MAGIC))
    assert [r["r"] for r in recs] == [1]
    assert consumed == len(SEGMENT_MAGIC) + len(a)


def test_follower_drives_watch_and_delta_pipeline(tmp):
    """Replica applies flow through the normal delta pipeline: follower
    watchers observe replicated writes exactly as local ones."""
    leader, kube = make_leader(tmp)
    follower, _ = make_follower(leader, kube)
    repl = follower.replication

    async def go():
        await repl.sync_once()
        watcher = follower.replication.store.subscribe(["namespace"])
        rel = "namespace:ns7#viewer@user:watched"
        await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(rel))])
        await repl.sync_once()
        upd = await watcher.next(timeout=2.0)
        assert upd is not None
        assert [u.rel.rel_string() for u in upd.updates] == [rel]
        assert upd.revision == repl.store.revision
        watcher.close()

    asyncio.run(go())


# -- PR 3 x PR 9 seam: expiry-driven invalidation on a REPLICA ----------------
# (ISSUE 12 satellite) An expiring tuple that arrived via the replica
# delta pipeline — apply_replica_batch, or wholesale via replica_reset
# (re-bootstrap) — must invalidate cached decision frontiers at its
# expiry INSTANT on the follower, exactly as a leader-local write would:
# the expiry heaps (decision cache + device graph) must be reseeded by
# both replica paths, not just by store.write.

EXPIRY_SCHEMA = """
definition user {}
definition namespace {
  relation viewer: user | user with expiration
  relation creator: user
  permission view = viewer + creator
}
"""


def _expiring(ns: str, user: str, at: float) -> RelationshipUpdate:
    return RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
        f"namespace:{ns}#viewer@user:{user}[expiration:{at}]"))


def test_replica_expiry_invalidates_cached_frontier_apply_batch():
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.decision_cache import (
        DecisionCacheEndpoint)
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    t = [1_700_000_000.0]
    leaf = TupleStore(clock=lambda: t[0])
    schema = sch.parse_schema(EXPIRY_SCHEMA)
    ep = DecisionCacheEndpoint(EmbeddedEndpoint(schema, store=leaf))
    alice = SubjectRef("user", "alice")

    async def go():
        # the replica applies a leader batch carrying a 10s grant — the
        # ONLY route the expiry instant has onto this node's heaps
        leaf.apply_replica_batch([
            _expiring("ns1", "alice", t[0] + 10.0),
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns2#creator@user:alice")),
        ])
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns1", "ns2"]
        # warm: the second list is served from the cache
        again = await ep.lookup_resources("namespace", "view", alice)
        assert getattr(again, "source", "") == "cache"
        # the clock crosses the expiry instant with NO further delta:
        # a heap that apply_replica_batch failed to seed would keep the
        # cached frontier "valid" and serve ns1 forever
        t[0] += 20.0
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns2"]
        assert ep.cache.stats["invalidations"] >= 1

    asyncio.run(go())


def test_replica_expiry_invalidates_cached_frontier_after_rebootstrap():
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.decision_cache import (
        DecisionCacheEndpoint)
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    t = [1_700_000_000.0]
    leaf = TupleStore(clock=lambda: t[0])
    schema = sch.parse_schema(EXPIRY_SCHEMA)
    ep = DecisionCacheEndpoint(EmbeddedEndpoint(schema, store=leaf))
    alice = SubjectRef("user", "alice")

    async def go():
        # pre-bootstrap state, cache warmed on it
        leaf.apply_replica_batch([RelationshipUpdate(
            UpdateOp.TOUCH,
            parse_relationship("namespace:ns9#creator@user:alice"))])
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns9"]
        # re-bootstrap (reclaimed-tail path): the adopted checkpoint
        # carries an expiring grant the delta listener NEVER saw — only
        # the post-reset expiry_schedule() rescan can seed its instant
        leaf.replica_reset(
            None,
            [parse_relationship(
                f"namespace:ns1#viewer@user:alice"
                f"[expiration:{t[0] + 10.0}]"),
             parse_relationship("namespace:ns2#creator@user:alice")],
            revision=50)
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns1", "ns2"]
        t[0] += 20.0
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns2"]

    asyncio.run(go())


def test_replica_expiry_reseeds_device_graph_heap():
    """Same seam, device side: a jax:// endpoint serving a FOLLOWER
    store must lazily expire tuples that arrived via apply_replica_batch
    and via replica_reset — the graph's own expiry heap is fed by the
    replica delta pipeline, not only by leader-local writes."""
    import os
    if os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu"):
        pytest.skip("CPU-only determinism test")
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    t = [1_700_000_000.0]
    leaf = TupleStore(clock=lambda: t[0])
    schema = sch.parse_schema(EXPIRY_SCHEMA)
    ep = JaxEndpoint(schema, store=leaf)
    alice = SubjectRef("user", "alice")

    async def go():
        leaf.apply_replica_batch([
            _expiring("ns1", "alice", t[0] + 10.0),
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns2#creator@user:alice")),
        ])
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns1", "ns2"]
        t[0] += 20.0
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns2"]
        # re-bootstrap with a fresh expiring grant: reset -> rebuild ->
        # expiry reseed from the adopted store
        leaf.replica_reset(
            None,
            [parse_relationship(
                f"namespace:ns3#viewer@user:alice"
                f"[expiration:{t[0] + 10.0}]")],
            revision=90)
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == ["ns3"]
        t[0] += 20.0
        assert sorted(await ep.lookup_resources(
            "namespace", "view", alice)) == []

    asyncio.run(go())
