"""Deterministic hunt for the stale-id-view/bitmap race (VERDICT r4 item 1).

Reproduces tests/test_concurrency_stress.py::test_lookups_race_spare_assigning_writes
in a tight loop with deep instrumentation: every rename / cache build /
capture is logged to a ring buffer with thread ids and sequence numbers;
the moment a suppression fires we freeze the endpoint lock and dump
  - the captured ids array vs the CURRENT program id at each bad index,
  - host vs device table contents for the affected rows,
  - the last N instrumentation events.

Run: JAX_PLATFORMS=cpu PYTHONPATH=/root/repo python scripts/probe_race.py [rounds]
"""

import asyncio
import itertools
import os
import sys
import threading
import time
from collections import deque

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import spicedb_kubeapi_proxy_tpu.ops.jax_endpoint as je
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap, create_endpoint
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition doc {
  relation viewer: user | group#member
  relation banned: user
  permission view = viewer - banned
}
"""

N_DOCS = 24
N_USERS = 12

EVENTS: deque = deque(maxlen=400)
SEQ = itertools.count()
FROZEN = threading.Event()   # set on first suppression: stop the world
REPORT: list = []


def log_event(kind, **kw):
    EVENTS.append((next(SEQ), time.monotonic(), threading.get_ident(),
                   kind, kw))


def seed_rels():
    out = []
    for d in range(N_DOCS):
        out.append(f"doc:d{d}#viewer@user:u{d % N_USERS}")
        out.append(f"doc:d{d}#viewer@group:g{d % 3}#member")
    for u in range(N_USERS):
        out.append(f"group:g{u % 3}#member@user:u{u}")
    return out


def install_instrumentation():
    orig_rename = je.JaxEndpoint._rename_row

    def rename_logged(self, graph, type_name, old_id, new_id):
        local = graph.prog.object_index[type_name].get(old_id)
        ok = orig_rename(self, graph, type_name, old_id, new_id)
        cache = getattr(graph, "_ids_np_cache", None)
        log_event("rename", graph=id(graph), t=type_name, old=old_id,
                  new=new_id, local=local, ok=ok,
                  cache_entry=id(cache.get(type_name)) if cache else None)
        return ok

    je.JaxEndpoint._rename_row = rename_logged

    orig_ids_np = je._object_ids_np

    def ids_np_logged(graph, resource_type):
        cache = getattr(graph, "_ids_np_cache", None)
        had = cache is not None and resource_type in cache
        out = orig_ids_np(graph, resource_type)
        log_event("ids_np", graph=id(graph), t=resource_type,
                  cached=had, arr=id(out[0]),
                  n_ph=int(out[1].sum()))
        return out

    je._object_ids_np = ids_np_logged

    orig_ids_for = je._ids_for
    capture: dict = {}

    def ids_for_logged(ids, idx, ph, mask):
        out, bad_n, bad_sample = orig_ids_for(ids, idx, ph, mask)
        if bad_n:
            bad_idx = idx[mask[idx]]
            capture[threading.get_ident()] = (ids, np.array(idx), ph,
                                              np.array(bad_idx))
        return out, bad_n, bad_sample

    je._ids_for = ids_for_logged

    orig_report = je.JaxEndpoint._report_suppressed

    def report_logged(self, n, sample, context):
        orig_report(self, n, sample, context)
        with self._lock:
            ids, idx, ph, bad_idx = capture.get(threading.get_ident(),
                                                (None, None, None, None))
            graph = self._graph
            lines = [f"=== SUPPRESSION n={n} sample={sample!r} "
                     f"context={context!r}"]
            lines.append(f"current graph={id(graph)} "
                         f"rev={self._graph_revision} "
                         f"spare_assignments={self.stats.get('spare_assignments')} "
                         f"reclaims={self.stats.get('spare_reclaims')} "
                         f"rebuilds={self.stats.get('rebuilds')}")
            if ids is not None and graph is not None:
                cur = graph.prog.object_ids.get("doc")
                cache = getattr(graph, "_ids_np_cache", {})
                ce = cache.get("doc")
                lines.append(
                    f"captured arr id={id(ids)} len={len(ids)}; current "
                    f"cache entry arr id={id(ce[0]) if ce else None}; "
                    f"current prog list len={len(cur) if cur else 0}")
                for b in np.asarray(bad_idx).tolist()[:8]:
                    cur_id = cur[b] if cur and b < len(cur) else "<oob>"
                    lines.append(
                        f"  local={b}: captured={ids[b]!r} current={cur_id!r}")
                    rng = graph.prog.slot_range("doc", "view")
                    if rng:
                        row = rng[0] + b
                        hm = getattr(graph, "host_main", None)
                        if hm is not None:
                            dm = np.asarray(graph.dev_main[row])
                            lines.append(f"    state_row={row} "
                                         f"host_main={hm[row].tolist()} "
                                         f"dev_main={dm.tolist()} "
                                         f"dirty={row in graph._dirty_main}")
                        rngv = graph.prog.slot_range("doc", "viewer")
                        if rngv:
                            rowv = rngv[0] + b
                            if hm is not None:
                                dmv = np.asarray(graph.dev_main[rowv])
                                lines.append(
                                    f"    viewer_row={rowv} "
                                    f"host_main={hm[rowv].tolist()} "
                                    f"dev_main={dmv.tolist()} "
                                    f"dirty={rowv in graph._dirty_main}")
            lines.append("--- last events (most recent last):")
            for ev in list(EVENTS):
                lines.append(f"  {ev}")
            REPORT.append("\n".join(lines))
            FROZEN.set()

    je.JaxEndpoint._report_suppressed = report_logged


async def run_round(round_no):
    ep = create_endpoint("jax://?dispatch=direct",
                         Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])
    inner = getattr(ep, "inner", ep)
    stop = asyncio.Event()
    created: list = []
    errors: list = []

    async def writer(wid):
        # churn: create AND delete so spare assign + reclaim both cycle
        for k in range(80):
            if FROZEN.is_set():
                break
            rel = f"doc:new-{wid}-{k}#viewer@user:u0"
            await ep.write_relationships([RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship(rel))])
            created.append(f"new-{wid}-{k}")
            if k % 3 == 2:  # delete an older one -> reclaim
                victim = f"doc:new-{wid}-{k-2}#viewer@user:u0"
                await ep.write_relationships([RelationshipUpdate(
                    UpdateOp.DELETE, parse_relationship(victim))])
                created.remove(f"new-{wid}-{k-2}")
            await asyncio.sleep(0)

    async def reader(rid):
        while not stop.is_set() and not FROZEN.is_set():
            ids = await ep.lookup_resources(
                "doc", "view", SubjectRef("user", "u0"))
            bad = [i for i in ids if "\x00" in i]
            if bad:
                errors.append(f"LEAK (post-retry): {bad[:6]}")
                FROZEN.set()
                return
            await asyncio.sleep(0)

    async def writers():
        # readers stop only after ALL writers finish: the tail of one
        # writer's churn must still race concurrent lookups
        await asyncio.gather(writer(0), writer(1))
        stop.set()

    await asyncio.wait_for(
        asyncio.gather(writers(), *[reader(i) for i in range(6)]), 180)
    return inner.stats.get("placeholder_suppressed", 0), errors


def main():
    install_instrumentation()
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    t0 = time.time()
    for r in range(rounds):
        supp, errors = asyncio.run(run_round(r))
        if errors:
            print("ERRORS:", errors)
        if supp or FROZEN.is_set():
            print(f"\n*** race fired in round {r} "
                  f"(suppressed={supp}, {time.time()-t0:.1f}s in)\n")
            for rep in REPORT:
                print(rep)
            return 1
        if r % 10 == 0:
            print(f"round {r} clean ({time.time()-t0:.1f}s)", flush=True)
    print(f"no race in {rounds} rounds ({time.time()-t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
