"""A003 near-misses: consistent order, reentrant re-entry, async locks."""
import asyncio
import threading


class Consistent:
    def __init__(self):
        self._store_lock = threading.RLock()
        self._gauge_lock = threading.Lock()

    def commit(self):
        with self._store_lock:
            with self._gauge_lock:        # store -> gauge everywhere
                return 1

    def checkpoint(self):
        with self._store_lock:
            with self._gauge_lock:        # same order: no cycle
                return 2

    def reenter(self):
        with self._store_lock:
            self._inner()                  # RLock re-entry is legal

    def _inner(self):
        with self._store_lock:
            pass


class AsyncSide:
    def __init__(self):
        self._alock = asyncio.Lock()

    async def guarded(self):
        async with self._alock:
            await asyncio.sleep(0)        # async lock: awaiting is fine
