"""Suppression fixture: reasons are REQUIRED — a bare code is itself a
finding (A000)."""
import time


async def suppressed_with_reason():
    time.sleep(0.01)  # noqa: A001(startup-only path, loop not serving yet)


async def suppressed_without_reason():
    time.sleep(0.01)  # noqa: A001


async def wrong_code_suppression():
    time.sleep(0.01)  # noqa: A002(wrong rule named, finding survives)
