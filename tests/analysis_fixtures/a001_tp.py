"""A001 true positives: blocking calls lexically inside async defs."""
import asyncio
import os
import subprocess
import time

import numpy as np


async def sleeps_on_loop():
    time.sleep(0.5)                      # A001


async def fsyncs_on_loop(fd):
    os.fsync(fd)                         # A001


async def shells_on_loop():
    subprocess.run(["true"])             # A001


async def materializes_on_loop(device_result):
    return np.asarray(device_result)     # A001


async def syncs_device(result):
    result.block_until_ready()           # A001


async def opens_on_loop(path):
    with open(path) as f:                # A001
        return f.read()


async def wal_flush(self):
    self.wal.fsync_if_dirty()            # A001 (method tail)


async def legit_async_sleep():
    await asyncio.sleep(0.1)             # fine
