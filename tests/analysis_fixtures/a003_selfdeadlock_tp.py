"""A003 true positive: non-reentrant lock re-acquired through a
one-level call while already held (the PR 5 finalizer-under-ledger-lock
shape)."""
import threading


class Pool:
    def __init__(self):
        self._pool_lock = threading.Lock()   # NOT an RLock

    def retire(self):
        with self._pool_lock:
            self._compact()                  # A003: callee re-locks

    def _compact(self):
        with self._pool_lock:
            pass
