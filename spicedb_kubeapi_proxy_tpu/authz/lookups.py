"""PreFilter LookupResources (reference pkg/authz/lookups.go).

Resolves the single LR template, streams allowed resource ids from the
endpoint, and maps each id to a NamespacedName via the rule's
fromObjectIDName/Namespace expressions.  The namespace expression is first
queried against `{"resourceId": id}`; a null result falls back to the full
request input (reference lookups.go:108-127).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..config.proxyrule import MATCHING_ID_FIELD_VALUE
from ..rules import blang
from ..rules.engine import ResolveInput, ResolvedPreFilter, _to_template_data
from ..spicedb.endpoints import PermissionsEndpoint
from ..spicedb.types import SubjectRef


class PreFilterError(Exception):
    pass


@dataclass
class PrefilterResult:
    """The allowed NamespacedName set (reference lookups.go:19-36)."""
    all_allowed: bool = False
    allowed: set = field(default_factory=set)  # {(namespace, name)}
    error: Optional[Exception] = None
    # evaluator that produced the frontier (cache|kernel|oracle; "" when
    # the endpoint chain doesn't attribute) — audit decision_source
    source: str = ""

    def is_allowed(self, namespace: str, name: str) -> bool:
        if self.all_allowed:
            return True
        return (namespace, name) in self.allowed


def extract_namespaced_name(filter: ResolvedPreFilter, input: ResolveInput,
                            resource_id: str,
                            subject_id: str = "") -> tuple:
    """Map an object id to (namespace, name) via the filter expressions."""
    data = {"resourceId": resource_id, "subjectId": subject_id}
    try:
        name = filter.name_from_object_id.query(data)
    except blang.BlangError as e:
        raise PreFilterError(f"error querying name from object ID: {e}") from e
    if not isinstance(name, str) or not name:
        raise PreFilterError(
            f"unable to determine name for resource {resource_id!r}")
    try:
        namespace = filter.namespace_from_object_id.query(data)
    except blang.BlangError as e:
        raise PreFilterError(f"error querying namespace from object ID: {e}") from e
    if namespace is None:
        # fall back to the request input for rules whose namespace comes from
        # the request rather than the object id
        try:
            namespace = filter.namespace_from_object_id.query(
                _to_template_data(input))
        except blang.BlangError as e:
            raise PreFilterError(
                f"error querying namespace from input: {e}") from e
    if namespace is None:
        namespace = ""
    if not isinstance(namespace, str):
        raise PreFilterError(
            f"namespace expression returned {type(namespace).__name__}")
    return namespace, name


async def run_lookup_resources(endpoint: PermissionsEndpoint,
                               filter: ResolvedPreFilter,
                               input: ResolveInput) -> PrefilterResult:
    """LR + per-result extraction (reference lookups.go:43-136).

    Drains the endpoint's id stream incrementally so NamespacedName
    extraction overlaps the remaining transfer (reference drains the gRPC
    server-stream the same way, lookups.go:74-135)."""
    if filter.rel.resource_id != MATCHING_ID_FIELD_VALUE:
        raise PreFilterError("preFilter called with non-$ resource ID")
    result = PrefilterResult()
    subject = SubjectRef(filter.rel.subject_type, filter.rel.subject_id,
                         filter.rel.subject_relation)
    if getattr(endpoint, "decision_cache_enabled", False):
        # decision-cached chain: a warm hit materializes the full frontier
        # without touching the dispatcher or the device, and carries the
        # decision source (cache|kernel|oracle) for the audit event.  The
        # id stream's transfer-overlap is moot here — hits are host lists
        # and misses are materialized before the cache fill anyway.
        ids = await endpoint.lookup_resources(
            filter.rel.resource_type, filter.rel.resource_relation, subject)
        result.source = getattr(ids, "source", "")
        for rid in ids:
            result.allowed.add(extract_namespaced_name(filter, input, rid))
        return result
    async for rid in endpoint.lookup_resources_stream(
            filter.rel.resource_type,
            filter.rel.resource_relation,
            subject):
        result.allowed.add(extract_namespaced_name(filter, input, rid))
    return result
