"""A005 true positives (fixture mirrors an ops/ module): host work and
traced-dim loops inside functions REACHED from jax.jit sites —
including through the factory idiom (`evaluate = make_evaluate(...)`)
that defeats fence-based linting."""
import datetime
import time

import jax
import jax.numpy as jnp
import numpy as np


def make_evaluate(n):
    def evaluate(x):
        host = np.zeros((n,))             # A005: host np in traced fn
        return x + jnp.asarray(host)

    return evaluate


def build(n):
    evaluate = make_evaluate(n)

    def run(q, width):
        x = evaluate(q)                   # factory-resolved reach
        stamp = time.time()               # A005: trace-time clock
        when = datetime.datetime.now()    # A005: trace-time clock
        total = x.sum().item()            # A005: forced materialization
        i = 0
        while i < width:                  # A005: while over traced param
            i += 1
        for _ in q:                       # A005: for over traced param
            pass
        return total, stamp

    return jax.jit(run)


@jax.jit
def decorated_kernel(x):
    return x + np.arange(4)               # A005: host np, decorator root
