"""In-memory relationship (tuple) store.

The host-side source of truth replacing embedded SpiceDB's memory datastore
(reference pkg/spicedb/spicedb.go:18-71): versioned writes with
create/touch/delete semantics, filter deletes with `$`-wildcards,
preconditions, relationship expiration (`use expiration` /
`with expiration`, used by the dual-write engine's idempotency keys,
reference activity.go:47-102), read filters, and watch subscriptions.

The device CSR used by the jax:// backend is a cache rebuilt/delta-updated
from this store (SURVEY.md §5 checkpoint/resume note).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Iterable, Optional

from .types import (
    AlreadyExistsError,
    ObjectRef,
    Precondition,
    PreconditionFailedError,
    PreconditionOp,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    WatchUpdate,
)

# Max mutations / preconditions per write call, mirroring the embedded
# server's limits (reference spicedb.go:35-36).
MAX_UPDATES_PER_WRITE = 1000
MAX_PRECONDITIONS = 1000


class WriteLimitExceededError(Exception):
    pass


class Watcher:
    """A subscription to relationship updates; drained via poll()."""

    def __init__(self, store: "TupleStore", object_types: Optional[set]):
        self._store = store
        self._object_types = object_types
        self._events: list[WatchUpdate] = []
        self._cond = threading.Condition()
        self.closed = False

    def _publish(self, update: WatchUpdate) -> None:
        if self._object_types:
            updates = tuple(u for u in update.updates
                            if u.rel.resource.type in self._object_types)
            if not updates:
                return
            update = WatchUpdate(updates=updates, revision=update.revision)
        with self._cond:
            self._events.append(update)
            self._cond.notify_all()

    def poll(self, timeout: Optional[float] = None) -> Optional[WatchUpdate]:
        """Block until the next batch (or timeout/close); None on timeout."""
        with self._cond:
            if not self._events and not self.closed:
                self._cond.wait(timeout)
            if self._events:
                return self._events.pop(0)
            return None

    def close(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()
        self._store._unsubscribe(self)


@dataclass
class _Entry:
    rel: Relationship
    revision: int


class TupleStore:
    """Thread-safe in-memory tuple store with monotonic revisions."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.RLock()
        self._clock = clock
        # (resource_type, relation) -> {resource_id -> {subject_key -> _Entry}}
        self._by_relation: dict = {}
        self._revision = 0
        self._watchers: list[Watcher] = []
        # delta listeners get every committed batch synchronously under the
        # store lock — used by the jax:// backend for incremental CSR updates.
        self._delta_listeners: list[Callable[[WatchUpdate], None]] = []
        # reset listeners fire on non-delta mass changes (bulk_load,
        # delete_all) that require a full cache rebuild.
        self._reset_listeners: list[Callable[[], None]] = []

    # -- revision -----------------------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._revision

    # -- reads --------------------------------------------------------------

    def read(self, flt: Optional[RelationshipFilter] = None) -> list:
        """All live (unexpired) relationships matching the filter."""
        now = self._clock()
        out = []
        with self._lock:
            for (rtype, relation), by_id in self._by_relation.items():
                if flt is not None and flt.resource_type and rtype != flt.resource_type:
                    continue
                if flt is not None and flt.relation and relation != flt.relation:
                    continue
                for rid, subjects in by_id.items():
                    if flt is not None and flt.resource_id and rid != flt.resource_id:
                        continue
                    for entry in subjects.values():
                        if entry.rel.expired(now):
                            continue
                        if flt is None or flt.matches(entry.rel):
                            out.append(entry.rel)
        return out

    def subjects_for(self, resource: ObjectRef, relation: str) -> list:
        """Live subjects of (resource, relation) — evaluator hot path."""
        now = self._clock()
        with self._lock:
            by_id = self._by_relation.get((resource.type, relation))
            if not by_id:
                return []
            subjects = by_id.get(resource.id)
            if not subjects:
                return []
            return [e.rel.subject for e in subjects.values()
                    if not e.rel.expired(now)]

    def resources_with_relation(self, resource_type: str, relation: str) -> list:
        """Live resource ids having any tuple for (type, relation)."""
        now = self._clock()
        with self._lock:
            by_id = self._by_relation.get((resource_type, relation))
            if not by_id:
                return []
            return [rid for rid, subjects in by_id.items()
                    if any(not e.rel.expired(now) for e in subjects.values())]

    def object_ids_of_type(self, resource_type: str) -> list:
        """All ids appearing as a resource of the given type (live tuples)."""
        now = self._clock()
        ids = set()
        with self._lock:
            for (rtype, _), by_id in self._by_relation.items():
                if rtype != resource_type:
                    continue
                for rid, subjects in by_id.items():
                    if any(not e.rel.expired(now) for e in subjects.values()):
                        ids.add(rid)
        return sorted(ids)

    def has_exact(self, rel: Relationship) -> bool:
        now = self._clock()
        with self._lock:
            by_id = self._by_relation.get((rel.resource.type, rel.relation), {})
            entry = by_id.get(rel.resource.id, {}).get(rel.subject)
            return entry is not None and not entry.rel.expired(now)

    def count(self) -> int:
        return len(self.read())

    # -- writes -------------------------------------------------------------

    def write(self, updates: Iterable[RelationshipUpdate],
              preconditions: Iterable[Precondition] = ()) -> int:
        """Atomically apply updates after checking preconditions; returns the
        new revision (the zedtoken equivalent)."""
        updates = list(updates)
        preconditions = list(preconditions)
        if len(updates) > MAX_UPDATES_PER_WRITE:
            raise WriteLimitExceededError(
                f"{len(updates)} updates exceeds limit {MAX_UPDATES_PER_WRITE}")
        if len(preconditions) > MAX_PRECONDITIONS:
            raise WriteLimitExceededError(
                f"{len(preconditions)} preconditions exceeds limit {MAX_PRECONDITIONS}")
        with self._lock:
            self._check_preconditions(preconditions)
            # validate CREATEs before mutating (atomicity); duplicates
            # within the batch are also conflicts
            now = self._clock()
            created_in_batch: set = set()
            for u in updates:
                if u.op != UpdateOp.CREATE:
                    continue
                key = u.rel.key()
                if (self._live_entry(u.rel, now) is not None
                        or key in created_in_batch):
                    raise AlreadyExistsError(
                        f"relationship already exists: {u.rel.rel_string()}")
                created_in_batch.add(key)
            self._revision += 1
            rev = self._revision
            applied = []
            for u in updates:
                if u.op in (UpdateOp.CREATE, UpdateOp.TOUCH):
                    self._put(u.rel, rev)
                    applied.append(RelationshipUpdate(UpdateOp.TOUCH, u.rel))
                elif u.op == UpdateOp.DELETE:
                    if self._remove(u.rel):
                        applied.append(RelationshipUpdate(UpdateOp.DELETE, u.rel))
            if applied:
                self._broadcast(WatchUpdate(updates=tuple(applied), revision=rev))
            return rev

    def bulk_load(self, rels: Iterable[Relationship]) -> int:
        """Bootstrap/benchmark path: load relationships without the per-call
        API update limit (the reference seeds bootstrap data straight into
        the datastore, not through WriteRelationships — spicedb.go:63-67).
        One revision, no watch events."""
        with self._lock:
            self._revision += 1
            rev = self._revision
            for rel in rels:
                self._put(rel, rev)
            for fn in list(self._reset_listeners):
                fn()
            return rev

    def delete_by_filter(self, flt: RelationshipFilter,
                         preconditions: Iterable[Precondition] = ()) -> tuple:
        """Delete all relationships matching the filter; returns
        (revision, deleted relationships)."""
        with self._lock:
            self._check_preconditions(list(preconditions))
            victims = self.read(flt)
            if not victims:
                return self._revision, []
            self._revision += 1
            rev = self._revision
            applied = []
            for rel in victims:
                if self._remove(rel):
                    applied.append(RelationshipUpdate(UpdateOp.DELETE, rel))
            if applied:
                self._broadcast(WatchUpdate(updates=tuple(applied), revision=rev))
            return rev, victims

    def delete_all(self) -> None:
        """Test helper (mirrors the reference e2e DeleteAllTuples util)."""
        with self._lock:
            self._by_relation.clear()
            self._revision += 1
            for fn in list(self._reset_listeners):
                fn()

    # -- watch --------------------------------------------------------------

    def subscribe(self, object_types: Optional[Iterable[str]] = None) -> Watcher:
        w = Watcher(self, set(object_types) if object_types else None)
        with self._lock:
            self._watchers.append(w)
        return w

    def _unsubscribe(self, w: Watcher) -> None:
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def add_delta_listener(self, fn: Callable[[WatchUpdate], None]) -> None:
        with self._lock:
            self._delta_listeners.append(fn)

    def add_reset_listener(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._reset_listeners.append(fn)

    def remove_delta_listener(self, fn: Callable[[WatchUpdate], None]) -> None:
        with self._lock:
            if fn in self._delta_listeners:
                self._delta_listeners.remove(fn)

    # -- internals ----------------------------------------------------------

    def _live_entry(self, rel: Relationship, now: float) -> Optional[_Entry]:
        by_id = self._by_relation.get((rel.resource.type, rel.relation), {})
        entry = by_id.get(rel.resource.id, {}).get(rel.subject)
        if entry is None or entry.rel.expired(now):
            return None
        return entry

    def _put(self, rel: Relationship, rev: int) -> None:
        key = (rel.resource.type, rel.relation)
        by_id = self._by_relation.setdefault(key, {})
        subjects = by_id.setdefault(rel.resource.id, {})
        subjects[rel.subject] = _Entry(rel=rel, revision=rev)

    def _remove(self, rel: Relationship) -> bool:
        key = (rel.resource.type, rel.relation)
        by_id = self._by_relation.get(key)
        if not by_id:
            return False
        subjects = by_id.get(rel.resource.id)
        if not subjects or rel.subject not in subjects:
            return False
        del subjects[rel.subject]
        if not subjects:
            del by_id[rel.resource.id]
        if not by_id:
            del self._by_relation[key]
        return True

    def _check_preconditions(self, preconditions: list) -> None:
        for p in preconditions:
            matched = bool(self.read(p.filter))
            if p.op == PreconditionOp.MUST_MATCH and not matched:
                raise PreconditionFailedError(p)
            if p.op == PreconditionOp.MUST_NOT_MATCH and matched:
                raise PreconditionFailedError(p)

    def _broadcast(self, update: WatchUpdate) -> None:
        for fn in list(self._delta_listeners):
            fn(update)
        for w in list(self._watchers):
            w._publish(update)
