"""Device-telemetry smoke: start the proxy, drive traffic, scrape
/metrics + /debug/flight, and fail loudly on any missing telemetry
family (wired into scripts/check.sh; fast, CPU-only, no TPU).

What it proves end to end:
- the server starts with the flight recorder + SLO tracker wired;
- `/metrics` carries the device-telemetry families (`authz_device_bytes`,
  `authz_batch_occupancy`, `authz_jit_cache_*`, `authz_slo_burn_rate`);
- `/debug/flight` returns >= 2 windows of snapshots after a warm-up;
- `/debug/timeline` serves valid chrome-trace JSON (every event has
  ph/ts/pid/tid, B/E pairing balanced) with >= 1 dispatch slice;
- the `/debug` index enumerates every debug surface uniformly;
- with the device-resident pipeline enabled (DevicePipeline gate on,
  `jax://?pipeline_depth=3`), concurrent per-user list requests fan
  into multiple fused batches and `authz_dispatch_overlap_ratio` goes
  positive, while `stall{cause=pack|transpose}` stays ~0 relative to
  kernel time (the host encode/word-transpose moved on-device);
- kernel introspection & workload attribution: after real mixed
  traffic the measured sweep histograms carry samples, `/debug/workload`
  attributes device seconds per (type, permission) and its total
  reconciles with cumulative `authz_kernel_time_seconds` within 5%,
  and `/debug/profile` returns non-empty collapsed stacks;
- admission control (second server, `--shed-queue-depth` +
  `jax://?max_queue_depth=`): driving concurrent read waves past the
  queue bound yields kube-style 429 Status responses carrying a
  `Retry-After` header, `authz_admission_rejected_total` increments,
  and `/readyz` reports the shedding as degraded-but-200 (docs/
  performance.md "Overload & rebuild behavior").
"""

import asyncio
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import (  # noqa: E402
    FakeKubeApiServer)
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (  # noqa: E402
    HandlerTransport)
from spicedb_kubeapi_proxy_tpu.proxy.server import (  # noqa: E402
    Options, ProxyServer)
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap  # noqa: E402
from spicedb_kubeapi_proxy_tpu.spicedb.types import (  # noqa: E402
    parse_relationship)
from spicedb_kubeapi_proxy_tpu.utils.features import GATES  # noqa: E402

# This smoke measures the device pipeline itself (fused-batch overlap,
# admission shedding against real kernel windows): keep the Leopard
# index out so nested lookups sweep instead of serving from the closure
# plane.  The /debug/workload leopard field still surfaces through the
# detector fallback (candidate | ineligible(unplanned)); the indexed
# path is exercised by tests/test_leopard.py and the live e2e driver.
GATES.set("LeopardIndex", False)

SCHEMA = """
definition user {}

definition namespace {
    relation creator: user
    permission view = creator
}

definition pod {
    relation creator: user
    relation namespace: namespace
    permission view = creator + namespace->view
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-pods}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
check: [{tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"}]
"""

REQUIRED_FAMILIES = (
    "authz_device_bytes",
    "authz_device_bytes_peak",
    "authz_batch_occupancy",
    "authz_jit_cache_hits_total",
    "authz_jit_cache_misses_total",
    "authz_jit_cache_entries",
    "authz_slo_burn_rate",
    "authz_kernel_time_seconds",
    # dispatch timeline (utils/timeline.py)
    "authz_dispatch_stall_seconds",
    "authz_dispatch_bandwidth_bytes_per_sec",
    "authz_roofline_fraction",
    "authz_dispatch_overlap_ratio",
    # kernel introspection & workload attribution (utils/workload.py)
    "authz_sweep_iterations",
    "authz_frontier_decay",
)

# stages that prove a real device dispatch landed on the timeline
DISPATCH_SLICES = ("kernel", "transfer", "transpose", "pack")


def validate_chrome_trace(trace: dict) -> list:
    """Chrome trace-event schema check: every event needs ph/ts/pid/tid
    (X additionally dur), and B/E pairs must balance per (pid, tid).
    Returns the dispatch-stage slices.  tests/test_timeline.py keeps an
    independent copy BY HAND (this script's module level sets env vars
    and imports jax — importing it from the test suite would drag those
    side effects in); schema changes must land in both."""
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        fail(f"/debug/timeline has no traceEvents list: {list(trace)}")
    depth: dict = {}
    slices = []
    for ev in events:
        for field in ("ph", "ts", "pid", "tid"):
            if field not in ev:
                fail(f"timeline event missing {field!r}: {ev}")
        if ev["ph"] == "X":
            if "dur" not in ev:
                fail(f"X event missing dur: {ev}")
            if ev["name"] in DISPATCH_SLICES:
                slices.append(ev)
        elif ev["ph"] == "B":
            depth[(ev["pid"], ev["tid"])] = (
                depth.get((ev["pid"], ev["tid"]), 0) + 1)
        elif ev["ph"] == "E":
            key = (ev["pid"], ev["tid"])
            depth[key] = depth.get(key, 0) - 1
            if depth[key] < 0:
                fail(f"unbalanced E event (no open B) on {key}")
    open_tracks = {k: v for k, v in depth.items() if v}
    if open_tracks:
        fail(f"unbalanced B/E pairs at end of trace: {open_tracks}")
    return slices


def fail(msg: str) -> None:
    print(f"devtel_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


async def main() -> None:
    kube = FakeKubeApiServer()
    for i in range(8):
        kube.seed("", "v1", "pods",
                  {"metadata": {"name": f"p{i}", "namespace": "team-a"}})
    # max_batch=4 + pipeline_depth=3: the concurrent per-user wave below
    # must split into several fused batches so the drain keeps started
    # batches in flight (the overlap assertion needs >= 2 batches whose
    # kernel/readback windows can interleave)
    server = ProxyServer(Options(
        spicedb_endpoint="jax://?max_batch=4&pipeline_depth=3",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        flight_window_s=0.15,
        flight_windows=16,
        slo_check_p99_ms=250.0,
        slo_objective=0.01,
    ))
    users = [f"u{j}" for j in range(12)]
    rels = ["namespace:team-a#creator@user:alice"] + [
        f"pod:team-a/p{i}#creator@user:alice" for i in range(0, 8, 2)] + [
        f"pod:team-a/p{i}#creator@user:{u}"
        for i in range(8) for u in users[i % 3::3]] + [
        # graph ballast (not in the fake kube, filtered from responses):
        # widens the lookup slot so each fused kernel's window is long
        # enough for the overlap assertion below to be deterministic on
        # the CPU backend — without it the sub-ms kernels finish before
        # the drain can dispatch the next batch
        f"pod:team-a/ballast{i}#creator@user:{users[i % len(users)]}"
        for i in range(30_000)]
    server.endpoint.store.bulk_load([parse_relationship(r) for r in rels])

    await server.start("127.0.0.1", 0)
    try:
        alice = server.get_embedded_client(user="alice")
        for _ in range(6):
            resp = await alice.get("/api/v1/pods")
            assert resp.status == 200, resp.body
        resp = await alice.get("/api/v1/namespaces/team-a/pods/p0")
        assert resp.status == 200, resp.body
        # >= 2 flight windows after the warm-up
        await asyncio.sleep(0.5)

        resp = await alice.get("/metrics")
        if resp.status != 200:
            fail(f"/metrics -> {resp.status}")
        text = resp.body.decode()
        missing = [f for f in REQUIRED_FAMILIES
                   if f"# TYPE {f} " not in text]
        if missing:
            fail(f"/metrics missing device-telemetry families: {missing}")
        if "authz_device_bytes{" not in text:
            fail("authz_device_bytes has no kind-labeled samples "
                 "(HBM ledger never registered a buffer)")
        if 'authz_slo_burn_rate{slo="latency_p99"' not in text:
            fail("authz_slo_burn_rate has no latency_p99 samples "
                 "(SLO evaluator never ran)")

        resp = await alice.get("/debug/flight")
        if resp.status != 200:
            fail(f"/debug/flight -> {resp.status}")
        flight = json.loads(resp.body)
        if len(flight.get("windows", [])) < 2:
            fail(f"/debug/flight returned "
                 f"{len(flight.get('windows', []))} windows, want >= 2")
        newest = flight["windows"][0]
        for field in ("http", "hbm", "occupancy", "jit", "slo"):
            if field not in newest:
                fail(f"flight window missing {field!r}: {newest}")
        if newest["hbm"]["total"] <= 0:
            fail("flight window reports an empty HBM ledger after "
                 "kernel traffic")

        resp = await alice.get("/debug/timeline")
        if resp.status != 200:
            fail(f"/debug/timeline -> {resp.status}")
        trace = json.loads(resp.body)
        slices = validate_chrome_trace(trace)
        if not slices:
            fail(f"/debug/timeline has no dispatch slices "
                 f"({len(trace.get('traceEvents', []))} events, none named "
                 f"{DISPATCH_SLICES})")
        summary = trace.get("otherData", {}).get("summary", {})
        if not summary.get("events"):
            fail(f"/debug/timeline summary is empty: {summary}")
        win = flight["windows"][0]
        if "timeline" not in win or "slow_traces" not in win:
            fail(f"flight window missing timeline/slow_traces evidence "
                 f"links: {sorted(win)}")

        # -- device-resident pipeline: overlap > 0, pack/transpose ~ 0 --
        # waves of concurrent per-user lists (distinct subjects, so the
        # singleflight dedup can't collapse them) fan into >= 3 fused
        # batches at max_batch=4; the pipelined drain keeps started
        # batches in flight, so some batch's readback must land inside
        # another batch's kernel window.  A couple of retry waves absorb
        # scheduler noise on the tiny CPU smoke graph.
        clients = [server.get_embedded_client(user=u) for u in users]
        overlap = 0.0
        for _ in range(6):
            waved = await asyncio.gather(
                *[c.get("/api/v1/pods") for c in clients])
            for r in waved:
                assert r.status == 200, r.body
            resp = await alice.get("/metrics")
            text = resp.body.decode()
            for line in text.splitlines():
                if line.startswith("authz_dispatch_overlap_ratio "):
                    overlap = float(line.split()[1])
            if overlap > 0:
                break
        if overlap <= 0:
            fail("authz_dispatch_overlap_ratio stayed 0 after 6 "
                 "concurrent waves with the pipeline enabled — the "
                 "pipelined drain is not overlapping readback with the "
                 "next batch's kernel")
        resp = await alice.get("/debug/timeline")
        summary = json.loads(resp.body).get("otherData", {}).get(
            "summary", {})
        stalls = summary.get("stall_s", {})
        kernel_ms = summary.get("stage_ms", {}).get("kernel", 0.0)
        if kernel_ms <= 0:
            fail(f"timeline summary has no kernel stage time: {summary}")
        host_prep = stalls.get("pack", 0.0) + stalls.get("transpose", 0.0)
        if host_prep > 0.2 * kernel_ms / 1e3:
            fail(f"stall{{cause=pack|transpose}} = {host_prep:.4f}s vs "
                 f"kernel {kernel_ms:.1f}ms — host query prep crept back "
                 f"onto the hot path (device-resident pipeline regression; "
                 f"see lint M003)")

        # -- workload attribution & profiling ------------------------
        # the waves above pushed real check + lookup traffic through
        # the kernels: the measured sweep histograms must carry samples
        resp = await alice.get("/metrics")
        text = resp.body.decode()
        if "authz_sweep_iterations_bucket{" not in text:
            fail("authz_sweep_iterations has no samples after kernel "
                 "traffic (sweep telemetry never read back a trace)")
        resp = await alice.get("/debug/workload")
        if resp.status != 200:
            fail(f"/debug/workload -> {resp.status}")
        wl = json.loads(resp.body)
        if not wl.get("enabled"):
            fail(f"/debug/workload reports disabled: {wl}")
        pairs = {(r["resource_type"], r["permission"]): r
                 for r in wl.get("rows", [])}
        pod_view = pairs.get(("pod", "view"))
        if not pod_view:
            fail(f"/debug/workload has no (pod, view) row: {sorted(pairs)}")
        if pod_view["kernel_rows"] + pod_view["oracle_rows"] <= 0:
            fail(f"(pod, view) row attributes no routed rows: {pod_view}")
        # every row must carry the Leopard per-pair status verdict
        # (`indexed | indexed(quarantined) | candidate |
        # ineligible(reason)` — ops/leopard.py status_map plus the
        # detector fallback), and the candidate list must be present
        for row in wl.get("rows", []):
            leo = row.get("leopard")
            if not (leo in ("indexed", "indexed(quarantined)", "candidate")
                    or (isinstance(leo, str)
                        and leo.startswith("ineligible("))):
                fail(f"/debug/workload row has no actionable leopard "
                     f"status: {row}")
        if "leopard_candidates" not in wl:
            fail(f"/debug/workload payload missing leopard_candidates: "
                 f"{sorted(wl)}")
        # total device seconds must reconcile with the cumulative
        # kernel-time histogram (same hook, same seconds) within 5%
        metric_s = 0.0
        for line in text.splitlines():
            if (line.startswith("authz_kernel_time_seconds_sum{")
                    and ('phase="kernel.device"' in line
                         or 'phase="kernel.dispatch"' in line)):
                metric_s += float(line.split()[-1])
        total_s = wl.get("total_device_s", 0.0)
        if metric_s <= 0 or total_s <= 0:
            fail(f"no device seconds to reconcile (metric {metric_s}, "
                 f"workload {total_s})")
        if abs(total_s - metric_s) > 0.05 * metric_s:
            fail(f"/debug/workload total_device_s {total_s:.4f}s does not "
                 f"reconcile with authz_kernel_time_seconds {metric_s:.4f}s "
                 f"(> 5% apart)")
        resp = await alice.get("/debug/profile?seconds=0.2")
        if resp.status != 200:
            fail(f"/debug/profile -> {resp.status}")
        prof = json.loads(resp.body)
        if not prof.get("enabled"):
            fail(f"/debug/profile reports disabled: {prof}")
        if prof.get("samples", 0) <= 0 or not prof.get("collapsed"):
            fail(f"/debug/profile captured nothing: samples="
                 f"{prof.get('samples')}, "
                 f"{len(prof.get('collapsed', []))} collapsed stacks")
        if not prof.get("chrome_trace", {}).get("traceEvents"):
            fail("/debug/profile chrome_trace is empty")

        resp = await alice.get("/debug")
        if resp.status != 200:
            fail(f"/debug -> {resp.status}")
        surfaces = json.loads(resp.body).get("surfaces", {})
        for path in ("/debug/traces", "/debug/decisions", "/debug/flight",
                     "/debug/timeline", "/debug/workload", "/debug/profile"):
            if path not in surfaces:
                fail(f"/debug index missing {path}: {surfaces}")
        resp = await alice.get("/debug/nonesuch")
        if resp.status != 404:
            fail(f"/debug/nonesuch -> {resp.status}, want uniform 404")
        resp = await alice.get("/readyz")
        if resp.status != 200 or not resp.body.startswith(b"ok"):
            fail(f"/readyz -> {resp.status} {resp.body!r}")
    finally:
        await server.stop()

    rejected = await overload_smoke(kube)
    print("devtel_smoke: OK (device-telemetry families present, "
          f"{len(flight['windows'])} flight windows, "
          f"{len(slices)} timeline dispatch slices, "
          f"pipeline overlap {overlap:.3f}, "
          f"workload attribution reconciled "
          f"({total_s:.4f}s vs {metric_s:.4f}s), "
          f"{prof['samples']} profile samples, "
          f"{rejected} overload rejections)")


def _metric_value(text: str, prefix: str) -> float:
    total = 0.0
    for line in text.splitlines():
        if line.startswith(prefix):
            total += float(line.split()[-1])
    return total


async def overload_smoke(kube) -> int:
    """Drive a bounded-queue proxy past capacity: reads must shed with
    429 + Retry-After (never hang), the admission counter must count
    every rejection, and /readyz must report degraded-but-200."""
    server = ProxyServer(Options(
        # tight bounds so a 12-wide concurrent wave reliably overflows:
        # each fused batch carries at most 2 queries and at most 4 more
        # may queue (the 4-deep backlog persists across several kernel
        # windows, giving the door shedder a visible depth); the
        # shedder additionally rejects reads at the door once anything
        # is queued
        spicedb_endpoint="jax://?max_batch=2&max_queue_depth=4",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        shed_queue_depth=1,
        shed_retry_after_s=7.0,
    ))
    users = [f"u{j}" for j in range(12)]
    rels = ["namespace:team-a#creator@user:alice"] + [
        # ballast widens the kernel window so queue depth actually
        # builds while a batch is in flight (same trick as above)
        f"pod:team-a/ballast{i}#creator@user:{users[i % len(users)]}"
        for i in range(30_000)]
    server.endpoint.store.bulk_load([parse_relationship(r) for r in rels])
    await server.start("127.0.0.1", 0)
    try:
        alice = server.get_embedded_client(user="alice")
        base = _metric_value(
            (await alice.get("/metrics")).body.decode(),
            "authz_admission_rejected_total")
        clients = [server.get_embedded_client(user=u) for u in users]
        shed = []
        door_shed = 0
        for _ in range(8):
            # two staggered waves: the first saturates the dispatcher
            # queues (its overflow 429s exercise the queue bound), the
            # second arrives while the first is still queued so its
            # door checks see non-zero depth and the LOAD SHEDDER
            # rejects before any authorization work (what /readyz
            # below must report)
            first = [asyncio.ensure_future(c.get("/api/v1/pods"))
                     for c in clients]
            await asyncio.sleep(0.01)
            second = [asyncio.ensure_future(c.get("/api/v1/pods"))
                      for c in clients]
            waved = await asyncio.wait_for(
                asyncio.gather(*first, *second), timeout=60)
            for r in waved:
                if r.status == 429:
                    shed.append(r)
                elif r.status != 200:
                    fail(f"overload wave: unexpected status {r.status}: "
                         f"{r.body[:200]}")
            door_shed = server.shedder.snapshot()["shed_total"]
            if shed and door_shed:
                break
        if not shed:
            fail("8 staggered double read waves against "
                 "max_queue_depth=4 + shed_queue_depth=1 produced no "
                 "429 — admission control is not engaging")
        if not door_shed:
            fail("429s came only from the dispatcher queue bound; the "
                 "load shedder never rejected at the door "
                 "(shed_queue_depth=1 with requests queued)")
        for r in shed:
            ra = r.headers.get("Retry-After")
            if not ra or int(ra) < 1:
                fail(f"429 without a usable Retry-After header: {ra!r}")
            status = json.loads(r.body)
            if (status.get("kind") != "Status"
                    or status.get("reason") != "TooManyRequests"
                    or status.get("code") != 429):
                fail(f"429 body is not a kube TooManyRequests Status: "
                     f"{status}")
        text = (await alice.get("/metrics")).body.decode()
        now = _metric_value(text, "authz_admission_rejected_total")
        if now - base < len(shed):
            fail(f"authz_admission_rejected_total rose {now - base:.0f} "
                 f"but {len(shed)} requests were rejected")
        resp = await alice.get("/readyz")
        if resp.status != 200:
            fail(f"/readyz during shedding -> {resp.status}, want "
                 "degraded-but-200 (shedding is backpressure, not an "
                 "outage)")
        body = resp.body.decode()
        if "shedding" not in body:
            fail(f"/readyz does not report recent shedding: {body!r}")
        # the system must drain, not wedge: a quiet follow-up succeeds
        await asyncio.sleep(0.2)
        resp = await alice.get("/api/v1/pods")
        if resp.status != 200:
            fail(f"post-overload request -> {resp.status}, want 200 "
                 "(queues must drain after the wave passes)")
        return len(shed)
    finally:
        await server.stop()


if __name__ == "__main__":
    asyncio.run(main())
