"""Workflow activities (reference pkg/authz/distributedtx/activity.go).

All inputs/outputs are JSON-serializable dicts (the journal round-trips
them).  Codec helpers translate between the wire dicts and the store types.

- write_to_spicedb: attaches an idempotency-key relationship (hash of the
  request payload + workflow id, 24h expiration); on error, an existing key
  means the write already happened and is treated as success
  (reference activity.go:47-126)
- read_relationships: drains the filter read (activity.go:152-172)
- write_to_kube: replays the original URI/body/headers (minus
  Accept-Encoding) against the upstream transport (activity.go:175-238)
- check_kube_resource: existence probe (activity.go:240-254)

Failpoints fire at the same five sites as the reference.
"""

from __future__ import annotations

import hashlib
import time

from ...proxy.httpcore import Headers, Request, Transport
from ...spicedb.endpoints import PermissionsEndpoint
from ...spicedb.types import (
    Precondition,
    PreconditionOp,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectFilter,
    UpdateOp,
    parse_relationship,
)
from ...utils.failpoints import fail_point

IDEMPOTENCY_KEY_EXPIRATION = 24 * 3600.0


# -- codecs ------------------------------------------------------------------

def update_to_dict(op: str, rel: Relationship) -> dict:
    return {"op": op, "rel": rel.rel_string()}


def update_from_dict(d: dict) -> RelationshipUpdate:
    return RelationshipUpdate(op=UpdateOp(d["op"]),
                              rel=parse_relationship(d["rel"]))


def filter_to_dict(f: RelationshipFilter) -> dict:
    out: dict = {
        "resource_type": f.resource_type,
        "resource_id": f.resource_id,
        "relation": f.relation,
    }
    if f.subject is not None:
        out["subject"] = {"type": f.subject.type, "id": f.subject.id,
                          "relation": f.subject.relation}
    return out


def filter_from_dict(d: dict) -> RelationshipFilter:
    subject = None
    if d.get("subject") is not None:
        s = d["subject"]
        subject = SubjectFilter(type=s.get("type", ""), id=s.get("id", ""),
                                relation=s.get("relation"))
    return RelationshipFilter(
        resource_type=d.get("resource_type", ""),
        resource_id=d.get("resource_id", ""),
        relation=d.get("relation", ""),
        subject=subject,
    )


def precondition_to_dict(p: Precondition) -> dict:
    return {"op": p.op.value, "filter": filter_to_dict(p.filter)}


def precondition_from_dict(d: dict) -> Precondition:
    return Precondition(op=PreconditionOp(d["op"]),
                        filter=filter_from_dict(d["filter"]))


# -- activities --------------------------------------------------------------

class ActivityHandler:
    def __init__(self, endpoint: PermissionsEndpoint, kube_transport: Transport):
        self.endpoint = endpoint
        self.kube_transport = kube_transport

    # write_request: {"updates": [update dicts], "preconditions": [dicts]}
    async def write_to_spicedb(self, write_request: dict, workflow_id: str) -> dict:
        fail_point("panicWriteSpiceDB")
        key_rel = idempotency_key_for_payload(write_request, workflow_id)

        updates = [update_from_dict(u) for u in write_request.get("updates", [])]
        updates.append(RelationshipUpdate(UpdateOp.CREATE, key_rel))
        preconditions = [precondition_from_dict(p)
                         for p in write_request.get("preconditions", [])]
        try:
            rev = await self.endpoint.write_relationships(updates, preconditions)
            fail_point("panicSpiceDBWriteResp")
        except Exception as e:
            from ...utils.failpoints import FailPointPanic
            if isinstance(e, FailPointPanic):
                raise
            # on error, an existing idempotency key means the relationships
            # were already written (activity.go:62-74)
            existing = await self.endpoint.read_relationships(RelationshipFilter(
                resource_type=key_rel.resource.type,
                resource_id=key_rel.resource.id,
                relation=key_rel.relation,
                subject=SubjectFilter(type=key_rel.subject.type,
                                      id=key_rel.subject.id),
            ))
            if existing:
                return {"written_at": self.endpoint.store.revision}
            raise
        return {"written_at": rev}

    async def read_relationships(self, filter_dict: dict) -> list:
        fail_point("panicReadSpiceDB")
        rels = await self.endpoint.read_relationships(filter_from_dict(filter_dict))
        fail_point("panicSpiceDBReadResp")
        return [r.rel_string() for r in rels]

    # kube_req: {"method_verb", "request_uri", "headers": {k: [v]}, "body": str}
    async def write_to_kube(self, kube_req: dict) -> dict:
        fail_point("panicKubeWrite")
        verb = kube_req.get("verb", "")
        method = {
            "put": "PUT", "patch": "PATCH", "post": "POST",
            "update": "PUT", "delete": "DELETE", "create": "POST",
        }.get(verb)
        if method is None:
            raise ValueError(f"unsupported kube verb: {verb}")
        uri = kube_req.get("request_uri", "")
        if not uri:
            raise ValueError("request URI must be specified for kube write")
        headers = Headers()
        for k, values in (kube_req.get("headers") or {}).items():
            # the transport owns gzip negotiation (activity.go:208-215)
            if k.lower() in ("accept-encoding", "content-length", "host",
                             "connection"):
                continue
            if k.lower().startswith("x-remote-"):
                continue
            for v in values:
                headers.add(k, v)
        body = (kube_req.get("body") or "").encode()
        resp = await self.kube_transport.round_trip(  # noqa: A006(external kube hop)
            Request(method=method, target=uri, headers=headers, body=body))
        fail_point("panicKubeReadResp")
        retry_after = 0
        header = resp.headers.get("Retry-After")
        if header.isdigit():
            retry_after = int(header)
        else:
            try:
                import json as _json
                details = (_json.loads(resp.body) or {}).get("details") or {}
                retry_after = int(details.get("retryAfterSeconds") or 0)
            except (ValueError, AttributeError):
                retry_after = 0
        return {
            "status_code": resp.status,
            "content_type": resp.headers.get("Content-Type", "application/json"),
            "body": resp.body.decode("utf-8", errors="replace"),
            "retry_after_seconds": retry_after,
        }

    async def check_kube_resource(self, probe_uri: str) -> bool:
        resp = await self.kube_transport.round_trip(  # noqa: A006(external kube hop)
            Request(method="GET", target=probe_uri, headers=Headers()))
        if 200 <= resp.status < 300:
            return True
        if resp.status == 404:
            return False
        raise RuntimeError(f"kube existence probe failed: {resp.status}")


def idempotency_key_for_payload(write_request: dict, workflow_id: str) -> Relationship:
    """workflow:{id}#idempotency_key@activity:{payload hash}, 24h expiration
    (reference activity.go:80-102; xxhash becomes blake2b here)."""
    import json
    payload = json.dumps(write_request, sort_keys=True).encode()
    digest = hashlib.blake2b(payload + workflow_id.encode(),
                             digest_size=8).hexdigest()
    from ...spicedb.types import ObjectRef, SubjectRef
    return Relationship(
        resource=ObjectRef("workflow", workflow_id),
        relation="idempotency_key",
        subject=SubjectRef("activity", digest),
        expires_at=time.time() + IDEMPOTENCY_KEY_EXPIRATION,
    )
