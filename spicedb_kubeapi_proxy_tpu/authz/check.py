"""Bulk permission-check runner (reference pkg/authz/check.go).

All Check/PostCheck templates across the matched rules resolve to
relationships and are checked concurrently per-expression; each expression's
relationships go through one CheckBulkPermissions call and every item must
be HAS_PERMISSION.
"""

from __future__ import annotations

from typing import Optional

from ..rules.engine import ResolveInput
from ..spicedb.endpoints import PermissionsEndpoint
from ..spicedb.types import CheckRequest, ObjectRef, SubjectRef


class UnauthorizedError(Exception):
    """A failed bulk check; carries the failing relationship and the rule
    that generated it so the audit event (and an explain witness) can
    name the exact check that denied the request."""

    def __init__(self, message: str, rel=None, rule: str = "",
                 check_type: str = "", source: str = ""):
        super().__init__(message)
        self.rel = rel            # resolved relationship (rel_string-able)
        self.rule = rule          # ProxyRule name the template came from
        self.check_type = check_type
        self.source = source      # evaluator that denied (kernel|oracle|cache)


def check_request_from_rel(rel) -> CheckRequest:
    return CheckRequest(
        resource=ObjectRef(rel.resource_type, rel.resource_id),
        permission=rel.resource_relation,
        subject=SubjectRef(rel.subject_type, rel.subject_id,
                           rel.subject_relation),
    )


async def check_relationships(endpoint: PermissionsEndpoint, resolved_rels: list,
                              check_type: str,
                              rules_of: Optional[list] = None) -> list:
    """One bulk check; all must pass (reference check.go:18-72); returns
    the CheckResult list so callers can attribute decision sources.
    `rules_of` (parallel to `resolved_rels`) attributes each rel to the
    ProxyRule that generated it for the UnauthorizedError."""
    if not resolved_rels:
        return []
    reqs = [check_request_from_rel(rel) for rel in resolved_rels]
    results = await endpoint.check_bulk_permissions(reqs)
    for i, (rel, result) in enumerate(zip(resolved_rels, results)):
        if not result.allowed:
            raise UnauthorizedError(
                f"bulk {check_type} failed for {rel.rel_string()}",
                rel=rel, rule=rules_of[i] if rules_of else "",
                check_type=check_type,
                source=getattr(result, "source", ""))
    return results


def decision_source_of(results: list) -> str:
    """Collapse per-check sources into one audit label: the common
    source, `mixed` when checks disagree, "" when nothing attributes."""
    sources = {getattr(r, "source", "") for r in results} - {""}
    if not sources:
        return ""
    return sources.pop() if len(sources) == 1 else "mixed"


async def _run_exprs(endpoint: PermissionsEndpoint, rules_list: list,
                     input: ResolveInput, attr: str, check_type: str) -> list:
    # All templates across all matched rules resolve first, then fold into
    # ONE CheckBulkPermissions call for the whole request (reference
    # check.go:23-48 collects every checkRel before the single bulk RPC).
    resolved: list = []
    rules_of: list = []
    for r in rules_list:
        rule_name = getattr(r, "name", "")
        for expr in getattr(r, attr):
            for rel in expr.generate_relationships(input):
                resolved.append(rel)
                rules_of.append(rule_name)
    return await check_relationships(endpoint, resolved, check_type,
                                     rules_of=rules_of)


async def run_all_matching_checks(endpoint: PermissionsEndpoint,
                                  matching_rules: list,
                                  input: ResolveInput) -> list:
    return await _run_exprs(endpoint, matching_rules, input, "checks",
                            "check")


async def run_all_matching_post_checks(endpoint: PermissionsEndpoint,
                                       matching_rules: list,
                                       input: ResolveInput) -> list:
    return await _run_exprs(endpoint, matching_rules, input, "post_checks",
                            "postcheck")
