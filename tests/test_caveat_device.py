"""Tri-state device path for caveats (round-4, VERDICT item 5).

Undecidable caveated tuples become MAYBE-plane edges in the ELL kernel
(definite/maybe bitplanes; exclusion mixes planes per Kleene logic), so
caveat-affected queries stay on the device instead of dropping to the
recursive host oracle.  These tests differential-check the kernel's
tri-state results against Evaluator.check3 across randomized graphs with
unions, intersections, exclusions, arrows, nested groups, and caveats in
all three decidability states (context-decided True / False, undecided).
"""

import asyncio
import random

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    Permissionship,
    SubjectRef,
    parse_relationship,
)

SCHEMA = """
caveat flag(on bool) { on }
caveat limit(n int, max int) { n < max }
definition user {}
definition group {
  relation member: user | group#member | user with flag
}
definition folder {
  relation owner: user
  relation viewer: user | group#member | user with flag
  permission view = viewer + owner
}
definition doc {
  relation folder: folder
  relation reader: user | user with flag | user with limit
  relation blocked: user | user with flag
  relation required: user | user with flag
  permission base = reader + folder->view
  permission gated = base & required
  permission view = base - blocked
  permission strict = gated - blocked
}
"""

P3 = {Permissionship.NO_PERMISSION: 0,
      Permissionship.CONDITIONAL_PERMISSION: 1,
      Permissionship.HAS_PERMISSION: 2}


def make_pair(rels):
    schema = sch.parse_schema(SCHEMA)
    ep = JaxEndpoint(sch.parse_schema(SCHEMA))
    parsed = [parse_relationship(r) for r in rels]
    ep.store.bulk_load(parsed)
    oracle_store = ep.store
    return ep, Evaluator(schema, oracle_store)


def assert_matches(ep, oracle, resource_type, object_ids, permissions,
                   subjects):
    async def run():
        for perm in permissions:
            for s in subjects:
                reqs = [CheckRequest(ObjectRef(resource_type, oid), perm, s)
                        for oid in object_ids]
                got = await ep.check_bulk_permissions(reqs)
                for oid, res in zip(object_ids, got):
                    want = oracle.check3(ObjectRef(resource_type, oid),
                                         perm, s)
                    assert P3[res.permissionship] == want, (
                        perm, oid, s.id, P3[res.permissionship], want)
                want_lr = sorted(oracle.lookup_resources(
                    resource_type, perm, s))
                got_lr = sorted(await ep.lookup_resources(
                    resource_type, perm, s))
                assert got_lr == want_lr, (perm, s.id, got_lr, want_lr)
    asyncio.run(run())


UNDECIDED = "[caveat:flag]"
TRUE_CTX = '[caveat:flag:{"on": true}]'
FALSE_CTX = '[caveat:flag:{"on": false}]'


class TestKleenePlaneAlgebra:
    """Hand-picked Kleene cases through each operator."""

    def test_union_definite_wins_over_maybe(self):
        ep, oracle = make_pair([
            f"doc:d#reader@user:a{UNDECIDED}",
            "doc:d#folder@folder:f",
            "folder:f#owner@user:a",
        ])
        # reader is MAYBE but folder->view is definite: T ∨ U = T
        assert_matches(ep, oracle, "doc", ["d"], ["base", "view"],
                       [SubjectRef("user", "a")])

    def test_exclusion_maybe_subtract_degrades_definite(self):
        ep, oracle = make_pair([
            "doc:d#reader@user:a",
            f"doc:d#blocked@user:a{UNDECIDED}",
        ])
        # base=T, blocked=U: T − U = U (CONDITIONAL, not HAS)
        assert_matches(ep, oracle, "doc", ["d"], ["view"],
                       [SubjectRef("user", "a")])

    def test_exclusion_definite_subtract_kills_maybe(self):
        ep, oracle = make_pair([
            f"doc:d#reader@user:a{UNDECIDED}",
            "doc:d#blocked@user:a",
        ])
        # base=U, blocked=T: U − T = NO
        assert_matches(ep, oracle, "doc", ["d"], ["view"],
                       [SubjectRef("user", "a")])

    def test_intersection_maybe_caps(self):
        ep, oracle = make_pair([
            "doc:d#reader@user:a",
            f"doc:d#required@user:a{UNDECIDED}",
        ])
        # base=T, required=U: T ∧ U = U
        assert_matches(ep, oracle, "doc", ["d"], ["gated"],
                       [SubjectRef("user", "a")])

    def test_decided_contexts_resolve_at_compile(self):
        ep, oracle = make_pair([
            f"doc:dt#reader@user:a{TRUE_CTX}",
            f"doc:df#reader@user:a{FALSE_CTX}",
        ])
        assert_matches(ep, oracle, "doc", ["dt", "df"], ["base", "view"],
                       [SubjectRef("user", "a")])
        # decided tuples never need the oracle OR the maybe plane
        assert ep.stats["oracle_residual_checks"] == 0

    def test_maybe_through_group_nesting(self):
        ep, oracle = make_pair([
            f"group:inner#member@user:a{UNDECIDED}",
            "group:outer#member@group:inner#member",
            "folder:f#viewer@group:outer#member",
            "doc:d#folder@folder:f",
        ])
        # MAYBE propagates through two userset hops + an arrow
        assert_matches(ep, oracle, "doc", ["d"], ["base", "view"],
                       [SubjectRef("user", "a")])
        assert ep.stats["oracle_residual_checks"] == 0

    def test_strict_composition(self):
        ep, oracle = make_pair([
            f"doc:d#reader@user:a{UNDECIDED}",
            "doc:d#required@user:a",
            f"doc:d#blocked@user:a{UNDECIDED}",
        ])
        # (U ∧ T) − U = U − U = U
        assert_matches(ep, oracle, "doc", ["d"], ["strict"],
                       [SubjectRef("user", "a")])


class TestRandomizedDifferential:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_random_graphs(self, seed):
        rng = random.Random(seed)
        users = [f"u{i}" for i in range(6)]
        docs = [f"d{i}" for i in range(8)]
        folders = [f"f{i}" for i in range(3)]
        groups = [f"g{i}" for i in range(3)]
        suffixes = ["", UNDECIDED, TRUE_CTX, FALSE_CTX,
                    '[caveat:limit:{"n": 1}]',        # undecided (max missing)
                    '[caveat:limit:{"n": 1, "max": 5}]']   # decided True
        rels = set()
        for _ in range(60):
            kind = rng.randrange(5)
            u = rng.choice(users)
            if kind == 0:
                suf = rng.choice(suffixes)
                if "limit" in suf:
                    rels.add(f"doc:{rng.choice(docs)}#reader@user:{u}{suf}")
                else:
                    rel = rng.choice(["reader", "blocked", "required"])
                    rels.add(f"doc:{rng.choice(docs)}#{rel}@user:{u}{suf}")
            elif kind == 1:
                rels.add(f"doc:{rng.choice(docs)}#folder@folder:"
                         f"{rng.choice(folders)}")
            elif kind == 2:
                suf = rng.choice(["", UNDECIDED])
                rels.add(f"folder:{rng.choice(folders)}#viewer@user:{u}{suf}")
            elif kind == 3:
                suf = rng.choice(["", UNDECIDED])
                rels.add(f"group:{rng.choice(groups)}#member@user:{u}{suf}")
            else:
                rels.add(f"folder:{rng.choice(folders)}#viewer@group:"
                         f"{rng.choice(groups)}#member")
        # a nested group edge to exercise recursion with caveats around it
        rels.add("group:g1#member@group:g0#member")
        ep, oracle = make_pair(sorted(rels))
        assert_matches(ep, oracle, "doc", docs,
                       ["base", "gated", "view", "strict"],
                       [SubjectRef("user", u) for u in users])
        assert ep.stats["oracle_residual_checks"] == 0

    def test_incremental_caveat_deltas_no_rebuild(self):
        """Caveated writes on the single-chip graph apply incrementally
        (VERDICT soft spot: they used to force a multi-second rebuild)."""
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate,
            UpdateOp,
        )

        def write(ep, *rels, op=UpdateOp.TOUCH):
            ep.store.write([RelationshipUpdate(op, parse_relationship(r))
                            for r in rels])

        # start WITH a caveated tuple so the bitplanes are compiled in
        ep, oracle = make_pair([
            "doc:d0#reader@user:a",
            f"doc:d9#reader@user:z{UNDECIDED}",
            "doc:d0#folder@folder:f0",
            "folder:f0#owner@user:a",
        ])
        subjects = [SubjectRef("user", u) for u in ("a", "b", "z")]
        assert_matches(ep, oracle, "doc", ["d0", "d9"], ["base", "view"],
                       subjects)
        rebuilds = ep.stats["rebuilds"]

        # undecidable caveats are written against already-compiled ids so
        # each write exercises the incremental path, not a new-id rebuild
        write(ep, f"doc:d0#blocked@user:a{UNDECIDED}")
        assert_matches(ep, oracle, "doc", ["d0"], ["view"], subjects)

        # re-touch flips it to context-decided True (definite edge)
        write(ep, f"doc:d0#blocked@user:a{TRUE_CTX}")
        assert_matches(ep, oracle, "doc", ["d0"], ["view"], subjects)

        # then to decided False (no edges)
        write(ep, f"doc:d0#blocked@user:a{FALSE_CTX}")
        assert_matches(ep, oracle, "doc", ["d0"], ["view"], subjects)

        # caveated tuple replaced by a definite one
        write(ep, "doc:d9#reader@user:z")
        assert_matches(ep, oracle, "doc", ["d9"], ["base"], subjects)

        # and back to caveated, then deleted
        write(ep, f"doc:d9#reader@user:z{UNDECIDED}")
        assert_matches(ep, oracle, "doc", ["d9"], ["base"], subjects)
        write(ep, f"doc:d9#reader@user:z{UNDECIDED}", op=UpdateOp.DELETE)
        assert_matches(ep, oracle, "doc", ["d9"], ["base"], subjects)

        # an ARROW-carrying tuple turning caveated: both its direct edge
        # and its aux (folder->view) edge must move to the MAYBE plane,
        # degrading the arrow branch to CONDITIONAL
        write(ep, f"doc:d0#folder@folder:f0{UNDECIDED}")
        assert_matches(ep, oracle, "doc", ["d0"], ["base", "view"],
                       subjects)
        write(ep, "doc:d0#folder@folder:f0")  # back to definite
        assert_matches(ep, oracle, "doc", ["d0"], ["base", "view"],
                       subjects)

        assert ep.stats["rebuilds"] == rebuilds, "caveat deltas rebuilt"

    def test_first_undecidable_caveat_rebuilds_once(self):
        """A graph compiled without bitplanes gains them via one rebuild
        when the first undecidable caveat arrives; decided caveats never
        rebuild."""
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate,
            UpdateOp,
        )
        ep, oracle = make_pair(["doc:d0#reader@user:a"])
        subjects = [SubjectRef("user", u) for u in ("a", "b")]
        assert_matches(ep, oracle, "doc", ["d0"], ["base"], subjects)
        rebuilds = ep.stats["rebuilds"]

        # decided-True caveat: ordinary definite edge, no rebuild
        ep.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"doc:d0#blocked@user:a{TRUE_CTX}"))])
        assert_matches(ep, oracle, "doc", ["d0"], ["view"], subjects)
        assert ep.stats["rebuilds"] == rebuilds

        # first UNDECIDABLE caveat: exactly one rebuild (turns planes
        # on).  The rebuild runs off-loop now: answers stay exact
        # throughout (stale pairs route to the oracle), and
        # wait_rebuilds() quiesces before the count is asserted.
        ep.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"doc:d0#required@user:a{UNDECIDED}"))])
        assert_matches(ep, oracle, "doc", ["d0"], ["gated"], subjects)
        assert ep.wait_rebuilds()
        assert ep.stats["rebuilds"] == rebuilds + 1
        assert not ep._stale_pairs

        # subsequent undecidable writes on compiled ids are incremental
        # (user:a is compiled; user:b would be a new-id rebuild, which is
        # the same behavior definite deltas have)
        ep.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"doc:d0#blocked@user:a{UNDECIDED}"))])
        assert_matches(ep, oracle, "doc", ["d0"], ["view", "strict"],
                       subjects)
        assert ep.wait_rebuilds()
        assert ep.stats["rebuilds"] == rebuilds + 1

    @pytest.mark.parametrize("seed", [0, 1])
    def test_random_graphs_sharded_mesh(self, seed):
        """The sharded kernel carries the same MAYBE plane (trailing plane
        axis, exclusion swap device-local): differential vs the oracle on
        the virtual 2x4 mesh."""
        from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
            Bootstrap,
            create_endpoint,
        )

        rng = random.Random(seed + 100)
        users = [f"u{i}" for i in range(5)]
        docs = [f"d{i}" for i in range(6)]
        folders = ["f0", "f1"]
        rels = set()
        for _ in range(40):
            kind = rng.randrange(4)
            u = rng.choice(users)
            if kind == 0:
                suf = rng.choice(["", UNDECIDED, TRUE_CTX, FALSE_CTX])
                rel = rng.choice(["reader", "blocked", "required"])
                rels.add(f"doc:{rng.choice(docs)}#{rel}@user:{u}{suf}")
            elif kind == 1:
                rels.add(f"doc:{rng.choice(docs)}#folder@folder:"
                         f"{rng.choice(folders)}")
            elif kind == 2:
                suf = rng.choice(["", UNDECIDED])
                rels.add(f"folder:{rng.choice(folders)}#viewer@user:{u}{suf}")
            else:
                rels.add(f"folder:{rng.choice(folders)}#owner@user:{u}")
        ep = create_endpoint("jax://?mesh=2x4&dispatch=direct",
                             Bootstrap(schema_text=SCHEMA))
        parsed = [parse_relationship(r) for r in sorted(rels)]
        ep.store.bulk_load(parsed)
        oracle = Evaluator(sch.parse_schema(SCHEMA), ep.store)
        assert_matches(ep, oracle, "doc", docs,
                       ["base", "gated", "view", "strict"],
                       [SubjectRef("user", u) for u in users])
        assert ep.stats["oracle_residual_checks"] == 0
        from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import _ShardedEllGraph
        assert isinstance(ep._graph, _ShardedEllGraph)
        assert ep._graph.kernel.planes  # the MAYBE plane really engaged

        # caveated deltas on compiled ids are incremental on the sharded
        # graph too (host mirror + padded-row remap on flush)
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate,
            UpdateOp,
        )
        rebuilds = ep.stats["rebuilds"]
        u0, d0 = users[0], docs[0]
        for rel in (f"doc:{d0}#blocked@user:{u0}{UNDECIDED}",
                    f"doc:{d0}#blocked@user:{u0}{TRUE_CTX}",
                    f"doc:{d0}#blocked@user:{u0}{FALSE_CTX}"):
            ep.store.write([RelationshipUpdate(UpdateOp.TOUCH,
                                               parse_relationship(rel))])
            assert_matches(ep, oracle, "doc", [d0], ["view", "strict"],
                           [SubjectRef("user", u0)])
        ep.store.write([RelationshipUpdate(UpdateOp.DELETE,
                                           parse_relationship(
            f"doc:{d0}#blocked@user:{u0}{FALSE_CTX}"))])
        assert_matches(ep, oracle, "doc", [d0], ["view"],
                       [SubjectRef("user", u0)])
        assert ep.stats["rebuilds"] == rebuilds, "sharded cav delta rebuilt"

    def test_wildcard_caveat_falls_back_to_oracle(self):
        """No device lowering for caveated wildcards: affected pairs route
        to the host oracle exactly as before round 4."""
        ep, oracle = make_pair([
            f"doc:d#reader@user:*{UNDECIDED}",
            "doc:d2#reader@user:b",
        ])
        assert_matches(ep, oracle, "doc", ["d", "d2"], ["base", "view"],
                       [SubjectRef("user", "a"), SubjectRef("user", "b")])
        assert ep.stats["oracle_residual_checks"] > 0
