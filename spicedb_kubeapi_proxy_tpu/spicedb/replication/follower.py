"""Follower side of WAL-shipping replication: the ReplicaFollower.

Lifecycle (docs/replication.md "Bootstrap & catch-up"):

1. **Bootstrap** — fetch `/replication/manifest`; adopt the newest
   checkpoint wholesale (`TupleStore.replica_reset`, which fires the
   reset listeners so the device graph / decision cache rebuild from the
   adopted state), position the segment cursor just past the
   checkpoint's watermark.
2. **Tail** — long-poll the manifest for `revision > applied`, fetch new
   segment bytes from the cursor offset, decode complete CRC frames
   (`persist.wal.parse_frames` — the same framing code the leader's own
   recovery uses), and apply each record in revision order through the
   live-store replica path: `apply_replica_batch` for deltas (drives
   watchers + delta listeners), `bulk_load_snapshot`/`bulk_load`/
   `delete_all` for the mass-change kinds (drive the reset listeners).
3. **Re-bootstrap** — a 404 on a segment (reclaimed under a newer
   checkpoint), a revision gap, or a damaged frame all converge on the
   same recovery: re-adopt the newest checkpoint instead of diverging.
   The applied revision may move BACKWARDS across a re-bootstrap after
   the leader lost an unsynced tail — bounded staleness, never
   divergence.

The follower never journals: commit listeners do not fire on the
replica-apply paths, so a follower is free to also be configured with
its own (independent) observability but never re-ships the leader's log.

Thread model: everything here runs on the server's event loop (one
`run()` task); `wait_for_revision` is how the serving path parks a
ZedToken-bearing request until the tail catches up.
"""

from __future__ import annotations

import asyncio
import logging
import time
import weakref
from typing import Optional

from ...utils import metrics as m
from ..store import TupleStore
from ..types import RelationshipUpdate, UpdateOp, parse_relationship
from ..persist.wal import SEGMENT_MAGIC, TornFrameError, parse_frames

logger = logging.getLogger("spicedb_kubeapi_proxy_tpu.replication")

STATE_BOOTSTRAPPING = "bootstrapping"
STATE_STREAMING = "streaming"
STATE_DEGRADED = "degraded"          # leader unreachable; still serving
STATE_AWAITING_CHECKPOINT = "awaiting_checkpoint"


class ReplicationProtocolError(Exception):
    """The leader's answers cannot be reconciled with the local state
    (revision gap, damaged frame, reclaimed artifact): re-bootstrap."""


# gate-off = no follower exists (the server requires --replicate-from
# AND the Replication gate before constructing one)
class ReplicaFollower:  # noqa: A004(built behind gate)
    """Tails one leader's replication API into a live TupleStore."""

    def __init__(self, store: TupleStore, transport,
                 identity: str = "replica",
                 groups: tuple = (),
                 poll_timeout_s: float = 25.0,
                 retry_backoff_s: float = 1.0,
                 registry: Optional[m.Registry] = None):
        self.store = store
        self.transport = transport
        self.identity = identity
        self.groups = tuple(groups)
        self.poll_timeout_s = poll_timeout_s
        self.retry_backoff_s = retry_backoff_s
        self.bootstrapped = False
        # once ANY state has been adopted, readiness never hard-fails
        # again: a re-bootstrap (leader restart, reclaimed tail) keeps
        # serving bounded-staleness reads from the existing store and
        # must report degraded-but-200, not eject every replica at once
        self.ever_bootstrapped = False
        self.state = STATE_BOOTSTRAPPING
        self.leader_id = ""
        self._boot_leader_id = ""  # incarnation the cursor belongs to
        self.leader_revision = 0
        self._cursor_seq = 0      # segment currently being tailed
        self._cursor_off = 0      # raw file bytes fully consumed from it
        self._caught_up_at: Optional[float] = None  # monotonic
        self._task: Optional[asyncio.Task] = None
        self._waiters: list = []  # (min_revision, future)
        self.stats = {"applied_records": 0, "applied_updates": 0,
                      "bootstraps": 0, "polls": 0, "poll_errors": 0,
                      "rebootstraps": 0}
        registry = registry or m.REGISTRY
        self._applied_bytes = registry.counter(
            "authz_replication_applied_bytes_total",
            "Bytes of leader WAL/checkpoint artifacts fetched and applied "
            "by this follower, by artifact kind", labels=("kind",))
        ref = weakref.ref(self)
        registry.gauge(
            "authz_replica_lag_revisions",
            "Leader revision minus the follower's applied revision "
            "(-1 = leader revision unknown yet)",
            callback=lambda: (ref().lag_revisions()
                              if ref() is not None else -1.0))
        registry.gauge(
            "authz_replica_lag_seconds",
            "Seconds since this follower last had the leader's newest "
            "revision fully applied (0 = caught up, -1 = never synced)",
            callback=lambda: (ref().lag_seconds()
                              if ref() is not None else -1.0))

    # -- lag accounting ------------------------------------------------------

    def lag_revisions(self) -> float:
        if self.leader_revision <= 0 and not self.bootstrapped:
            return -1.0
        return float(max(0, self.leader_revision - self.store.revision))

    def lag_seconds(self) -> float:
        if self._caught_up_at is None:
            return -1.0
        if self.store.revision >= self.leader_revision:
            return 0.0
        return time.monotonic() - self._caught_up_at

    def _note_progress(self) -> None:
        if self.store.revision >= self.leader_revision:
            self._caught_up_at = time.monotonic()
        rev = self.store.revision
        pending, self._waiters = self._waiters, []
        for min_rev, fut in pending:
            if rev >= min_rev:
                if not fut.done():
                    fut.set_result(True)
            else:
                self._waiters.append((min_rev, fut))

    async def wait_for_revision(self, min_revision: int,
                                timeout_s: float) -> bool:
        """Park until the applied revision reaches `min_revision` — the
        ZedToken wait path for a read whose token is ahead of the tail."""
        if self.store.revision >= min_revision:
            return True
        if timeout_s <= 0:
            return False
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append((min_revision, fut))
        try:
            await asyncio.wait_for(fut, timeout_s)
            return True
        except asyncio.TimeoutError:
            return self.store.revision >= min_revision
        finally:
            self._waiters = [(r, f) for r, f in self._waiters if f is not fut]

    # -- HTTP ----------------------------------------------------------------

    async def _request(self, target: str):
        from ...proxy.httpcore import Headers, Request
        h = Headers([("Accept", "application/json"),
                     ("X-Remote-User", self.identity)])
        for g in self.groups:
            h.add("X-Remote-Group", g)
        return await self.transport.round_trip(
            Request(method="GET", target=target, headers=h))

    async def _fetch_manifest(self, wait: bool) -> dict:
        import json
        target = "/replication/manifest"
        if wait:
            target += (f"?wait_revision={self.store.revision}"
                       f"&timeout_ms={int(self.poll_timeout_s * 1e3)}")
        resp = await self._request(target)
        if resp.status != 200:
            raise ConnectionError(
                f"manifest fetch failed: HTTP {resp.status}")
        man = json.loads(resp.body)
        self.leader_id = man.get("leader_id", "")
        self.leader_revision = int(man.get("revision", 0))
        return man

    async def _fetch_artifact(self, kind: str, name: str,
                              offset: int = 0) -> bytes:
        target = f"/replication/{kind}/{name}"
        if offset:
            target += f"?offset={offset}"
        resp = await self._request(target)
        if resp.status == 404:
            raise ReplicationProtocolError(
                f"{kind} {name!r} gone (reclaimed); re-bootstrap")
        if resp.status not in (200, 206):
            raise ConnectionError(
                f"{kind} {name!r} fetch failed: HTTP {resp.status}")
        return resp.body

    async def _spool_npz(self, body: bytes, prefix: str):
        """Spool fetched artifact bytes to a temp file and parse the
        columnar npz OFF the event loop (analyzer A001): a 1M-tuple
        checkpoint or bulk-load sidecar is tens of MB, and this loop is
        also serving every read on the replica — only the store
        adoption (already serialized by the store lock) stays on it.
        Returns (snap, overlay, meta) from load_columnar_file."""
        from ..persist import checkpoint as ckpt

        def _spool_and_parse():
            import tempfile
            import os
            fd, path = tempfile.mkstemp(suffix=".npz", prefix=prefix)
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(body)
                return ckpt.load_columnar_file(path)
            finally:
                try:
                    os.unlink(path)
                except OSError:
                    pass

        return await asyncio.get_running_loop().run_in_executor(
            None, _spool_and_parse)

    # -- bootstrap -----------------------------------------------------------

    async def _bootstrap(self, man: dict) -> None:
        cp = man.get("checkpoint")
        if cp is None:
            if self.store.revision > 0:
                # local state exists but the leader has no checkpoint to
                # re-anchor on; wait for its periodic checkpoint rather
                # than guessing at divergence
                self.state = STATE_AWAITING_CHECKPOINT
                return
            watermark = 0
        else:
            body = await self._fetch_artifact("checkpoint", cp["checkpoint"])
            self._applied_bytes.inc(len(body), kind="checkpoint")
            snap, overlay, _meta = await self._spool_npz(body,
                                                         "replica-ckpt-")
            self.store.replica_reset(snap if len(snap) else None, overlay,
                                     int(cp["revision"]))
            watermark = int(cp.get("watermark", 0))
        # position the cursor on the first segment past the watermark
        seqs = sorted(s["seq"] for s in man.get("segments", ()))
        nxt = [s for s in seqs if s > watermark]
        self._cursor_seq = nxt[0] if nxt else 0
        self._cursor_off = 0
        self._boot_leader_id = man.get("leader_id", "")
        self.bootstrapped = True
        self.ever_bootstrapped = True
        self.stats["bootstraps"] += 1
        self.state = STATE_STREAMING
        logger.info(
            "replica bootstrapped from %s at revision %d (watermark seg %d)",
            self.leader_id or "leader", self.store.revision, watermark)

    async def _rebootstrap(self, why: str) -> None:
        logger.warning("replica re-bootstrap (%s)", why)
        self.stats["rebootstraps"] += 1
        self.bootstrapped = False
        self.state = STATE_BOOTSTRAPPING
        await self._bootstrap(await self._fetch_manifest(wait=False))

    # -- record application --------------------------------------------------

    async def _apply_record(self, rec: dict) -> bool:
        """Apply one decoded WAL record; False when it predates the
        local revision (overlap from a re-fetch), True when applied."""
        rev = int(rec["r"])
        if rev <= self.store.revision:
            return False
        if rev != self.store.revision + 1:
            raise ReplicationProtocolError(
                f"revision gap: follower at {self.store.revision}, "
                f"next shipped record {rev}")
        kind = rec["k"]
        if kind == "d":
            updates = [
                RelationshipUpdate(
                    UpdateOp.DELETE if op == "d" else UpdateOp.TOUCH,
                    parse_relationship(s))
                for op, s in rec.get("u", ())]
            self.store.apply_replica_batch(updates)
            self.stats["applied_updates"] += len(updates)
        elif kind == "s":
            body = await self._fetch_artifact("segment", rec["f"])
            self._applied_bytes.inc(len(body), kind="sidecar")
            snap, _overlay, _meta = await self._spool_npz(body,
                                                          "replica-snap-")
            self.store.bulk_load_snapshot(snap)
        elif kind == "b":
            self.store.bulk_load(
                [parse_relationship(s) for s in rec.get("u", ())])
        elif kind == "c":
            self.store.delete_all()
        else:
            raise ReplicationProtocolError(
                f"unknown shipped record kind {kind!r}")
        if self.store.revision != rev:
            raise ReplicationProtocolError(
                f"replica apply of kind {kind!r} landed at revision "
                f"{self.store.revision}, record says {rev}")
        self.stats["applied_records"] += 1
        return True

    async def _consume_segments(self, man: dict) -> int:
        """Fetch + apply whatever the manifest says is available past the
        cursor; returns records applied."""
        segs = {s["seq"]: s for s in man.get("segments", ())}
        applied = 0
        if self._cursor_seq == 0:
            if not segs:
                return 0
            self._cursor_seq = min(segs)
            self._cursor_off = 0
        while True:
            entry = segs.get(self._cursor_seq)
            if entry is None:
                later = sorted(s for s in segs if s > self._cursor_seq)
                if not later:
                    return applied  # nothing new yet
                if self._cursor_off > 0:
                    # mid-segment and the file vanished: reclaimed under
                    # a newer checkpoint while we were tailing it
                    raise ReplicationProtocolError(
                        f"segment seq {self._cursor_seq} reclaimed "
                        f"mid-tail")
                self._cursor_seq = later[0]
                continue
            if self._cursor_off >= int(entry["size"]):
                later = sorted(s for s in segs if s > self._cursor_seq)
                if entry["sealed"] and later:
                    self._cursor_seq, self._cursor_off = later[0], 0
                    continue
                return applied  # drained the open tail
            name = entry["name"]
            data = await self._fetch_artifact("segment", name,
                                              offset=self._cursor_off)
            if not data:
                return applied
            base = self._cursor_off
            if base == 0:
                if len(data) < len(SEGMENT_MAGIC):
                    return applied  # torn header: wait for more bytes
                if not data.startswith(SEGMENT_MAGIC):
                    raise ReplicationProtocolError(
                        f"segment {name}: bad magic")
                records, consumed = parse_frames(data, len(SEGMENT_MAGIC))
            else:
                records, consumed = parse_frames(data, 0)
            if (not records and entry["sealed"]
                    and base + len(data) >= int(entry["size"])
                    and consumed < len(data)):
                # a sealed segment with undecodable remainder can never
                # grow the missing bytes: damaged, not torn
                raise ReplicationProtocolError(
                    f"segment {name}: damaged frame at offset "
                    f"{base + consumed}")
            for rec in records:
                if await self._apply_record(rec):
                    applied += 1
            # `consumed` is relative to the fetched chunk when resuming
            # mid-file (base > 0) and absolute (incl. the magic) on a
            # fresh segment — `base + consumed` is the new raw offset
            # either way, since base is 0 in the fresh case
            self._applied_bytes.inc(consumed, kind="segment")
            self._cursor_off = base + consumed if base else consumed
            if not records:
                return applied  # torn tail: wait for the next poll

    # -- sync driver ---------------------------------------------------------

    async def sync_once(self, wait: bool = False) -> int:
        """One manifest poll + apply pass (deterministic unit for tests;
        `run()` loops it).  Returns records applied."""
        self.stats["polls"] += 1
        man = await self._fetch_manifest(wait=wait)
        if (self.bootstrapped
                and man.get("leader_id", "") != self._boot_leader_id):
            # a restarted (or replaced) leader restarts its segment
            # seqs: the byte cursor is meaningless against the new log
            await self._rebootstrap(
                f"leader incarnation changed "
                f"({self._boot_leader_id} -> {man.get('leader_id')})")
            man = await self._fetch_manifest(wait=False)
        if not self.bootstrapped:
            await self._bootstrap(man)
            if not self.bootstrapped:
                return 0  # awaiting a leader checkpoint
            man = await self._fetch_manifest(wait=False)
        try:
            applied = await self._consume_segments(man)
        except (ReplicationProtocolError, TornFrameError) as e:
            await self._rebootstrap(str(e))
            applied = 0
            if self.bootstrapped:
                # catch up in the same pass (a second protocol error
                # propagates to run()'s backoff rather than looping)
                man = await self._fetch_manifest(wait=False)
                applied = await self._consume_segments(man)
        self._note_progress()
        if self.bootstrapped:
            self.state = STATE_STREAMING
        return applied

    async def run(self) -> None:
        """Tail forever; leader outages degrade (keep serving local
        reads at the last applied revision) and retry with backoff."""
        backoff = self.retry_backoff_s
        while True:
            try:
                await self.sync_once(wait=self.bootstrapped)
                backoff = self.retry_backoff_s
                if not self.bootstrapped:
                    # un-bootstrapped polls don't long-poll (there is
                    # no revision to wait past): pace them, or an
                    # awaiting-checkpoint follower hammers the leader
                    await asyncio.sleep(self.retry_backoff_s)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.stats["poll_errors"] += 1
                if self.bootstrapped:
                    self.state = STATE_DEGRADED
                logger.warning("replication poll failed (%s); retrying in "
                               "%.1fs", e, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 15.0)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self.run())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    def snapshot(self) -> dict:
        """/debug/replication payload (follower role)."""
        return {"role": "follower", "state": self.state,
                "leader_id": self.leader_id,
                "leader_revision": self.leader_revision,
                "applied_revision": self.store.revision,
                "lag_revisions": self.lag_revisions(),
                "lag_seconds": round(self.lag_seconds(), 3),
                "cursor": {"seq": self._cursor_seq,
                           "offset": self._cursor_off},
                **self.stats}
