"""Benchmark workload models: the BASELINE.json config sweep.

Each generator returns (schema_text, relationships, query_subjects,
resource_type, permission) for one of the five north-star configs
(BASELINE.md):

1. namespace list Filter, e2e/rules.yaml style (CPU-baseline scale)
2. 10k-pod list, 100k direct tuples, depth-1 (no rewrites)
3. user -> group -> team -> namespace nested groups, depth-4 recursion
4. intersection + exclusion userset rewrites (RBAC-with-deny)
5. 1M-tuple multi-tenant graph, 256 concurrent list subjects
"""

from __future__ import annotations

import random
from dataclasses import dataclass


@dataclass
class Workload:
    name: str
    schema_text: str
    relationships: list          # rel strings
    subjects: list               # user ids issuing list requests
    resource_type: str
    permission: str
    expected_objects: int = 0    # size of the listed collection


NAMESPACE_SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

PODS_DEPTH1_SCHEMA = """
definition user {}
definition pod {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

NESTED_GROUPS_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition team {
  relation member: user | group#member
}
definition namespace {
  relation viewer: team#member | group#member | user
  permission view = viewer
}
definition pod {
  relation namespace: namespace
  relation creator: user
  permission view = creator + namespace->view
}
"""

RBAC_DENY_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition pod {
  relation assigned: user | group#member
  relation approved: group#member
  relation banned: user | group#member
  permission view = assigned & approved - banned
}
"""

CAVEATED_RBAC_SCHEMA = """
caveat within_quota(used int, quota int) { used < quota }
definition user {}
definition group {
  relation member: user | group#member | user with within_quota
}
definition pod {
  relation assigned: user | group#member | user with within_quota
  relation approved: group#member
  relation banned: user | group#member | user with within_quota
  permission view = assigned & approved - banned
}
"""

MULTITENANT_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition tenant {
  relation admin: user
  relation member: user | group#member
  permission access = admin + member
}
definition namespace {
  relation tenant: tenant
  relation viewer: user | group#member
  permission view = viewer + tenant->access
}
definition pod {
  relation namespace: namespace
  relation creator: user
  permission view = creator + namespace->view
}
"""


def namespace_baseline(n_namespaces: int = 200, n_users: int = 50,
                       seed: int = 0) -> Workload:
    """Config 1: the deploy/rules.yaml namespace list filter shape."""
    rng = random.Random(seed)
    rels = []
    for ns in range(n_namespaces):
        rels.append(f"namespace:ns{ns}#creator@user:u{rng.randrange(n_users)}")
        for u in rng.sample(range(n_users), rng.randint(0, 3)):
            rels.append(f"namespace:ns{ns}#viewer@user:u{u}")
    return Workload(
        name="namespace-baseline",
        schema_text=NAMESPACE_SCHEMA,
        relationships=sorted(set(rels)),
        subjects=[f"u{i}" for i in range(n_users)],
        resource_type="namespace",
        permission="view",
        expected_objects=n_namespaces,
    )


def pods_depth1(n_pods: int = 10_000, n_users: int = 1_000,
                n_tuples: int = 100_000, seed: int = 1) -> Workload:
    """Config 2: 10k-pod list, 100k direct tuples, no rewrites."""
    rng = random.Random(seed)
    rels = set()
    while len(rels) < n_tuples:
        p = rng.randrange(n_pods)
        u = rng.randrange(n_users)
        rel = "viewer" if rng.random() < 0.8 else "creator"
        rels.add(f"pod:ns{p % 100}/p{p}#{rel}@user:u{u}")
    return Workload(
        name="pods-depth1",
        schema_text=PODS_DEPTH1_SCHEMA,
        relationships=sorted(rels),
        subjects=[f"u{i}" for i in range(n_users)],
        resource_type="pod",
        permission="view",
        expected_objects=n_pods,
    )


def nested_groups(n_pods: int = 10_000, n_users: int = 2_000,
                  n_groups: int = 200, n_teams: int = 40,
                  n_namespaces: int = 100, seed: int = 2) -> Workload:
    """Config 3: user -> group -> group -> team -> namespace, depth-4
    recursive rewrite reaching pods through an arrow."""
    rng = random.Random(seed)
    rels = set()
    for u in range(n_users):
        rels.add(f"group:g{rng.randrange(n_groups)}#member@user:u{u}")
    for g in range(n_groups):
        if g % 3 == 0 and g + 1 < n_groups:
            rels.add(f"group:g{g + 1}#member@group:g{g}#member")
        rels.add(f"team:t{g % n_teams}#member@group:g{g}#member")
    for ns in range(n_namespaces):
        rels.add(f"namespace:ns{ns}#viewer@team:t{rng.randrange(n_teams)}#member")
    for p in range(n_pods):
        ns = p % n_namespaces
        rels.add(f"pod:ns{ns}/p{p}#namespace@namespace:ns{ns}")
        if rng.random() < 0.1:
            rels.add(f"pod:ns{ns}/p{p}#creator@user:u{rng.randrange(n_users)}")
    return Workload(
        name="nested-groups-depth4",
        schema_text=NESTED_GROUPS_SCHEMA,
        relationships=sorted(rels),
        subjects=[f"u{i}" for i in range(n_users)],
        resource_type="pod",
        permission="view",
        expected_objects=n_pods,
    )


def rbac_deny(n_pods: int = 10_000, n_users: int = 2_000,
              n_groups: int = 100, seed: int = 3) -> Workload:
    """Config 4: intersection + exclusion (assigned & approved - banned)."""
    rng = random.Random(seed)
    rels = set()
    for u in range(n_users):
        rels.add(f"group:g{rng.randrange(n_groups)}#member@user:u{u}")
        if rng.random() < 0.05:
            rels.add(f"group:blocked#member@user:u{u}")
    for p in range(n_pods):
        g = rng.randrange(n_groups)
        rels.add(f"pod:ns{p % 100}/p{p}#assigned@group:g{g}#member")
        rels.add(f"pod:ns{p % 100}/p{p}#approved@group:g{(g + rng.randrange(2)) % n_groups}#member")
        if rng.random() < 0.3:
            rels.add(f"pod:ns{p % 100}/p{p}#banned@group:blocked#member")
    return Workload(
        name="rbac-deny",
        schema_text=RBAC_DENY_SCHEMA,
        relationships=sorted(rels),
        subjects=[f"u{i}" for i in range(n_users)],
        resource_type="pod",
        permission="view",
        expected_objects=n_pods,
    )


def caveated_rbac(n_pods: int = 10_000, n_users: int = 2_000,
                  n_groups: int = 100, caveat_fraction: float = 0.15,
                  seed: int = 7) -> Workload:
    """Caveat-heavy variant of config 4 (round-4 VERDICT item 5): a
    `caveat_fraction` share of membership/assignment/ban tuples carry an
    UNDECIDABLE caveat (context lacks the quota), exercising the tri-state
    definite/maybe bitplane path of the ELL kernel — previously these
    queries dropped to the recursive host oracle at ~1.8e3 checks/s."""
    rng = random.Random(seed)

    def maybe_caveat():
        if rng.random() < caveat_fraction:
            # one in three carries a DECIDED context (compile-time resolve)
            roll = rng.random()
            if roll < 0.2:
                return '[caveat:within_quota:{"used": 1, "quota": 5}]'
            if roll < 0.33:
                return '[caveat:within_quota:{"used": 9, "quota": 5}]'
            return '[caveat:within_quota:{"used": 1}]'  # undecidable
        return ""

    rels = set()
    for u in range(n_users):
        rels.add(f"group:g{rng.randrange(n_groups)}#member@user:u{u}"
                 f"{maybe_caveat()}")
        if rng.random() < 0.05:
            rels.add(f"group:blocked#member@user:u{u}")
    for p in range(n_pods):
        g = rng.randrange(n_groups)
        rels.add(f"pod:ns{p % 100}/p{p}#assigned@group:g{g}#member")
        rels.add(f"pod:ns{p % 100}/p{p}#approved@group:"
                 f"g{(g + rng.randrange(2)) % n_groups}#member")
        if rng.random() < 0.3:
            rels.add(f"pod:ns{p % 100}/p{p}#banned@group:blocked#member")
        if rng.random() < 0.1:
            rels.add(f"pod:ns{p % 100}/p{p}#banned@user:"
                     f"u{rng.randrange(n_users)}{maybe_caveat()}")
    return Workload(
        name="caveats-rbac",
        schema_text=CAVEATED_RBAC_SCHEMA,
        relationships=sorted(rels),
        subjects=[f"u{i}" for i in range(n_users)],
        resource_type="pod",
        permission="view",
        expected_objects=n_pods,
    )


def multitenant_1m(n_tenants: int = 100, n_users: int = 50_000,
                   n_groups: int = 2_000, n_namespaces: int = 2_000,
                   n_pods: int = 200_000, n_tuples: int = 1_000_000,
                   cold_subjects: float = 0.0, seed: int = 4) -> Workload:
    """Config 5: ~1M-tuple multi-tenant graph; subjects for 256 concurrent
    list requests.

    `cold_subjects` is the fraction of QUERY subjects that appear in no
    tuple at all (first-contact users): they exercise the phantom-column
    path instead of the compiled per-user columns (round-1 VERDICT item 7
    demanded a no-cliff bench for this)."""
    rng = random.Random(seed)
    rels = set()
    for u in range(n_users):
        rels.add(f"group:g{rng.randrange(n_groups)}#member@user:u{u}")
    for g in range(n_groups):
        t = rng.randrange(n_tenants)
        rels.add(f"tenant:t{t}#member@group:g{g}#member")
        if g % 7 == 0 and g + 1 < n_groups:
            rels.add(f"group:g{g + 1}#member@group:g{g}#member")
    for t in range(n_tenants):
        rels.add(f"tenant:t{t}#admin@user:u{rng.randrange(n_users)}")
    for ns in range(n_namespaces):
        rels.add(f"namespace:ns{ns}#tenant@tenant:t{ns % n_tenants}")
        if rng.random() < 0.2:
            rels.add(f"namespace:ns{ns}#viewer@group:g{rng.randrange(n_groups)}#member")
    for p in range(n_pods):
        ns = p % n_namespaces
        rels.add(f"pod:ns{ns}/p{p}#namespace@namespace:ns{ns}")
    # top up to the tuple target with direct pod viewers
    while len(rels) < n_tuples:
        p = rng.randrange(n_pods)
        rels.add(f"pod:ns{p % n_namespaces}/p{p}#viewer@user:u{rng.randrange(n_users)}")
    subjects = [f"u{i}" for i in range(n_users)]
    if cold_subjects > 0:
        n_cold = int(len(subjects) * cold_subjects)
        subjects[:n_cold] = [f"cold{i}" for i in range(n_cold)]
        rng.shuffle(subjects)
    return Workload(
        name="multitenant-1m",
        schema_text=MULTITENANT_SCHEMA,
        relationships=sorted(rels),
        subjects=subjects,
        resource_type="pod",
        permission="view",
        expected_objects=n_pods,
    )
