"""Decision-explain witnesses (authz/explain.py): golden differential
against the Python oracle evaluator across every schema construct, path
validity (each hop is a real store tuple), denial-path verification (the
acceptance criterion), and the jax iterate-capture path."""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.authz.explain import (
    Witness,
    device_witness,
    oracle_witness,
    witness_for,
)
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import (
    Evaluator,
    NO,
    MAYBE,
    YES,
)
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    ObjectRef,
    SubjectRef,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition group {
  relation member: user
}
definition org {
  relation admin: user
  permission manage = admin
}
definition folder {
  relation parent_org: org
  relation reader: user | group#member | user:*
  relation banned: user
  permission read = (reader + parent_org->manage) - banned
  permission audit = reader & banned
}
caveat only_tuesday(day string) {
  day == "tuesday"
}
definition doc {
  relation parent: folder
  relation viewer: user with only_tuesday
  permission view = viewer + parent->read
}
"""

RELS = [
    "org:acme#admin@user:root",
    "folder:f1#parent_org@org:acme",
    "folder:f1#reader@user:alice",
    "folder:f1#reader@group:eng#member",
    "folder:f1#reader@user:mallory",  # reader AND banned: exclusion case
    "folder:f1#banned@user:mallory",
    "folder:f2#reader@user:*",
    "folder:f2#banned@user:alice",
    "group:eng#member@user:carol",
    "doc:d1#parent@folder:f1",
    'doc:d2#viewer@user:dave[caveat:only_tuesday:{"day": "tuesday"}]',
    "doc:d3#viewer@user:erin[caveat:only_tuesday]",
]


def make_store():
    schema = sch.parse_schema(SCHEMA)
    store = TupleStore()
    store.bulk_load([parse_relationship(r) for r in RELS])
    return schema, store


# every construct: direct, userset, wildcard, arrow, union, intersection,
# exclusion, caveat-decided, caveat-undecided
CASES = [
    ("folder", "f1", "read", "alice"),     # direct reader
    ("folder", "f1", "read", "carol"),     # userset via group#member
    ("folder", "f1", "read", "root"),      # arrow parent_org->manage
    ("folder", "f1", "read", "mallory"),   # banned: exclusion denial
    ("folder", "f1", "read", "nobody"),    # plain denial
    ("folder", "f1", "audit", "alice"),    # intersection denial (not banned)
    ("folder", "f1", "audit", "mallory"),  # intersection admit (both legs)
    ("folder", "f2", "read", "bob"),       # wildcard admit
    ("folder", "f2", "read", "alice"),     # wildcard admitted, then banned
    ("doc", "d1", "view", "alice"),        # arrow into union chain
    ("doc", "d1", "view", "carol"),        # 3-hop chain
    ("doc", "d1", "view", "mallory"),      # excluded upstream
    ("doc", "d2", "view", "dave"),         # caveat decided true
    ("doc", "d3", "view", "erin"),         # caveat undecided: conditional
    ("doc", "d3", "view", "alice"),        # denial
]

_DECISION_OF = {NO: "denied", MAYBE: "conditional", YES: "allowed"}


class TestOracleWitnessGolden:
    @pytest.mark.parametrize("rtype,rid,perm,user", CASES)
    def test_decision_matches_oracle(self, rtype, rid, perm, user):
        schema, store = make_store()
        oracle = Evaluator(schema, store)
        subject = SubjectRef("user", user)
        resource = ObjectRef(rtype, rid)
        expected = _DECISION_OF[oracle.check3(resource, perm, subject)]
        w = oracle_witness(schema, store, resource, perm, subject)
        assert w.decision == expected, (rtype, rid, perm, user, w.to_dict())

    @pytest.mark.parametrize("rtype,rid,perm,user", CASES)
    def test_allowed_paths_are_real_tuples(self, rtype, rid, perm, user):
        """Every direct/wildcard/userset/arrow hop in an admitting chain
        must correspond to a live tuple in the store."""
        schema, store = make_store()
        subject = SubjectRef("user", user)
        w = oracle_witness(schema, store, ObjectRef(rtype, rid), perm,
                           subject)
        if w.decision == "denied":
            return
        assert w.path, w.to_dict()
        live = {r.rel_string().split("[")[0] for r in store.read(None)}
        for hop in w.path:
            if hop.via in ("direct", "userset", "arrow"):
                assert hop.rel_string() in live, (hop.rel_string(), live)
            elif hop.via == "wildcard":
                assert hop.rel_string().replace("user:*", "user:*") in live
        # the chain terminates at the querying subject (or a wildcard of
        # the subject's type)
        last = w.path[-1]
        assert last.subject in (f"user:{user}", "user:*")

    def test_allowed_iterations_is_hop_count(self):
        schema, store = make_store()
        w = oracle_witness(schema, store, ObjectRef("doc", "d1"), "view",
                           SubjectRef("user", "carol"))
        assert w.decision == "allowed"
        # doc:d1#view <- parent->read <- reader@group:eng#member <- member
        assert w.iterations == len(w.path) >= 3

    def test_exclusion_denial_names_excluding_path(self):
        """The acceptance case: an explained denial's relation path is
        verified against the oracle — mallory is denied folder:f1#read
        BECAUSE of the banned tuple, and the witness names it."""
        schema, store = make_store()
        oracle = Evaluator(schema, store)
        w = oracle_witness(schema, store, ObjectRef("folder", "f1"), "read",
                           SubjectRef("user", "mallory"))
        assert w.decision == "denied"
        assert w.path, "exclusion denial must carry the excluding chain"
        assert all(h.via == "exclusion" and not h.admitted for h in w.path)
        # the excluding hop is a real tuple AND the oracle confirms the
        # subtracted branch admits the subject
        assert w.path[0].rel_string() == "folder:f1#banned@user:mallory"
        assert oracle.check3(ObjectRef("folder", "f1"), "banned",
                             SubjectRef("user", "mallory")) == YES

    def test_plain_denial_probes_verified_against_oracle(self):
        """Each probed (searched-and-empty) hop really is denied per the
        oracle: the witness never claims a relation was empty when the
        oracle would have admitted through it."""
        schema, store = make_store()
        oracle = Evaluator(schema, store)
        w = oracle_witness(schema, store, ObjectRef("folder", "f1"), "read",
                           SubjectRef("user", "nobody"))
        assert w.decision == "denied" and w.probed
        for hop in w.probed:
            assert not hop.admitted
            if hop.via == "permission":
                rel = hop.rel_string()
                res, rest = rel.split("#", 1)
                relation, subj = rest.split("@", 1)
                rt, rid = res.split(":", 1)
                st, sid = subj.split(":", 1)
                assert oracle.check3(ObjectRef(rt, rid), relation,
                                     SubjectRef(st, sid)) == NO, rel

    def test_conditional_witness_marks_caveated_hop(self):
        schema, store = make_store()
        w = oracle_witness(schema, store, ObjectRef("doc", "d3"), "view",
                           SubjectRef("user", "erin"))
        assert w.decision == "conditional"
        assert any(h.caveated for h in w.path)

    def test_witness_serialization_round_trips(self):
        schema, store = make_store()
        import json

        for rtype, rid, perm, user in CASES:
            w = oracle_witness(schema, store, ObjectRef(rtype, rid), perm,
                               SubjectRef("user", user))
            d = json.loads(json.dumps(w.to_dict()))
            assert d["decision"] == w.decision


class TestDeviceWitness:
    def _compile(self, schema, store):
        from spicedb_kubeapi_proxy_tpu.ops.graph_compile import compile_graph
        return compile_graph(schema, store.read(None))

    def test_device_replay_matches_oracle_decisions(self):
        """The host replay of the kernel iterate agrees with the oracle
        on every non-caveated case (caveated tuples don't compile to
        definite edges)."""
        schema, store = make_store()
        oracle = Evaluator(schema, store)
        prog = self._compile(schema, store)
        for rtype, rid, perm, user in CASES:
            if rtype == "doc" and rid in ("d2", "d3"):
                continue  # caveat planes: covered by the oracle path
            sidx = prog.subject_index("user", user)
            tidx = prog.state_index(rtype, perm, rid)
            if sidx is None or tidx is None:
                continue  # outside the compiled universe
            w = device_witness(prog, sidx, tidx)
            expected = _DECISION_OF[oracle.check3(
                ObjectRef(rtype, rid), perm, SubjectRef("user", user))]
            # the replayed iterate has no MAYBE plane: denied==denied,
            # allowed==allowed
            assert w.decision == expected, (rtype, rid, perm, user)

    def test_device_chain_decodes_relation_hops(self):
        schema, store = make_store()
        prog = self._compile(schema, store)
        w = device_witness(prog,
                           prog.subject_index("user", "carol"),
                           prog.state_index("doc", "view", "d1"))
        assert w.decision == "allowed"
        assert w.backend == "device"
        assert w.iterations >= 1
        rels = [h.rel_string() for h in w.path]
        # the chain starts at the queried permission row and bottoms out
        # at carol's group membership
        assert rels[0].startswith("doc:d1#view@")
        assert any("group:eng" in r for r in rels)

    def test_admission_iteration_ordering(self):
        """Deeper chains admit at strictly later iterations."""
        schema, store = make_store()
        prog = self._compile(schema, store)
        shallow = device_witness(prog,
                                 prog.subject_index("user", "alice"),
                                 prog.state_index("folder", "reader", "f1"))
        deep = device_witness(prog,
                              prog.subject_index("user", "carol"),
                              prog.state_index("doc", "view", "d1"))
        assert shallow.decision == deep.decision == "allowed"
        assert shallow.iterations < deep.iterations


class TestJaxEndpointExplain:
    @pytest.fixture()
    def proxy(self):
        import sys
        sys.path.insert(0, str(__import__("pathlib").Path(
            __file__).resolve().parent))
        from test_proxy_e2e import make_proxy
        proxy, _ = make_proxy("jax://")

        async def warm():
            alice = proxy.get_embedded_client(user="alice")
            assert (await alice.get("/api/v1/pods")).status == 200
        asyncio.run(warm())
        return proxy

    def test_allowed_witness_carries_iteration(self, proxy):
        w = witness_for(proxy.endpoint, ObjectRef("pod", "team-a/p0"),
                        "view", SubjectRef("user", "alice"))
        assert isinstance(w, Witness)
        assert w.decision == "allowed"
        assert w.backend == "jax"
        assert w.iterations >= 1
        assert any("pod:team-a/p0#creator@user:alice" == h.rel_string()
                   for h in w.path)

    def test_denied_witness_verified_against_oracle(self, proxy):
        """Acceptance criterion: the explained denial's relation path is
        verified against the oracle evaluator."""
        inner = proxy.endpoint
        w = witness_for(inner, ObjectRef("pod", "team-b/p1"), "view",
                        SubjectRef("user", "alice"))
        assert w.decision == "denied"
        oracle = Evaluator(inner.schema, inner.store)
        assert oracle.check3(ObjectRef("pod", "team-b/p1"), "view",
                             SubjectRef("user", "alice")) == NO
        for hop in w.probed:
            res, rest = hop.rel_string().split("#", 1)
            relation, subj = rest.split("@", 1)
            rt, rid = res.split(":", 1)
            st, sid = subj.split(":", 1)
            assert oracle.check3(ObjectRef(rt, rid), relation,
                                 SubjectRef(st, sid)) == NO

    def test_explain_after_incremental_delta(self, proxy):
        """A grant written AFTER the graph compiled (device tables
        updated incrementally, program edge arrays stale) still explains
        correctly via the oracle fallback."""
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate,
            UpdateOp,
        )

        async def grant():
            await proxy.endpoint.write_relationships([RelationshipUpdate(
                UpdateOp.TOUCH,
                parse_relationship("pod:team-b/p1#viewer@user:alice"))])
        asyncio.run(grant())
        w = witness_for(proxy.endpoint, ObjectRef("pod", "team-b/p1"),
                        "view", SubjectRef("user", "alice"))
        assert w.decision == "allowed"
        assert any("viewer@user:alice" in h.rel_string() for h in w.path)

    def test_batching_endpoint_bypass_counted(self, proxy):
        batching = proxy.endpoint.inner  # Instrumented -> Batching
        base = batching.stats.get("explain_bypass", 0)
        witness_for(proxy.endpoint, ObjectRef("pod", "team-a/p0"), "view",
                    SubjectRef("user", "alice"))
        assert batching.stats.get("explain_bypass", 0) == base + 1
