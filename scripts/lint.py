"""Zero-dependency lint gate — THIN WRAPPER.

The rule implementations moved into scripts/analysis/legacy_lint.py
behind the unified analyzer driver (scripts/analyze.py, see
docs/static-analysis.md for the full catalog: F401/E722/B006/E711/
F811/W291/E501/TAB/E999 plus M001 metric-label cardinality, M002
docs-vs-registry metric drift, M003 hotpath fences).  This wrapper
keeps the historical CLI contract byte-compatible:

    python scripts/lint.py [paths...]     # exit 1 on any finding

Prefer `scripts/analyze.py --all` (adds the A-rules, noqa suppressions
and the baseline); this entry point applies neither — it reports raw
findings exactly as it always did.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from analysis.legacy_lint import run_legacy  # noqa: E402


def main():
    findings, n = run_legacy(sys.argv[1:] or None)
    for f in findings:
        print(f"{f.path}:{f.line}: {f.rule} {f.message}")
    print(f"lint: {n} files, {len(findings)} findings")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
