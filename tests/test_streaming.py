"""Bulk-check folding + streaming verbs (reference check.go:23-48,
lookups.go:74-135, activity.go:160-172).

- ALL check templates for a request fold into ONE CheckBulkPermissions call
  (round-1 issued one bulk RPC per check-expr).
- lookup_resources_stream / read_relationships_stream yield incrementally;
  the prefilter drains the stream so extraction overlaps transfer.
"""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.authz.check import (UnauthorizedError,
                                                   run_all_matching_checks)
from spicedb_kubeapi_proxy_tpu.authz.lookups import run_lookup_resources
from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.rules.engine import (ResolvedPreFilter,
                                                    compile_template_expression)
from spicedb_kubeapi_proxy_tpu.rules.relstring import ResolvedRel
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)


def rrel(s: str) -> ResolvedRel:
    """`type:id#rel@stype:sid` -> ResolvedRel (literal templates)."""
    res, _, sub = s.partition("@")
    rt, _, rest = res.partition(":")
    rid, _, rrl = rest.partition("#")
    st, _, sid = sub.partition(":")
    return ResolvedRel(resource_type=rt, resource_id=rid,
                       resource_relation=rrl, subject_type=st, subject_id=sid)

SCHEMA = """
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
definition pod {
  relation viewer: user
  permission view = viewer
}
"""


class CountingEndpoint(EmbeddedEndpoint):
    def __init__(self, schema):
        super().__init__(schema)
        self.bulk_calls = 0
        self.lr_calls = 0
        self.stream_calls = 0

    async def check_bulk_permissions(self, reqs):
        self.bulk_calls += 1
        return await super().check_bulk_permissions(reqs)

    async def lookup_resources(self, resource_type, permission, subject):
        self.lr_calls += 1
        return await super().lookup_resources(resource_type, permission,
                                              subject)

    async def lookup_resources_stream(self, resource_type, permission,
                                      subject):
        self.stream_calls += 1
        async for rid in super().lookup_resources_stream(
                resource_type, permission, subject):
            yield rid


def make_counting(rels):
    ep = CountingEndpoint(sch.parse_schema(SCHEMA))
    ep.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
                    for r in rels])
    return ep


class _FakeExpr:
    def __init__(self, *rels):
        self._rels = [rrel(r) for r in rels]

    def generate_relationships(self, input):
        return self._rels


class _FakeRule:
    def __init__(self, *exprs):
        self.checks = list(exprs)
        self.post_checks = []


class TestBulkCheckFolding:
    def test_single_bulk_call_across_rules_and_exprs(self):
        ep = make_counting([
            "namespace:ns1#viewer@user:alice",
            "pod:p1#viewer@user:alice",
            "pod:p2#viewer@user:alice",
        ])
        rules = [
            _FakeRule(_FakeExpr("namespace:ns1#view@user:alice"),
                      _FakeExpr("pod:p1#view@user:alice")),
            _FakeRule(_FakeExpr("pod:p2#view@user:alice")),
        ]
        asyncio.run(run_all_matching_checks(ep, rules, input=None))
        assert ep.bulk_calls == 1  # reference check.go:23-48: ONE bulk RPC

    def test_any_failure_unauthorized(self):
        ep = make_counting(["namespace:ns1#viewer@user:alice"])
        rules = [
            _FakeRule(_FakeExpr("namespace:ns1#view@user:alice"),
                      _FakeExpr("pod:p1#view@user:alice")),
        ]
        with pytest.raises(UnauthorizedError):
            asyncio.run(run_all_matching_checks(ep, rules, input=None))
        assert ep.bulk_calls == 1

    def test_no_templates_no_rpc(self):
        ep = make_counting([])
        asyncio.run(run_all_matching_checks(ep, [_FakeRule()], input=None))
        assert ep.bulk_calls == 0


class TestStreamingLookup:
    def test_default_stream_matches_list(self):
        ep = make_counting([f"pod:p{i}#viewer@user:alice" for i in range(10)])

        async def run():
            sub = SubjectRef("user", "alice")
            want = await ep.lookup_resources("pod", "view", sub)
            got = [r async for r in ep.lookup_resources_stream(
                "pod", "view", sub)]
            assert sorted(got) == sorted(want)
        asyncio.run(run())

    def test_jax_stream_matches_list_and_chunks(self):
        ep = JaxEndpoint(sch.parse_schema(SCHEMA))
        ep.store.bulk_load([parse_relationship(
            f"pod:p{i:05d}#viewer@user:alice") for i in range(5000)])

        async def run():
            sub = SubjectRef("user", "alice")
            got = [r async for r in ep.lookup_resources_stream(
                "pod", "view", sub)]
            want = await ep.lookup_resources("pod", "view", sub)
            assert got == want and len(got) == 5000
        asyncio.run(run())

    def test_prefilter_drains_stream(self):
        ep = make_counting([f"pod:ns/p{i}#viewer@user:alice" for i in range(4)])
        flt = ResolvedPreFilter(
            rel=rrel("pod:$#view@user:alice"),
            name_from_object_id=compile_template_expression(
                '{{split_name(resourceId)}}'),
            namespace_from_object_id=compile_template_expression(
                '{{split_namespace(resourceId)}}'),
        )

        async def run():
            res = await run_lookup_resources(ep, flt, input=None)
            assert res.allowed == {("ns", f"p{i}") for i in range(4)}
        asyncio.run(run())
        assert ep.stream_calls == 1

    def test_read_relationships_stream(self):
        ep = make_counting([f"pod:p{i}#viewer@user:alice" for i in range(6)])

        async def run():
            rels = [r async for r in ep.read_relationships_stream(None)]
            assert len(rels) == 6
        asyncio.run(run())
