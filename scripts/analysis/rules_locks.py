"""A003 — lock-order inversion, await-under-sync-lock, and sync-lock
self-deadlock.

The PR 5 finalizer bug is the template: a `weakref.finalize` callback
ran inside gc on a thread already holding the ledger/gauge lock and
re-acquired it — a self-deadlock no test provoked for four PRs.  The
PR 8 shedder snapshot deadlock was the two-lock variant.  This rule
builds the acquisition graph of every NAMED lock (`with self._lock:`
style sites — any name/attr chain whose last component contains "lock")
plus a one-level inter-procedural closure (calls made while holding a
lock contribute the callee's direct acquisitions), then flags:

  * cycles in the acquisition order (ABBA deadlocks waiting to happen);
  * `await` lexically under a SYNC lock — the loop parks inside the
    critical section, so every other coroutine needing the lock (or the
    loop) stalls behind an arbitrary-length await;
  * re-acquiring a lock created as `threading.Lock()` (not RLock) while
    already holding it, via the same one-level closure — the
    finalizer-class self-deadlock.

Lock identity: `self.X` -> "<ClassName>.X" (per-class), a longer
`self.a.b` chain -> "a.b" (shared object, e.g. every holder of
`store.lock` means THE TupleStore lock), a bare module global ->
"<module>.X".
"""

from __future__ import annotations

import ast

from .core import attr_chain


def _lock_id(chain: tuple, class_name: str, module: str):
    if not chain or "lock" not in chain[-1].lower():
        return None
    if chain[0] == "self":
        rest = chain[1:]
        if len(rest) == 1:
            return f"{class_name or module}.{rest[0]}"
        return ".".join(rest)
    if len(chain) == 1:
        return f"{module}.{chain[0]}"
    return ".".join(chain)


class _FuncInfo:
    def __init__(self, qual):
        self.qual = qual
        self.direct_locks: set = set()       # lock ids acquired anywhere
        self.nested: list = []               # (holder, acquired, line)
        self.calls_under: list = []          # (holder, callee_quals, line)
        self.sync_await: list = []           # (holder, line, node)
        self.reacquire: list = []            # (lock, line) same-lock nesting


class _LockWalker(ast.NodeVisitor):
    """Walk one function body tracking the held-lock stack; nested
    function defs are separate execution contexts and are not entered."""

    def __init__(self, src, info, class_name, module, self_methods,
                 module_funcs):
        self.src = src
        self.info = info
        self.class_name = class_name
        self.module = module
        self.self_methods = self_methods
        self.module_funcs = module_funcs
        self.stack: list = []     # (lock_id, is_sync)

    def visit_FunctionDef(self, node):
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def _with(self, node, is_sync):
        # items acquire LEFT TO RIGHT: each is pushed before the next is
        # checked, so `with a, b:` records the a->b edge (and a
        # re-acquire of a lock earlier in the SAME statement) exactly
        # like the nested form
        n_acquired = 0
        for item in node.items:
            lid = _lock_id(attr_chain(item.context_expr),
                           self.class_name, self.module)
            if lid is None:
                continue
            self.info.direct_locks.add(lid)
            for held, _hs in self.stack:
                if held == lid:
                    self.info.reacquire.append((lid, node.lineno))
                else:
                    self.info.nested.append((held, lid, node.lineno))
            self.stack.append((lid, is_sync))
            n_acquired += 1
        for stmt in node.body:
            self.visit(stmt)
        if n_acquired:
            del self.stack[-n_acquired:]
        # visit the context expressions too (call args may hide spawns)
        for item in node.items:
            self.visit(item.context_expr)

    def visit_With(self, node):
        self._with(node, True)

    def visit_AsyncWith(self, node):
        self._with(node, False)

    def visit_Await(self, node):
        sync_held = [lid for lid, is_sync in self.stack if is_sync]
        if sync_held:
            self.info.sync_await.append((sync_held[-1], node.lineno, node))
        self.generic_visit(node)

    def visit_Call(self, node):
        if self.stack:
            callees = self._resolve(node)
            if callees:
                holders = [lid for lid, _s in self.stack]
                self.info.calls_under.append(
                    (holders, callees, node.lineno))
        self.generic_visit(node)

    def _resolve(self, call: ast.Call) -> list:
        chain = attr_chain(call.func)
        if len(chain) == 2 and chain[0] == "self" and self.class_name:
            qual = f"{self.class_name}.{chain[1]}"
            if qual in self.self_methods:
                return [qual]
        elif len(chain) == 1 and chain[0] in self.module_funcs:
            return [chain[0]]
        return []


def _collect(src):
    module = src.rel.rsplit("/", 1)[-1].removesuffix(".py")
    infos: dict = {}
    lock_kinds: dict = {}
    # lock construction sites: self._x = threading.Lock() / RLock()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            ctor = attr_chain(node.value.func)
            if ctor[-1:] not in (("Lock",), ("RLock",)):
                continue
            for tgt in node.targets:
                chain = attr_chain(tgt)
                cls = src.enclosing_class(node)
                lid = _lock_id(chain, cls.name if cls else "", module)
                if lid is not None:
                    lock_kinds[lid] = ctor[-1]
    quals = set(src.qualnames.values())
    for node in ast.walk(src.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = src.qualnames.get(id(node), node.name)
        cls = src.enclosing_class(node)
        info = _FuncInfo(qual)
        walker = _LockWalker(
            src, info, cls.name if cls else "", module,
            self_methods=quals, module_funcs=quals)
        for stmt in node.body:
            walker.visit(stmt)
        infos[qual] = info
    return module, infos, lock_kinds


def rule_a003(sources) -> list:
    findings: list = []
    edges: dict = {}      # (a, b) -> (src, line, via)
    lock_kinds: dict = {}
    per_file = []
    for src in sources:
        module, infos, kinds = _collect(src)
        lock_kinds.update(kinds)
        per_file.append((src, infos))

    for src, infos in per_file:
        for info in infos.values():
            for held, acq, line in info.nested:
                edges.setdefault((held, acq), (src, line, "direct"))
            for lid, line in info.reacquire:
                if lock_kinds.get(lid, "RLock") != "RLock":
                    findings.append(src.finding(
                        "A003", line,
                        f"self-deadlock: re-acquiring non-reentrant lock "
                        f"`{lid}` while already holding it"))
            for lid, line, node in info.sync_await:
                findings.append(src.finding(
                    "A003", node,
                    f"`await` while holding sync lock `{lid}` — the "
                    f"critical section spans an arbitrary suspension; "
                    f"every thread needing the lock stalls behind it"))
    # one-level call closure: calls under a lock contribute the callee's
    # direct acquisitions (callee resolved within the same file)
    for src, infos in per_file:
        for info in infos.values():
            for holders, callees, line in info.calls_under:
                for callee in callees:
                    ci = infos.get(callee)
                    if ci is None:
                        continue
                    for acq in ci.direct_locks:
                        for held in holders:
                            if held == acq:
                                if lock_kinds.get(acq, "RLock") != "RLock":
                                    findings.append(src.finding(
                                        "A003", line,
                                        f"self-deadlock: `{callee}` "
                                        f"re-acquires non-reentrant "
                                        f"`{acq}` already held here"))
                            else:
                                edges.setdefault(
                                    (held, acq),
                                    (src, line, f"via call to {callee}"))

    findings.extend(_cycles(edges))
    return findings


def _cycles(edges) -> list:
    """Every elementary cycle in the (small) lock graph, each reported
    once at its lexically-first edge site."""
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles = set()
    findings = []

    def dfs(start, node, path):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                canon = tuple(sorted(path))
                if canon in seen_cycles:
                    continue
                seen_cycles.add(canon)
                ordered = path + [start]
                src, line, via = edges[(path[0], path[1])]
                findings.append(src.finding(
                    "A003", line,
                    f"lock-order cycle: "
                    f"{' -> '.join(ordered)} ({via}; an ABBA deadlock "
                    f"needs only two threads taking these in opposite "
                    f"order)"))
            elif nxt not in path and nxt in graph:
                dfs(start, nxt, path + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return findings
