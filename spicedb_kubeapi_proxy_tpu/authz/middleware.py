"""Authorization middleware (reference pkg/authz/authz.go WithAuthorization).

Per-request orchestration: extract ResolveInput -> match rules -> CEL filter
-> run Checks (concurrent bulk) -> dispatch to the update workflow / watch
filter / prefilter+response-filter / post-check / post-filter path.
"""

from __future__ import annotations


from ..proxy.httpcore import Handler, Request, Response, json_response
from ..proxy.kube import RequestInfo
from ..proxy.restmapper import CachingRESTMapper
from ..rules.engine import (
    ResolveError,
    filter_rules_with_cel_conditions,
    resolve_input_from_request)
from ..utils.tracing import span
from ..spicedb.endpoints import PermissionsEndpoint
from .check import (
    UnauthorizedError,
    run_all_matching_checks,
    run_all_matching_post_checks,
)
from .postfilter import filter_list_response
from .responsefilterer import (
    EmptyResponseFilterer,
    StandardResponseFilterer,
    WatchResponseFilterer,
)
from .rulesel import MultipleRulesError, single_pre_filter_rule, single_update_rule

UPDATE_VERBS = ("create", "update", "patch", "delete")

FILTERER_KEY = "response_filterer"


def forbidden_response(message: str) -> Response:
    return json_response(403, {
        "kind": "Status", "apiVersion": "v1", "metadata": {},
        "status": "Failure", "message": message, "reason": "Forbidden",
        "code": 403,
    })


def always_allow(info: RequestInfo) -> bool:
    """Unfiltered access to api metadata (reference authz.go:207-210)."""
    return info.path in ("/api", "/apis", "/openapi/v2") and info.verb == "get"


def should_run_post_checks(verb: str) -> bool:
    return verb == "get"


def should_run_post_filters(verb: str, rules_list: list) -> bool:
    return verb == "list" and any(r.post_filter for r in rules_list)


def with_authorization(handler: Handler, failed: Handler,
                       rest_mapper: CachingRESTMapper,
                       endpoint: PermissionsEndpoint,
                       matcher_ref,  # callable returning the current matcher
                       workflow_client=None,
                       input_extractor=None) -> Handler:
    """Build the authorization handler (reference authz.go:23-197).

    `matcher_ref` is a zero-arg callable returning the active MapMatcher so
    tests can swap rule sets at runtime (the reference exposes *Matcher)."""

    async def authorized(req: Request) -> Response:
        info: RequestInfo = req.context["request_info"]
        user = req.context["user"]
        # structured request logging (reference requestlogger.go +
        # rules.go:242-279): the logging middleware reads these back out
        # of the request context after the chain completes
        req.context["authz_outcome"] = "denied"
        try:
            with span("resolve", phase=True):
                if input_extractor is not None:
                    input = input_extractor(req, info, user)
                else:
                    input = resolve_input_from_request(
                        info, user, req.body, req.headers.to_dict())
        except ResolveError as e:
            return forbidden_response(str(e))
        req.context["resolve_input"] = input

        if always_allow(info):
            req.context["authz_outcome"] = "always_allow"
            req.context[FILTERER_KEY] = EmptyResponseFilterer()
            return await handler(req)

        # rule matching + CEL condition filtering are one attribution
        # phase: both walk the matched rule set against the request
        with span("match", phase=True) as match_attrs:
            matching_rules = matcher_ref().match(info)
            filtered_rules: list = []
            cel_failed = False
            if matching_rules:
                try:
                    filtered_rules = filter_rules_with_cel_conditions(
                        matching_rules, input)
                except ResolveError:
                    cel_failed = True
            match_attrs["rules"] = len(filtered_rules)
        if cel_failed or not filtered_rules:
            return await failed(req)
        req.context["matched_rules"] = [r.name for r in filtered_rules]

        try:
            # informational wrapper: the dispatch layer records the
            # queue_wait/execute phase spans for the bulk check itself
            with span("check"):
                await run_all_matching_checks(endpoint, filtered_rules, input)
        except (UnauthorizedError, ResolveError):
            return await failed(req)

        try:
            update_rule = single_update_rule(filtered_rules)
        except MultipleRulesError:
            return await failed(req)

        if update_rule is not None:
            if info.verb not in UPDATE_VERBS:
                return await failed(req)
            if workflow_client is None:
                return json_response(500, {
                    "kind": "Status", "apiVersion": "v1",
                    "status": "Failure", "code": 500,
                    "message": "update engine not configured"})
            from .update import perform_update
            try:
                req.context["authz_outcome"] = "allowed"
                with span("workflow", phase=True):
                    return await perform_update(update_rule, input, req,
                                                workflow_client)
            except Exception as e:
                return forbidden_response(f"failed to perform update: {e}")

        if info.verb == "watch":
            try:
                watch_rule = single_pre_filter_rule(filtered_rules)
            except MultipleRulesError:
                return await failed(req)
            if watch_rule is None:
                return await failed(req)
            filterer = WatchResponseFilterer(rest_mapper, input, watch_rule,
                                             endpoint)
            try:
                filterer.run_watcher()
            except Exception:
                return await failed(req)
            req.context[FILTERER_KEY] = filterer
            req.context["authz_outcome"] = "allowed"
            return await handler(req)

        filterer = StandardResponseFilterer(rest_mapper, input,
                                            filtered_rules, endpoint)
        req.context[FILTERER_KEY] = filterer
        try:
            filterer.run_pre_filters()
        except Exception:
            return await failed(req)

        if should_run_post_checks(info.verb):
            resp = await handler(req)
            if 200 <= resp.status < 300:
                try:
                    with span("postcheck"):
                        await run_all_matching_post_checks(
                            endpoint, filtered_rules, input)
                except (UnauthorizedError, ResolveError):
                    return await failed(req)
            req.context["authz_outcome"] = "allowed"
            return resp
        if should_run_post_filters(info.verb, filtered_rules):
            resp = await handler(req)
            if 200 <= resp.status < 300 and info.verb == "list":
                try:
                    with span("postfilter"):
                        body = await filter_list_response(
                            resp.body, filtered_rules, input, endpoint)
                except Exception:
                    return await failed(req)
                resp.body = body
                resp.headers.set("Content-Type", "application/json")
                resp.headers.set("Content-Length", str(len(body)))
            req.context["authz_outcome"] = "allowed"
            return resp
        req.context["authz_outcome"] = "allowed"
        return await handler(req)

    return authorized
