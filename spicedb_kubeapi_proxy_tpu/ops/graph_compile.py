"""Schema + tuple-snapshot -> TPU reachability program.

Lowers Zanzibar userset-rewrite evaluation onto an iterative boolean-SpMV
fixpoint (the BASELINE.json north star): the authorization state is one
boolean vector over `(slot, object)` pairs, relationship tuples become edges
whose one-step closure is a gather + segment-sum, and permission expressions
(union / intersection / exclusion / arrow) become an elementwise program over
slot ranges executed each iteration.  This replaces the reference's recursive
graph walk inside embedded SpiceDB (the dominant cost behind
CheckBulkPermissions / LookupResources — reference pkg/authz/check.go:48,
lookups.go:74-135).

State layout
------------
Every definition type T contributes:
  - a `self` slot (one-hot marks "this object IS the query subject"),
  - one slot per relation,
  - one slot per permission,
  - one slot per arrow occurrence in its permission expressions (aux).
Each slot spans T's object-id range; ranges are concatenated into one state
vector of size `state_size` (+1 trailing dead index used for edge padding).

Edges (all boolean-OR semantics, presorted by destination):
  - direct tuple  o#rel@u       : self(type(u))[u]        -> rel(type(o))[o]
  - userset tuple o#rel@s#r2    : slot(type(s), r2)[s]    -> rel(type(o))[o]
  - arrow tuple   o#left@s (for `left->target` in a permission of type(o)):
                                  slot(type(s), target)[s] -> aux[o]
Wildcard tuples (`o#rel@T:*`) are not edges: each (rel, subject-type) pair
yields a dense mask applied when any self(T) bit is live in the query column.

Per iteration: y = OR-SpMV(x); wildcard masks OR'd in; x = max(y, x0); then
permission slots are recomputed from x by the expression program.  All values
are monotone in x, so recomputation converges to the least fixpoint; the
iteration count bounds effective recursion depth exactly like SpiceDB's
dispatch depth cap (reference pkg/spicedb/spicedb.go:34).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..spicedb import schema as sch
from ..spicedb.types import SchemaError, WILDCARD

SELF_SLOT = "__self__"


# -- expression program -----------------------------------------------------

@dataclass(frozen=True)
class PRead:
    """Read a slot range (a relation/permission/aux vector of this type)."""
    offset: int
    length: int


@dataclass(frozen=True)
class PZero:
    length: int


@dataclass(frozen=True)
class PUnion:
    children: tuple


@dataclass(frozen=True)
class PIntersect:
    children: tuple


@dataclass(frozen=True)
class PExclude:
    base: object
    subtract: object


@dataclass(frozen=True)
class PermOp:
    """Write `expr` into [offset, offset+length) each iteration."""
    offset: int
    length: int
    expr: object


@dataclass(frozen=True)
class WildcardTerm:
    """OR `mask` into y wherever any self(subject-type) bit is live."""
    self_offset: int
    self_length: int
    mask_indices: tuple  # state indices activated by this wildcard


@dataclass
class GraphProgram:
    state_size: int                      # includes trailing dead index
    edge_src: np.ndarray                 # int32 [E] (sorted by dst)
    edge_dst: np.ndarray                 # int32 [E]
    # MAYBE-plane edges from caveated tuples whose stored context cannot
    # decide the caveat (tri-state device path; tuples whose context
    # decides True are ordinary definite edges, False-deciding tuples are
    # dropped entirely — matching Evaluator._caveat_value)
    cav_src: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))
    cav_dst: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))
    # False when a caveat shape has no device lowering (caveated wildcard,
    # unknown caveat name, non-bool caveat body): queries on affected
    # pairs must fall back to the host oracle
    caveats_device_ok: bool = True
    perm_ops: list = field(default_factory=list)       # topo-ordered PermOp
    wildcard_terms: list = field(default_factory=list)
    # (resource_type, left_relation) -> [(perm, occurrence, target, aux_slot)]
    # — the arrow edges each tuple on that relation contributes; consumed by
    # the jax endpoint's incremental delta path
    arrow_specs: dict = field(default_factory=dict)
    num_objects: dict = field(default_factory=dict)    # type -> count
    object_ids: dict = field(default_factory=dict)     # type -> list[str]
    object_index: dict = field(default_factory=dict)   # type -> {id: local}
    slot_offsets: dict = field(default_factory=dict)   # (type, slot) -> offset
    suggested_iterations: int = 8

    @property
    def dead_index(self) -> int:
        return self.state_size - 1

    # -- host-side lookups --------------------------------------------------

    def state_index(self, type_name: str, slot: str, object_id: str) -> Optional[int]:
        off = self.slot_offsets.get((type_name, slot))
        if off is None:
            return None
        local = self.object_index.get(type_name, {}).get(object_id)
        if local is None:
            return None
        return off + local

    def slot_range(self, type_name: str, slot: str) -> Optional[tuple]:
        off = self.slot_offsets.get((type_name, slot))
        if off is None:
            return None
        return off, self.num_objects[type_name]

    def subject_index(self, subject_type: str, subject_id: str,
                      subject_relation: str = "") -> Optional[int]:
        """State index whose one-hot encodes this query subject."""
        slot = subject_relation if subject_relation else SELF_SLOT
        return self.state_index(subject_type, slot, subject_id)


def _assign_slots(prog: GraphProgram, schema: sch.Schema) -> tuple:
    """Slot layout + arrow bookkeeping shared by both compilers; returns
    (arrow_slots, arrows_by_left)."""
    offset = 0
    arrow_slots: dict[tuple, str] = {}  # (type, perm, occurrence) -> slot name

    def add_slot(t: str, slot: str) -> None:
        nonlocal offset
        prog.slot_offsets[(t, slot)] = offset
        offset += prog.num_objects[t]

    for t, d in schema.definitions.items():
        add_slot(t, SELF_SLOT)
        for r in d.relations:
            add_slot(t, r)
        for p in d.permissions:
            add_slot(t, p)
        # aux slots for arrows, one per occurrence
        for p, expr in d.permissions.items():
            for k, arrow in enumerate(_find_arrows(expr)):
                slot = f"__arrow__:{p}:{k}"
                arrow_slots[(t, p, k)] = slot
                add_slot(t, slot)
    prog.state_size = offset + 1  # trailing dead index

    # arrow tuple-edge construction needs, per (type, left-relation), the
    # list of (perm, occurrence, target) arrows reading it
    arrows_by_left: dict[tuple, list] = {}
    for t, d in schema.definitions.items():
        for p, expr in d.permissions.items():
            for k, arrow in enumerate(_find_arrows(expr)):
                arrows_by_left.setdefault((t, arrow.left), []).append(
                    (p, k, arrow.target))
                prog.arrow_specs.setdefault((t, arrow.left), []).append(
                    (p, k, arrow.target, arrow_slots[(t, p, k)]))
    return arrow_slots, arrows_by_left


def _emit_tuple_edges(prog: GraphProgram, schema: sch.Schema,
                      arrow_slots: dict, arrows_by_left: dict, rel,
                      srcs: list, dsts: list, wildcard_map: dict,
                      cav_srcs: Optional[list] = None,
                      cav_dsts: Optional[list] = None,
                      cav_flags: Optional[dict] = None) -> None:
    """Per-tuple edge emission (object path; also used for overlay tuples
    on top of a columnar base).

    Caveated tuples (SURVEY.md hard part (c)): a stored context that
    DECIDES the caveat resolves at compile time — True emits ordinary
    definite edges, False emits nothing.  Undecidable tuples emit
    MAYBE-plane edges (`cav_srcs`/`cav_dsts`) consumed by the tri-state
    ELL kernel; shapes with no device lowering (wildcards, unknown
    caveats) clear `cav_flags['ok']` so affected queries fall back to the
    host oracle (the pre-round-4 behavior for ALL caveats)."""
    cav = getattr(rel, "caveat", None)
    if cav is not None:
        c = schema.caveats.get(cav.name)
        try:
            value = c.evaluate(cav.context()) if c is not None else None
        except Exception:
            value = None
            c = None  # evaluation error: no device story for this caveat
        if value is False:
            return
        if value is None:
            if cav_srcs is None or c is None or rel.subject.id == WILDCARD:
                if cav_flags is not None:
                    cav_flags["ok"] = False
                return
            # MAYBE: route every edge this tuple contributes to the
            # caveat plane
            srcs, dsts = cav_srcs, cav_dsts
        # value is True: definite — fall through unchanged
    rt = rel.resource.type
    if rt not in schema.definitions:
        return
    d = schema.definitions[rt]
    if rel.relation not in d.relations:
        return  # tuples on undefined relations are unreachable
    dst = prog.state_index(rt, rel.relation, rel.resource.id)
    st, sid, srel = rel.subject.type, rel.subject.id, rel.subject.relation
    if sid == WILDCARD:
        if dst is not None:
            wildcard_map.setdefault(st, []).append(dst)
    else:
        src = (prog.state_index(st, srel, sid) if srel
               else prog.state_index(st, SELF_SLOT, sid))
        if src is not None and dst is not None:
            srcs.append(src)
            dsts.append(dst)
    # arrow edges ride the same tuples (direct subjects only)
    for (p, k, target) in arrows_by_left.get((rt, rel.relation), ()):
        if sid == WILDCARD or srel:
            continue
        target_def = schema.definitions.get(st)
        if target_def is None or not target_def.has_relation_or_permission(target):
            continue
        src = prog.state_index(st, target, sid)
        aux = prog.state_index(rt, arrow_slots[(rt, p, k)], rel.resource.id)
        if src is not None and aux is not None:
            srcs.append(src)
            dsts.append(aux)


def _finalize_program(prog: GraphProgram, schema: sch.Schema,
                      src_arr: np.ndarray, dst_arr: np.ndarray,
                      wildcard_map: dict, arrow_slots: dict,
                      cav_srcs: Optional[list] = None,
                      cav_dsts: Optional[list] = None,
                      caveats_device_ok: bool = True) -> GraphProgram:
    """Sort edges, materialize wildcard terms and the permission program."""
    if cav_srcs:
        prog.cav_src = np.asarray(cav_srcs, np.int32)
        prog.cav_dst = np.asarray(cav_dsts, np.int32)
    prog.caveats_device_ok = caveats_device_ok
    if len(src_arr):
        order = np.argsort(dst_arr, kind="stable")
        prog.edge_src = np.ascontiguousarray(src_arr[order])
        prog.edge_dst = np.ascontiguousarray(dst_arr[order])

    for st, indices in wildcard_map.items():
        rng = prog.slot_range(st, SELF_SLOT)
        if rng is None:
            continue
        prog.wildcard_terms.append(WildcardTerm(
            self_offset=rng[0], self_length=rng[1],
            mask_indices=tuple(sorted(set(int(i) for i in indices)))))

    # permission program (topo order within each type)
    for t, d in schema.definitions.items():
        order = _topo_permissions(d)
        for p in order:
            expr = d.permissions[p]
            off, n = prog.slot_range(t, p)
            compiled = _compile_expr(prog, schema, t, p, expr, arrow_slots,
                                     counter=[0])
            prog.perm_ops.append(PermOp(offset=off, length=n, expr=compiled))

    prog.suggested_iterations = max(2, schema.max_rewrite_depth() + 2)
    return prog


def compile_graph(schema: sch.Schema, tuples: list,
                  extra_subject_ids: Optional[dict] = None) -> GraphProgram:
    """Build a GraphProgram from a schema and a tuple snapshot.

    `extra_subject_ids` ({type: iterable of ids}) registers objects that
    appear in queries but not (yet) in tuples, so checks against them index
    correctly instead of falling to the dead slot.
    """
    # -- collect object universes ------------------------------------------
    ids_by_type: dict[str, set] = {t: set() for t in schema.definitions}
    for rel in tuples:
        if rel.resource.type in ids_by_type:
            ids_by_type[rel.resource.type].add(rel.resource.id)
        if rel.subject.type in ids_by_type and rel.subject.id != WILDCARD:
            ids_by_type[rel.subject.type].add(rel.subject.id)
    if extra_subject_ids:
        for t, ids in extra_subject_ids.items():
            if t in ids_by_type:
                ids_by_type[t].update(ids)

    prog = GraphProgram(state_size=0, edge_src=np.zeros(0, np.int32),
                        edge_dst=np.zeros(0, np.int32))
    for t, ids in ids_by_type.items():
        ordered = sorted(ids)
        prog.object_ids[t] = ordered
        prog.object_index[t] = {oid: i for i, oid in enumerate(ordered)}
        prog.num_objects[t] = len(ordered)

    arrow_slots, arrows_by_left = _assign_slots(prog, schema)

    srcs: list[int] = []
    dsts: list[int] = []
    cav_srcs: list[int] = []
    cav_dsts: list[int] = []
    cav_flags = {"ok": True}
    wildcard_map: dict[str, list] = {}  # subject type -> [state indices]
    for rel in tuples:
        _emit_tuple_edges(prog, schema, arrow_slots, arrows_by_left, rel,
                          srcs, dsts, wildcard_map,
                          cav_srcs, cav_dsts, cav_flags)

    return _finalize_program(prog, schema,
                             np.asarray(srcs, np.int32),
                             np.asarray(dsts, np.int32),
                             wildcard_map, arrow_slots,
                             cav_srcs, cav_dsts, cav_flags["ok"])


def compile_graph_columnar(schema: sch.Schema, snap, rows: np.ndarray,
                           overlay: list = (),
                           extra_subject_ids: Optional[dict] = None
                           ) -> GraphProgram:
    """Vectorized compile from a columnar snapshot (spicedb/columnar.py).

    Produces a GraphProgram identical (up to intra-destination edge order)
    to `compile_graph` over the equivalent materialized tuples: the same
    object universes/slot layout, the same edge multiset, wildcard terms,
    and permission program.  `rows` selects the live base rows; `overlay`
    is the (small) list of post-bootstrap Relationship objects, emitted via
    the per-tuple path on top.
    """
    pool = snap.pool
    n_pool = len(pool)
    rtype = snap.rtype[rows]
    rid = snap.rid[rows]
    rel_c = snap.rel[rows]
    stype = snap.stype[rows]
    sid = snap.sid[rows]
    srel = snap.srel[rows]
    wc_ord = snap.ordinal(WILDCARD)

    # -- universes (vectorized per type) ------------------------------------
    prog = GraphProgram(state_size=0, edge_src=np.zeros(0, np.int32),
                        edge_dst=np.zeros(0, np.int32))
    # ord -> local index, per type (pool-backed ids; extras live in dicts)
    local_of_ord: dict[str, np.ndarray] = {}
    for t in schema.definitions:
        t_ord = snap.ordinal(t)
        if t_ord >= 0:
            res = rid[rtype == t_ord]
            sub = sid[(stype == t_ord) & (sid != wc_ord)]
            ords = np.unique(np.concatenate([res, sub])) if (len(res) or len(sub)) \
                else np.zeros(0, np.int32)
        else:
            ords = np.zeros(0, np.int32)
        id_strings = [pool[o] for o in ords]
        extras: set = set()
        if extra_subject_ids and t in extra_subject_ids:
            extras.update(extra_subject_ids[t])
        for r in overlay:
            if r.resource.type == t:
                extras.add(r.resource.id)
            if r.subject.type == t and r.subject.id != WILDCARD:
                extras.add(r.subject.id)
        extras.difference_update(id_strings)
        # numpy string sort for the (large) pool-backed id set; the (few)
        # extras are merged through a second vectorized sort
        arr = np.asarray(id_strings, dtype=str) if id_strings else \
            np.zeros(0, dtype="U1")
        order = np.argsort(arr, kind="stable")
        lo = np.full(n_pool, -1, np.int32)
        if extras:
            ex = np.asarray(sorted(extras), dtype=str)
            merged = np.concatenate([arr[order], ex]) if len(arr) else ex
            m_order = np.argsort(merged, kind="stable")
            ordered = merged[m_order].tolist()
            inv = np.empty(len(m_order), np.int32)
            inv[m_order] = np.arange(len(m_order), dtype=np.int32)
            if len(ords):
                lo[ords[order]] = inv[: len(arr)]
        else:
            ordered = arr[order].tolist()
            if len(ords):
                lo[ords[order]] = np.arange(len(order), dtype=np.int32)
        prog.object_ids[t] = ordered
        prog.object_index[t] = {oid: i for i, oid in enumerate(ordered)}
        prog.num_objects[t] = len(ordered)
        local_of_ord[t] = lo

    arrow_slots, arrows_by_left = _assign_slots(prog, schema)

    # -- edges (grouped by (rtype, rel, stype, srel), vectorized per group) --
    src_parts: list[np.ndarray] = []
    dst_parts: list[np.ndarray] = []
    wildcard_map: dict[str, list] = {}

    wc_rows = np.nonzero(sid == wc_ord)[0] if wc_ord >= 0 else ()
    for i in wc_rows:
        t = pool[rtype[i]]
        d = schema.definitions.get(t)
        if d is None or pool[rel_c[i]] not in d.relations:
            continue
        off = prog.slot_offsets.get((t, pool[rel_c[i]]))
        loc = local_of_ord[t][rid[i]] if t in local_of_ord else -1
        if off is not None and loc >= 0:
            wildcard_map.setdefault(pool[stype[i]], []).append(int(off + loc))

    direct = np.nonzero(sid != wc_ord)[0] if wc_ord >= 0 else \
        np.arange(len(rows))
    if len(direct):
        g_rt, g_rl = rtype[direct], rel_c[direct]
        g_st, g_sr = stype[direct], srel[direct]
        order = np.lexsort((g_sr, g_st, g_rl, g_rt))
        srt, srl = g_rt[order], g_rl[order]
        sst, ssr = g_st[order], g_sr[order]
        change = np.nonzero((np.diff(srt) != 0) | (np.diff(srl) != 0)
                            | (np.diff(sst) != 0) | (np.diff(ssr) != 0))[0] + 1
        bounds = np.concatenate([[0], change, [len(order)]])
        for gi in range(len(bounds) - 1):
            lo_b, hi_b = int(bounds[gi]), int(bounds[gi + 1])
            if lo_b == hi_b:
                continue
            t = pool[srt[lo_b]]
            relation = pool[srl[lo_b]]
            st = pool[sst[lo_b]]
            sr = pool[ssr[lo_b]]
            d = schema.definitions.get(t)
            if d is None or relation not in d.relations:
                continue
            rows_g = direct[order[lo_b:hi_b]]
            dst_off = prog.slot_offsets[(t, relation)]
            dst_loc = local_of_ord[t][rid[rows_g]]
            dst_state = (dst_off + dst_loc).astype(np.int32)
            # direct/userset edge
            src_slot = prog.slot_offsets.get((st, sr if sr else SELF_SLOT))
            if src_slot is not None and st in local_of_ord:
                src_loc = local_of_ord[st][sid[rows_g]]
                ok = (src_loc >= 0) & (dst_loc >= 0)
                src_parts.append((src_slot + src_loc[ok]).astype(np.int32))
                dst_parts.append(dst_state[ok])
            # arrow edges (direct subjects only)
            if not sr:
                for (p, k, target) in arrows_by_left.get((t, relation), ()):
                    target_def = schema.definitions.get(st)
                    if (target_def is None
                            or not target_def.has_relation_or_permission(target)):
                        continue
                    a_src_off = prog.slot_offsets.get((st, target))
                    a_dst_off = prog.slot_offsets.get(
                        (t, arrow_slots[(t, p, k)]))
                    if a_src_off is None or a_dst_off is None:
                        continue
                    src_loc = local_of_ord[st][sid[rows_g]]
                    ok = (src_loc >= 0) & (dst_loc >= 0)
                    src_parts.append((a_src_off + src_loc[ok]).astype(np.int32))
                    dst_parts.append((a_dst_off + dst_loc[ok]).astype(np.int32))

    # overlay tuples via the per-tuple path (the columnar base layer is
    # caveat-free by construction — store.py bulk_load_text — so caveated
    # tuples only ever arrive here)
    srcs_o: list[int] = []
    dsts_o: list[int] = []
    cav_srcs: list[int] = []
    cav_dsts: list[int] = []
    cav_flags = {"ok": True}
    for r in overlay:
        _emit_tuple_edges(prog, schema, arrow_slots, arrows_by_left, r,
                          srcs_o, dsts_o, wildcard_map,
                          cav_srcs, cav_dsts, cav_flags)
    if srcs_o:
        src_parts.append(np.asarray(srcs_o, np.int32))
        dst_parts.append(np.asarray(dsts_o, np.int32))

    src_arr = np.concatenate(src_parts) if src_parts else np.zeros(0, np.int32)
    dst_arr = np.concatenate(dst_parts) if dst_parts else np.zeros(0, np.int32)
    return _finalize_program(prog, schema, src_arr, dst_arr,
                             wildcard_map, arrow_slots,
                             cav_srcs, cav_dsts, cav_flags["ok"])


def relation_footprint(schema: sch.Schema, resource_type: str,
                       name: str) -> frozenset:
    """All (type, relation) pairs whose tuples can influence evaluation of
    `name` (a permission or relation) on `resource_type` — the compiled
    program's relation footprint.

    This is exactly the set of relation nodes reachable in the schema's
    dependency graph from (resource_type, name): a relation depends on
    itself and, through userset annotations (`viewer: group#member`), on
    the referenced (type, relation); a permission depends on the
    relations/permissions its expression reads, and an arrow
    `left->target` additionally on `target` at every subject type
    annotated on `left` (a conservative superset, like
    caveat_affected_pairs).  Wildcard and caveated tuples live on
    ordinary relations, so they are covered without special cases.

    Used by the decision cache (spicedb/decision_cache.py) for
    relation-scoped invalidation: a store delta touching relation R only
    invalidates cached decisions whose footprint contains R."""
    seen: set = set()
    rels: set = set()
    stack: list = [(resource_type, name)]

    def push_expr(t: str, d: sch.Definition, e: sch.Expr) -> None:
        if isinstance(e, sch.RelRef):
            stack.append((t, e.name))
        elif isinstance(e, sch.Arrow):
            stack.append((t, e.left))
            for ref in d.relations.get(e.left, ()):
                stack.append((ref.type, e.target))
        elif isinstance(e, (sch.Union, sch.Intersection)):
            for c in e.children:
                push_expr(t, d, c)
        elif isinstance(e, sch.Exclusion):
            push_expr(t, d, e.base)
            push_expr(t, d, e.subtract)
        # Nil reads nothing

    while stack:
        node = stack.pop()
        if node in seen:
            continue
        seen.add(node)
        t, n = node
        d = schema.definitions.get(t)
        if d is None:
            continue
        if n in d.relations:
            rels.add((t, n))
            for ref in d.relations[n]:
                if ref.relation:
                    stack.append((ref.type, ref.relation))
            continue
        expr = d.permissions.get(n)
        if expr is not None:
            push_expr(t, d, expr)
    return frozenset(rels)


def caveat_affected_pairs(schema: sch.Schema, caveated_rels: set) -> set:
    """All (type, relation-or-permission) pairs whose evaluation could
    traverse a relation in `caveated_rels` ({(type, relation)} pairs that
    hold >=1 live caveated tuple).  Queries on these pairs are routed to
    the host oracle (tri-state Kleene evaluation); everything else stays on
    the kernel.  Static over the schema, so it is a superset of the truly
    affected queries — correct, and empty when no caveated tuples exist."""
    affected = set(caveated_rels)

    def expr_affected(t: str, d: sch.Definition, e: sch.Expr) -> bool:
        if isinstance(e, sch.Nil):
            return False
        if isinstance(e, sch.RelRef):
            return (t, e.name) in affected
        if isinstance(e, sch.Arrow):
            if (t, e.left) in affected:
                return True
            for ref in d.relations.get(e.left, ()):
                if (ref.type, e.target) in affected:
                    return True
            return False
        if isinstance(e, (sch.Union, sch.Intersection)):
            return any(expr_affected(t, d, c) for c in e.children)
        if isinstance(e, sch.Exclusion):
            return (expr_affected(t, d, e.base)
                    or expr_affected(t, d, e.subtract))
        raise SchemaError(f"unknown expression {e!r}")

    changed = True
    while changed:
        changed = False
        for t, d in schema.definitions.items():
            for r, refs in d.relations.items():
                if (t, r) in affected:
                    continue
                for ref in refs:
                    if ref.relation and (ref.type, ref.relation) in affected:
                        affected.add((t, r))
                        changed = True
                        break
            for p, expr in d.permissions.items():
                if (t, p) in affected:
                    continue
                if expr_affected(t, d, expr):
                    affected.add((t, p))
                    changed = True
    return affected


def _find_arrows(expr: sch.Expr) -> list:
    out = []

    def walk(e: sch.Expr) -> None:
        if isinstance(e, sch.Arrow):
            out.append(e)
        elif isinstance(e, (sch.Union, sch.Intersection)):
            for c in e.children:
                walk(c)
        elif isinstance(e, sch.Exclusion):
            walk(e.base)
            walk(e.subtract)

    walk(expr)
    return out


def _compile_expr(prog: GraphProgram, schema: sch.Schema, t: str, perm: str,
                  expr: sch.Expr, arrow_slots: dict, counter: list):
    n = prog.num_objects[t]
    if isinstance(expr, sch.Nil):
        return PZero(n)
    if isinstance(expr, sch.RelRef):
        off, ln = prog.slot_range(t, expr.name)
        return PRead(off, ln)
    if isinstance(expr, sch.Arrow):
        k = counter[0]
        counter[0] += 1
        off, ln = prog.slot_range(t, arrow_slots[(t, perm, k)])
        return PRead(off, ln)
    if isinstance(expr, sch.Union):
        return PUnion(tuple(
            _compile_expr(prog, schema, t, perm, c, arrow_slots, counter)
            for c in expr.children))
    if isinstance(expr, sch.Intersection):
        return PIntersect(tuple(
            _compile_expr(prog, schema, t, perm, c, arrow_slots, counter)
            for c in expr.children))
    if isinstance(expr, sch.Exclusion):
        base = _compile_expr(prog, schema, t, perm, expr.base, arrow_slots, counter)
        sub = _compile_expr(prog, schema, t, perm, expr.subtract, arrow_slots, counter)
        return PExclude(base, sub)
    raise SchemaError(f"unknown expression {expr!r}")


def _topo_permissions(d: sch.Definition) -> list:
    """Order permissions so intra-type references resolve in one pass;
    cycles fall back to declaration order (converge across iterations)."""
    deps: dict[str, set] = {}
    for p, expr in d.permissions.items():
        refs: set[str] = set()

        def walk(e: sch.Expr) -> None:
            if isinstance(e, sch.RelRef) and e.name in d.permissions:
                refs.add(e.name)
            elif isinstance(e, (sch.Union, sch.Intersection)):
                for c in e.children:
                    walk(c)
            elif isinstance(e, sch.Exclusion):
                walk(e.base)
                walk(e.subtract)

        walk(expr)
        deps[p] = refs

    ordered: list[str] = []
    visiting: set[str] = set()
    done: set[str] = set()

    def visit(p: str) -> None:
        if p in done or p in visiting:
            return
        visiting.add(p)
        for q in deps[p]:
            visit(q)
        visiting.discard(p)
        done.add(p)
        ordered.append(p)

    for p in d.permissions:
        visit(p)
    return ordered
