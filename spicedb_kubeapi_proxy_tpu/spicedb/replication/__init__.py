"""WAL-shipping read replication (ROADMAP item 3, docs/replication.md).

The durable store's segmented CRC-framed WAL + columnar checkpoints
(spicedb/persist) are already a replication log: WAL-before-visibility
ordering guarantees any shipped record is replayable, and the revision
counter is the ZedToken.  This package ships that log over HTTP:

- **Leader** (`leader.py` ReplicationHub): serves the live data dir —
  `/replication/manifest` (revision + artifact listing, with a long-poll
  "wait for revision > R" mode fed by the store's commit-listener hook),
  `/replication/segment/<name>` and `/replication/checkpoint/<name>`
  (raw bytes with offset/range semantics, safe-name validated).

- **Follower** (`follower.py` ReplicaFollower): bootstraps from the
  newest checkpoint, tails segments, applies records through the
  exact-replay `TupleStore.apply_replica_batch` path into the live
  store — driving the normal delta pipeline (device-graph deltas,
  decision-cache epoch bumps, watch events) — and re-bootstraps from
  the checkpoint instead of diverging when the tail is torn or
  reclaimed.

Consistency contract: a follower serves any read whose min-revision
(ZedToken, `X-Authz-Min-Revision`) it has already applied; fresher
reads wait up to `--replica-wait-ms` and then forward to the leader
(or 503 naming it).  Update verbs always go to the leader.  The
`Replication` feature gate is the killswitch: off, routes and follower
mode are inert and the proxy is exactly single-node.
"""

from .follower import ReplicaFollower, StaleLeaderError
from .leader import (
    INCARNATION_HEADER,
    LEADER_ID_HEADER,
    ReplicationHub,
    safe_artifact_name,
)

MIN_REVISION_HEADER = "X-Authz-Min-Revision"
REVISION_HEADER = "X-Authz-Revision"


def enabled() -> bool:
    """Replication gate accessor; unknown-gate errors fail CLOSED — a
    stripped gate registry must not accidentally serve the data dir."""
    try:
        from ...utils.features import GATES
        return GATES.enabled("Replication")
    except Exception:
        return False


__all__ = [
    "INCARNATION_HEADER",
    "LEADER_ID_HEADER",
    "MIN_REVISION_HEADER",
    "REVISION_HEADER",
    "ReplicaFollower",
    "ReplicationHub",
    "StaleLeaderError",
    "enabled",
    "safe_artifact_name",
]
