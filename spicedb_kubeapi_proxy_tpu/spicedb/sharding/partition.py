"""Tuple-space partition map: resource type -> shard leader.

The write path scales out by splitting the tuple space BY RESOURCE TYPE
across N independent leaders (each its own WAL, checkpoint lineage, and
replication tree) behind a thin router.  What makes type-partitioning
*provably* safe per-schema — rather than hoped-for — is the
`relation_footprint` closure (ops/graph_compile.py, Cedar's
analyzability angle, PAPERS.md): a permission whose closure only touches
relations of types co-located on one shard evaluates identically over
that shard's tuple subset and over the full store, because no tuple
outside the shard can influence it.  `PartitionMap.validate_schema`
enforces exactly that at startup: a permission (or proxy-rule template)
whose closure spans two shards is a hard configuration error unless the
operator routes the involved types to the same shard.

Internal bookkeeping types (lock / workflow / activity — the dual-write
engine's tuples, endpoints.INTERNAL_SCHEMA) are shard-agnostic: they
ride the shard of the batch that writes them (a dual-write's lock and
idempotency key land — and stay — on the same shard as the rule tuples
they guard, so lock contenders meet where the rule types live and a
router retry converges against that shard's key).  An internal-only
batch falls back to a stable hash of its resource id here; the
ShardedEndpoint additionally locates internal-only DELETE batches (a
dual-write's post-success lock release) on the shard that actually
holds the tuple, since the acquiring batch's rule types are not
recoverable from the release batch.  Internal-type READS fan out
across shards.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Optional

from .. import schema as sch
from ...ops.graph_compile import relation_footprint

# definitions the dual-write engine owns (endpoints.INTERNAL_SCHEMA);
# mirrored from schema_lint.INTERNAL_TYPES (import would be circular:
# schema_lint consumes PartitionMap for SL007/SL008)
INTERNAL_TYPES = frozenset(("lock", "workflow", "activity"))


class PartitionMapError(ValueError):
    """Malformed --partition-map / --shards configuration."""


class CrossShardWriteError(Exception):
    """A write batch touches resource types on two different shards —
    unroutable: no single leader can apply it atomically.  The
    footprint validation at startup makes this unreachable for
    rule-generated dual-writes; hitting it means a caller bypassed the
    schema (or the map changed under a live client)."""


def _stable_shard(key: str, n_shards: int) -> int:
    """Deterministic, process-independent shard for internal-type ids
    (crc32: stable across runs/hosts, unlike hash())."""
    return zlib.crc32(key.encode("utf-8")) % n_shards


class PartitionMap:
    """Explicit `type=shard` assignments plus a default shard.

    `n_shards` bounds every assignment; unassigned types land on
    `default_shard`.  The map is static configuration — exactly one map
    must be shared by the router and every shard leader."""

    def __init__(self, n_shards: int, assignments: Optional[dict] = None,
                 default_shard: int = 0):
        if n_shards < 1:
            raise PartitionMapError(f"n_shards must be >= 1, got {n_shards}")
        assignments = dict(assignments or {})
        for t, s in assignments.items():
            if not isinstance(s, int) or not (0 <= s < n_shards):
                raise PartitionMapError(
                    f"partition map assigns type {t!r} to shard {s!r}, "
                    f"outside the configured 0..{n_shards - 1} range")
        if not (0 <= default_shard < n_shards):
            raise PartitionMapError(
                f"default shard {default_shard} outside 0..{n_shards - 1}")
        self.n_shards = n_shards
        self.assignments = assignments
        self.default_shard = default_shard

    @classmethod
    def parse(cls, spec: str, n_shards: Optional[int] = None,
              default_shard: int = 0) -> "PartitionMap":
        """Parse the `--partition-map` flag value: comma-separated
        `type=shard` pairs (`pod=0,secret=1`).  When `n_shards` is
        omitted it is inferred as max(assigned shard) + 1."""
        assignments: dict = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            name, eq, raw = part.partition("=")
            name = name.strip()
            raw = raw.strip()
            if not eq or not name or not raw:
                raise PartitionMapError(
                    f"invalid partition-map entry {part!r}: want type=shard")
            try:
                shard = int(raw)
            except ValueError as e:
                raise PartitionMapError(
                    f"invalid shard id in partition-map entry {part!r}: "
                    f"{e}") from e
            if shard < 0:
                raise PartitionMapError(
                    f"negative shard id in partition-map entry {part!r}")
            if name in assignments and assignments[name] != shard:
                raise PartitionMapError(
                    f"type {name!r} assigned to two shards "
                    f"({assignments[name]} and {shard})")
            assignments[name] = shard
        if n_shards is None:
            n_shards = max(assignments.values(), default=0) + 1
        return cls(n_shards, assignments, default_shard=default_shard)

    # -- routing -------------------------------------------------------------

    def shard_for_type(self, resource_type: str) -> int:
        return self.assignments.get(resource_type, self.default_shard)

    def shard_of(self, resource_type: str, resource_id: str = "") -> int:
        """Shard of one tuple/query: schema types route by assignment;
        internal bookkeeping types route by a stable hash of the id so
        retries and lock contenders always meet on one shard."""
        if resource_type in INTERNAL_TYPES and resource_id:
            return _stable_shard(resource_id, self.n_shards)
        return self.shard_for_type(resource_type)

    def shard_for_updates(self, updates: Iterable) -> int:
        """Route one write batch to exactly one shard.  All non-internal
        resource types in the batch must co-locate (the footprint
        validation guarantees this for every rule-generated dual-write);
        internal bookkeeping tuples ride along.  An internal-only batch
        routes by the stable hash of its first resource id —
        deterministic, so a crashed router's retry of the same
        dual-write lands on the SAME shard and converges against that
        shard's idempotency key.  (The ShardedEndpoint refines this for
        internal-only DELETE batches — a lock release must land where
        the acquiring rule batch put the lock, which this map alone
        cannot know; see ShardedEndpoint._locate_internal_shard.)"""
        shards: set = set()
        first_internal: Optional[tuple] = None
        for u in updates:
            rtype = u.rel.resource.type
            if rtype in INTERNAL_TYPES:
                if first_internal is None:
                    first_internal = (rtype, u.rel.resource.id)
                continue
            shards.add(self.shard_for_type(rtype))
        if len(shards) > 1:
            raise CrossShardWriteError(
                f"write batch spans shards {sorted(shards)}: no single "
                f"leader can apply it atomically (run --lint-schema with "
                f"the partition map to find the offending rule)")
        if shards:
            return shards.pop()
        if first_internal is not None:
            return self.shard_of(*first_internal)
        return self.default_shard

    def shards_for_filter(self, flt) -> list:
        """Shards a RelationshipFilter can touch.  A typed filter on a
        schema type touches one shard; internal types (whose tuples ride
        the shard of the batch that wrote them) and untyped filters fan
        out to every shard."""
        rtype = getattr(flt, "resource_type", "") if flt is not None else ""
        if rtype and rtype not in INTERNAL_TYPES:
            return [self.shard_for_type(rtype)]
        return list(range(self.n_shards))

    def shards_for_types(self, object_types: Optional[Iterable[str]]) -> list:
        """Shards a watch over `object_types` must merge (None = all)."""
        if not object_types:
            return list(range(self.n_shards))
        out: set = set()
        for t in object_types:
            if t in INTERNAL_TYPES:
                return list(range(self.n_shards))
            out.add(self.shard_for_type(t))
        return sorted(out)

    # -- static validation (the footprint proof) -----------------------------

    def closure_types(self, schema: sch.Schema, resource_type: str,
                      name: str) -> frozenset:
        """Resource types whose tuples can influence (resource_type,
        name): the type itself plus every type appearing in the
        relation_footprint closure."""
        types = {resource_type}
        for t, _rel in relation_footprint(schema, resource_type, name):
            types.add(t)
        return frozenset(types)

    def closure_shards(self, schema: sch.Schema, resource_type: str,
                       name: str) -> dict:
        """shard -> sorted types of the closure, excluding internal
        bookkeeping types (they are shard-agnostic by design)."""
        out: dict = {}
        for t in self.closure_types(schema, resource_type, name):
            if t in INTERNAL_TYPES:
                continue
            out.setdefault(self.shard_for_type(t), []).append(t)
        return {k: sorted(v) for k, v in out.items()}

    def validate_schema(self, schema: sch.Schema,
                        rule_configs: Iterable = ()) -> tuple:
        """-> (errors, warnings), each a list of (where, message).

        Errors (SL007, hard startup failure): a permission or a proxy
        rule whose relation_footprint closure spans two shards — an
        unroutable evaluation/dual-write.  Warnings (SL008): a partition
        map key naming a type absent from the schema (a typo silently
        falls back to the default shard)."""
        errors: list = []
        warnings: list = []
        if self.n_shards > 1:
            for tname, d in sorted(schema.definitions.items()):
                if tname in INTERNAL_TYPES:
                    continue
                for pname in sorted(d.permissions):
                    spread = self.closure_shards(schema, tname, pname)
                    if len(spread) > 1:
                        errors.append((
                            f"{tname}#{pname}",
                            f"permission {tname}#{pname} has a relation "
                            f"footprint spanning shards "
                            f"{sorted(spread)}: {self._spread_desc(spread)}"
                            f" — co-locate these types in the partition "
                            f"map or split the permission"))
            for rule_name, types in self._rule_type_sets(schema,
                                                         rule_configs):
                spread: dict = {}
                for t in types:
                    if t in INTERNAL_TYPES or t not in schema.definitions:
                        continue
                    spread.setdefault(self.shard_for_type(t), []).append(t)
                if len(spread) > 1:
                    spread = {k: sorted(v) for k, v in spread.items()}
                    errors.append((
                        f"rule {rule_name}",
                        f"rule {rule_name!r} touches types on shards "
                        f"{sorted(spread)}: {self._spread_desc(spread)} — "
                        f"an unroutable dual-write (its checks and "
                        f"updates cannot land on one leader)"))
        for t in sorted(self.assignments):
            if t not in schema.definitions:
                warnings.append((
                    f"partition-map {t}",
                    f"partition map assigns type {t!r} to shard "
                    f"{self.assignments[t]}, but the schema defines no "
                    f"such type — tuples of a mistyped name would route "
                    f"to the default shard instead"))
        return errors, warnings

    @staticmethod
    def _spread_desc(spread: dict) -> str:
        return "; ".join(f"shard {k} holds {', '.join(v)}"
                         for k, v in sorted(spread.items()))

    def _rule_type_sets(self, schema: sch.Schema,
                        rule_configs: Iterable) -> list:
        """(rule_name, closure-expanded resource types) per rule: every
        type a rule's templates name, each expanded through its
        footprint closure when the template names a real permission or
        relation."""
        from ..schema_lint import _iter_rule_templates, _parse_template
        by_rule: dict = {}
        for rule_name, tpl in _iter_rule_templates(rule_configs or ()):
            parsed = _parse_template(tpl)
            if parsed is None:
                continue
            rtype, rel, stype, srel = parsed
            types = by_rule.setdefault(rule_name, set())
            types.add(rtype)
            d = schema.definitions.get(rtype)
            if d is not None and d.has_relation_or_permission(rel):
                types.update(t for t, _ in relation_footprint(schema,
                                                              rtype, rel))
            if srel and srel != "*":
                sd = schema.definitions.get(stype)
                if sd is not None and sd.has_relation_or_permission(srel):
                    types.add(stype)
                    types.update(
                        t for t, _ in relation_footprint(schema, stype,
                                                         srel))
        return sorted(by_rule.items())

    def describe(self) -> dict:
        return {"n_shards": self.n_shards,
                "default_shard": self.default_shard,
                "assignments": dict(sorted(self.assignments.items()))}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"PartitionMap(n_shards={self.n_shards}, "
                f"assignments={self.assignments}, "
                f"default_shard={self.default_shard})")


def partition_map_for_schema(schema: sch.Schema, n_shards: int,
                             default_shard: int = 0) -> PartitionMap:
    """Derive a footprint-compatible partition map for a schema: types
    entangled through any permission's closure form one co-location
    class (union-find over closure type sets), classes spread
    round-robin (largest first) over `n_shards`.  Used by the fuzz
    harness (random schemas need a valid map per seed) and as a
    starting point for operators."""
    parent: dict = {}

    def find(x):
        parent.setdefault(x, x)
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[rb] = ra

    types = [t for t in schema.definitions if t not in INTERNAL_TYPES]
    for t in types:
        find(t)
    for tname, d in schema.definitions.items():
        if tname in INTERNAL_TYPES:
            continue
        for pname in d.permissions:
            closure = {tname}
            closure.update(t for t, _ in relation_footprint(schema, tname,
                                                            pname))
            closure = [t for t in closure
                       if t not in INTERNAL_TYPES and t in schema.definitions]
            for other in closure[1:]:
                union(closure[0], other)
        # a relation's userset annotation (`viewer: group#member`)
        # entangles the referenced type even outside any permission
        for refs in d.relations.values():
            for ref in refs:
                if (ref.relation and ref.type in schema.definitions
                        and ref.type not in INTERNAL_TYPES):
                    union(tname, ref.type)
    classes: dict = {}
    for t in types:
        classes.setdefault(find(t), []).append(t)
    assignments: dict = {}
    ordered = sorted(classes.values(), key=lambda c: (-len(c), sorted(c)))
    for i, cls_types in enumerate(ordered):
        shard = i % n_shards
        for t in cls_types:
            assignments[t] = shard
    return PartitionMap(n_shards, assignments, default_shard=default_shard)
