"""E2E parity scenarios from the reference suite (VERDICT r2 item 3):

- gzip through the proxy with a ~300KB object, both failure paths the
  reference guards: the workflow-engine write path and the reverse-proxy
  read path (reference e2e/proxy_test.go:1225-1290);
- proxy-level concurrent dual-write mutual exclusion, repeated 5x
  (reference proxy_test.go:889, MustPassRepeatedly(5));
- custom resource type (CRD-equivalent) registered in kubefake with its
  own rules (reference e2e/testresource-crd.yaml usage).
"""

import asyncio
import json

import pytest

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import (
    BUILTIN_TYPES,
    FakeKubeApiServer,
    ResourceType,
)
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
    H11Transport,
    HandlerTransport,
    HttpServer,
)
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.types import parse_relationship


def run(coro):
    return asyncio.run(coro)


SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  permission view = creator
}
definition configmap {
  relation creator: user
  permission view = creator
}
definition testresource {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

GZIP_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-configmaps}
match: [{apiVersion: v1, resource: configmaps, verbs: [create]}]
update:
  creates:
  - tpl: "configmap:{{namespacedName}}#creator@user:{{user.name}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-configmaps}
match: [{apiVersion: v1, resource: configmaps, verbs: [get]}]
check: [{tpl: "configmap:{{namespacedName}}#view@user:{{user.name}}"}]
"""


class TestGzipThroughProxy:
    """A large (~300KB) ConfigMap round-trips through the proxy over REAL
    HTTP with the upstream gzip-encoding its responses.  Exercises both
    reference failure paths: the workflow-engine kube write (CREATE) and
    the reverse-proxy filter read (GET) — each must see plaintext because
    the transport owns encoding negotiation."""

    def test_large_configmap_create_and_get(self):
        async def go():
            gzipped_paths = []
            kube = FakeKubeApiServer()

            async def recording_kube(req):
                resp = await kube(req)
                if resp.headers.get("Content-Encoding") == "gzip":
                    gzipped_paths.append(req.path)
                return resp

            upstream_srv = HttpServer(recording_kube)
            port = await upstream_srv.start("127.0.0.1", 0)
            try:
                proxy = ProxyServer(Options(
                    spicedb_endpoint="embedded://",
                    bootstrap=Bootstrap(schema_text=SCHEMA),
                    rules_yaml=GZIP_RULES,
                    upstream_transport=H11Transport(
                        f"http://127.0.0.1:{port}"),
                ))
                proxy.enable_dual_writes()
                paul = proxy.get_embedded_client(user="paul")

                # ~300KB payload: far over the fake apiserver's 1KB gzip
                # threshold (the real apiserver's is ~128KB)
                payload = "x" * (300 * 1024)
                cm = {"apiVersion": "v1", "kind": "ConfigMap",
                      "metadata": {"name": "large-cm", "namespace": "ns1"},
                      "data": {"payload": payload}}

                # CREATE goes through the workflow engine -> kube write
                # activity -> H11Transport; kube gzips the 201 response
                resp = await paul.post(
                    "/api/v1/namespaces/ns1/configmaps", cm)
                assert resp.status in (200, 201), (resp.status,
                                                   resp.body[:300])
                created = json.loads(resp.body)  # plaintext, not gzip bytes
                assert created["data"]["payload"] == payload

                # GET goes through the reverse proxy + response filterer;
                # kube gzips the 300KB 200 response
                resp = await paul.get(
                    "/api/v1/namespaces/ns1/configmaps/large-cm")
                assert resp.status == 200, (resp.status, resp.body[:300])
                fetched = json.loads(resp.body)
                assert fetched["data"]["payload"] == payload

                # intruder without the creator tuple is denied
                resp = await proxy.get_embedded_client(user="eve").get(
                    "/api/v1/namespaces/ns1/configmaps/large-cm")
                assert resp.status == 403

                # the upstream really did gzip both hops — otherwise this
                # test proves nothing
                assert len(gzipped_paths) >= 2, gzipped_paths
            finally:
                await upstream_srv.stop()
        run(go())


NS_CREATE_RULES_TMPL = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: create-namespaces}}
lock: {lock_mode}
match: [{{apiVersion: v1, resource: namespaces, verbs: [create]}}]
update:
  creates:
  - tpl: "namespace:{{{{name}}}}#creator@user:{{{{user.name}}}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: get-namespaces}}
match: [{{apiVersion: v1, resource: namespaces, verbs: [get]}}]
check: [{{tpl: "namespace:{{{{name}}}}#view@user:{{{{user.name}}}}"}}]
"""


class TestConcurrentDualWriteMutex:
    """Two clients race a create of the SAME object through the full proxy
    HTTP path; exactly one must win, the loser must get a conflict-class
    error (409 pessimistic-lock or 409 AlreadyExists optimistic).  The
    reference runs this with MustPassRepeatedly(5) because the interleaving
    is timing-dependent — we repeat 5x per lock mode."""

    @pytest.mark.parametrize("lock_mode", ["Pessimistic", "Optimistic"])
    def test_only_one_write_wins(self, lock_mode):
        async def go():
            kube = FakeKubeApiServer()
            rules = NS_CREATE_RULES_TMPL.format(lock_mode=lock_mode)
            proxy = ProxyServer(Options(
                spicedb_endpoint="embedded://",
                bootstrap=Bootstrap(schema_text=SCHEMA),
                rules_yaml=rules,
                upstream_transport=HandlerTransport(kube),
            ))
            proxy.enable_dual_writes()
            paul = proxy.get_embedded_client(user="paul")
            chani = proxy.get_embedded_client(user="chani")

            for attempt in range(5):
                ns_name = f"contested-{lock_mode.lower()}-{attempt}"
                ns_obj = {"apiVersion": "v1", "kind": "Namespace",
                          "metadata": {"name": ns_name}}

                async def create(client):
                    return await client.post("/api/v1/namespaces", ns_obj)

                r1, r2 = await asyncio.gather(create(paul), create(chani))
                statuses = sorted([r1.status, r2.status])
                assert statuses[0] in (200, 201), (attempt, statuses,
                                                   r1.body[:200],
                                                   r2.body[:200])
                assert statuses[1] == 409, (attempt, statuses,
                                            r1.body[:200], r2.body[:200])

                # the winner owns the namespace.  (The loser's tuples are
                # intentionally NOT asserted absent: when the 409 comes
                # from kube AlreadyExists — lock released before the loser
                # acquired it — the reference keeps the loser's tuples as
                # converged state, workflow.go isSuccessfulCreateOrUpdate.)
                winner = paul if r1.status in (200, 201) else chani
                assert (await winner.get(
                    f"/api/v1/namespaces/{ns_name}")).status == 200
        run(go())


CRD_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-testresources}
match: [{apiVersion: example.com/v1, resource: testresources, verbs: [get]}]
check: [{tpl: "testresource:{{namespacedName}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-testresources}
match: [{apiVersion: example.com/v1, resource: testresources, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources: {tpl: "testresource:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-testresources}
match: [{apiVersion: example.com/v1, resource: testresources, verbs: [create]}]
update:
  creates:
  - tpl: "testresource:{{namespacedName}}#creator@user:{{user.name}}"
"""


class TestCustomResourceType:
    """CRD-equivalent scenario: a new ResourceType registered at runtime
    (the reference applies e2e/testresource-crd.yaml) gets its own rules;
    get/list filtering and dual-write creation all work through the proxy,
    including discovery via the RESTMapper for the new group."""

    def _make(self):
        kube = FakeKubeApiServer(types=list(BUILTIN_TYPES) + [
            ResourceType("example.com", "v1", "testresources",
                         "TestResource", namespaced=True,
                         short_names=("tr",)),
        ])
        for name, ns in (("alpha", "team-a"), ("beta", "team-b")):
            kube.seed("example.com", "v1", "testresources", {
                "metadata": {"name": name, "namespace": ns},
                "spec": {"message": f"hello {name}"}})
        proxy = ProxyServer(Options(
            spicedb_endpoint="embedded://",
            bootstrap=Bootstrap(schema_text=SCHEMA),
            rules_yaml=CRD_RULES,
            upstream_transport=HandlerTransport(kube),
        ))
        proxy.enable_dual_writes()
        proxy.endpoint.store.bulk_load([parse_relationship(
            "testresource:team-a/alpha#viewer@user:alice")])
        return proxy, kube

    def test_get_and_list_filtered(self):
        proxy, _ = self._make()

        async def go():
            alice = proxy.get_embedded_client(user="alice")
            base = "/apis/example.com/v1"
            resp = await alice.get(
                f"{base}/namespaces/team-a/testresources/alpha")
            assert resp.status == 200, (resp.status, resp.body[:200])
            assert json.loads(resp.body)["spec"]["message"] == "hello alpha"
            assert (await alice.get(
                f"{base}/namespaces/team-b/testresources/beta")).status == 403

            resp = await alice.get(f"{base}/testresources")
            assert resp.status == 200, (resp.status, resp.body[:200])
            names = {i["metadata"]["name"]
                     for i in json.loads(resp.body)["items"]}
            assert names == {"alpha"}
        run(go())

    def test_dual_write_create(self):
        proxy, kube = self._make()

        async def go():
            bob = proxy.get_embedded_client(user="bob")
            base = "/apis/example.com/v1"
            tr = {"apiVersion": "example.com/v1", "kind": "TestResource",
                  "metadata": {"name": "gamma", "namespace": "team-c"},
                  "spec": {"message": "hi"}}
            resp = await bob.post(
                f"{base}/namespaces/team-c/testresources", tr)
            assert resp.status in (200, 201), (resp.status, resp.body[:300])
            # kube object exists
            key = ("example.com", "v1", "testresources")
            assert "gamma" in kube.objects[key]["team-c"]
            # tuple written -> bob can get + list it
            resp = await bob.get(
                f"{base}/namespaces/team-c/testresources/gamma")
            assert resp.status == 200
            resp = await bob.get(f"{base}/testresources")
            names = {i["metadata"]["name"]
                     for i in json.loads(resp.body)["items"]}
            assert names == {"gamma"}
        run(go())

    def test_unmatched_custom_group_forbidden(self):
        proxy, _ = self._make()

        async def go():
            alice = proxy.get_embedded_client(user="alice")
            # no rule matches anothertestresources (reference
            # proxy_test.go:371-399: unmatched custom GVR is forbidden)
            resp = await alice.get(
                "/apis/example.com/v1/anothertestresources")
            assert resp.status == 403
        run(go())
