"""CEL condition evaluator tests (shapes from reference rule `if` docs,
pkg/config/proxyrule/rule.go:58-77 and rules_test.go)."""

import pytest

from spicedb_kubeapi_proxy_tpu.rules import cel

ACT = {
    "request": {"verb": "get", "resource": "pods", "apiGroup": "",
                "apiVersion": "v1", "name": "pod1", "namespace": "default"},
    "user": {"name": "admin", "uid": "u1",
             "groups": ["system:masters", "dev"], "extra": {}},
    "name": "pod1",
    "resourceNamespace": "default",
    "namespacedName": "default/pod1",
    "headers": {"Accept": ["application/json"]},
}


def run(src, act=None):
    return cel.compile_condition(src).eval(act if act is not None else ACT)


class TestConditions:
    def test_verb_equality(self):
        assert run("request.verb == 'get'") is True
        assert run("request.verb == 'list'") is False

    def test_user_name(self):
        assert run("user.name == 'admin'") is True

    def test_group_membership(self):
        assert run("'system:masters' in user.groups") is True
        assert run("'nope' in user.groups") is False

    def test_namespace(self):
        assert run("resourceNamespace == 'default'") is True

    def test_compound(self):
        assert run("request.resource == 'pods' && request.verb in ['get', 'list']") is True

    def test_negation_and_or(self):
        assert run("!(user.name == 'bob') || false") is True

    def test_ternary(self):
        assert run("user.name == 'admin' ? true : false") is True

    def test_string_methods(self):
        assert run("user.name.startsWith('ad')") is True
        assert run("user.name.endsWith('min')") is True
        assert run("namespacedName.contains('/')") is True
        assert run("user.name.matches('^a.*n$')") is True

    def test_size(self):
        assert run("size(user.groups) == 2") is True
        assert run("user.groups.size() == 2") is True

    def test_has(self):
        assert run("has(user.name)") is True
        assert run("has(user.missing)") is False

    def test_in_map(self):
        assert run("'Accept' in headers") is True

    def test_arithmetic_comparison(self):
        assert run("1 + 2 * 3 == 7") is True
        assert run("10 / 3 == 3") is True
        assert run("-7 % 3 == -1") is True


class TestCompileGate:
    def test_non_boolean_rejected(self):
        with pytest.raises(cel.CELCompileError, match="must return a boolean"):
            cel.compile_condition("user.name")
        with pytest.raises(cel.CELCompileError, match="must return a boolean"):
            cel.compile_condition("name")
        with pytest.raises(cel.CELCompileError, match="must return a boolean"):
            cel.compile_condition("1 + 2")

    def test_boolean_accepted(self):
        cel.compile_condition("true")
        cel.compile_condition("has(user.name)")
        cel.compile_condition("size(user.groups) > 0")

    def test_syntax_error(self):
        with pytest.raises(cel.CELCompileError):
            cel.compile_condition("request.verb ==")
        with pytest.raises(cel.CELCompileError):
            cel.compile_condition("(a && b")


class TestEvalErrors:
    def test_missing_attribute(self):
        with pytest.raises(cel.CELEvalError):
            run("missing == 'x'", {"user": {}})

    def test_missing_key(self):
        with pytest.raises(cel.CELEvalError):
            run("user.nokey == 'x'")

    def test_type_error_in_logic(self):
        with pytest.raises(cel.CELEvalError):
            cel.compile_condition("user.name && true").eval(ACT)


class TestMacros:
    def test_exists(self):
        assert run("user.groups.exists(g, g == 'dev')") is True
        assert run("user.groups.exists(g, g == 'nope')") is False

    def test_all(self):
        assert run("user.groups.all(g, g.size() > 0)") is True
        assert run("user.groups.all(g, g == 'dev')") is False

    def test_exists_one(self):
        assert run("user.groups.exists_one(g, g == 'dev')") is True

    def test_macro_bad_args(self):
        with pytest.raises(cel.CELEvalError):
            run("user.groups.exists(g)")
