"""Deliberate evaluator mutations — the harness's own tripwire.

A differential fuzzer that never fires is indistinguishable from one
that cannot fire.  These context managers inject a *real* class of
kernel bug into the device compiler at runtime; the fixed-seed smoke
set must catch each one and shrink it to a small artifact
(tests/test_fuzz.py::TestMutationCheck, the ISSUE 12 mutation
acceptance).  They are test/tooling helpers — never imported by
production code.
"""

from __future__ import annotations

import contextlib


@contextlib.contextmanager
def wildcard_plane_skipped():
    """Compile device graphs with every wildcard term dropped — the
    `user:*` plane silently skipped, exactly the class of bug where one
    lowering path forgets a term class.  The host oracle is untouched,
    so any wildcard-granted answer diverges."""
    from ..ops import graph_compile as gc

    orig = gc._finalize_program

    def broken(prog, schema, src_arr, dst_arr, wildcard_map, arrow_slots,
               *args, **kwargs):
        return orig(prog, schema, src_arr, dst_arr, {}, arrow_slots,
                    *args, **kwargs)

    gc._finalize_program = broken
    try:
        yield
    finally:
        gc._finalize_program = orig


@contextlib.contextmanager
def exclusion_dropped():
    """Compile permission programs with `base - subtract` lowered as
    just `base` — the subtraction plane skipped.  Any banned/denied
    subject the oracle rejects shows up allowed on the device."""
    from ..ops import graph_compile as gc
    from ..spicedb import schema as sch

    orig = gc._compile_expr

    def broken(prog, schema, t, p, expr, arrow_slots, counter):
        if isinstance(expr, sch.Exclusion):
            expr = expr.base
        return orig(prog, schema, t, p, expr, arrow_slots, counter)

    gc._compile_expr = broken
    try:
        yield
    finally:
        gc._compile_expr = orig


MUTATIONS = {
    "wildcard-plane-skipped": wildcard_plane_skipped,
    "exclusion-dropped": exclusion_dropped,
}
