"""Multi-chip mesh smoke: boot the proxy on a sharded mesh endpoint
(`jax://?mesh=1x2`) over a forced 8-device virtual CPU host, drive
filtered LIST traffic through the full proxy stack, and assert parity
with the embedded host oracle under live write churn (wired into
scripts/check.sh; runs even with --fast).

What it proves end to end:
- the server boots with `mesh=1x2` parsed into a 2D (data x graph)
  mesh and the SHARDED ELL graph serving (not the single-chip path);
- a filtered LIST through the proxy returns exactly the oracle's
  visible set, before and after write churn (tuple adds/deletes
  absorbed by the sharded device tables with no full rebuild);
- /metrics carries per-device HBM ledger rows
  (`authz_device_shard_bytes{kind,device}`) for the sharded tables,
  one row per mesh device.
"""

import asyncio
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# must land before jax initializes its backend: the virtual device
# count is what gives `mesh=1x2` its two graph-axis devices
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import (  # noqa: E402
    FakeKubeApiServer)
from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import (  # noqa: E402
    _ShardedEllGraph)
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (  # noqa: E402
    HandlerTransport)
from spicedb_kubeapi_proxy_tpu.proxy.server import (  # noqa: E402
    Options, ProxyServer)
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap  # noqa: E402
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator  # noqa: E402
from spicedb_kubeapi_proxy_tpu.spicedb.types import (  # noqa: E402
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}

definition namespace {
    relation creator: user
    permission view = creator
}

definition pod {
    relation creator: user
    relation namespace: namespace
    permission view = creator + namespace->view
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
"""

N_PODS = 10


def fail(msg: str) -> None:
    print(f"mesh_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def delete(*rels):
    return [RelationshipUpdate(UpdateOp.DELETE, parse_relationship(r))
            for r in rels]


async def listed_pods(client) -> list:
    resp = await client.get("/api/v1/pods")
    if resp.status != 200:
        fail(f"/api/v1/pods -> {resp.status}: {resp.body[:200]}")
    items = json.loads(resp.body)["items"]
    return sorted(f"{i['metadata']['namespace']}/{i['metadata']['name']}"
                  for i in items)


def oracle_pods(oracle, user: str) -> list:
    return sorted(oracle.lookup_resources(
        "pod", "view", SubjectRef("user", user)))


async def assert_parity(clients, oracle, where: str) -> None:
    for user, client in clients.items():
        got = await listed_pods(client)
        want = [p for p in oracle_pods(oracle, user)
                if p.split("/", 1)[1].startswith("p")]
        if got != want:
            fail(f"filtered-list parity {where} for {user}: "
                 f"proxy={got} oracle={want}")


async def main() -> None:
    kube = FakeKubeApiServer()
    for i in range(N_PODS):
        kube.seed("", "v1", "pods",
                  {"metadata": {"name": f"p{i}", "namespace": "team-a"}})
    server = ProxyServer(Options(
        spicedb_endpoint="jax://?mesh=1x2",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
    ))
    ep = server.endpoint
    if ep.mesh is None or ep.mesh.shape != {"data": 1, "graph": 2}:
        fail(f"mesh=1x2 did not build a 1x2 mesh: {ep.mesh}")
    rels = ["namespace:team-a#creator@user:alice"] + [
        f"pod:team-a/p{i}#creator@user:bob" for i in range(0, N_PODS, 2)] + [
        f"pod:team-a/p{i}#creator@user:carol" for i in range(0, N_PODS, 3)]
    ep.store.bulk_load([parse_relationship(r) for r in rels])
    oracle = Evaluator(ep.schema, ep.store)

    await server.start("127.0.0.1", 0)
    try:
        clients = {u: server.get_embedded_client(user=u)
                   for u in ("alice", "bob", "carol", "stranger")}
        await assert_parity(clients, oracle, "at boot")
        if not isinstance(ep._graph, _ShardedEllGraph):
            fail(f"mesh=1x2 built {type(ep._graph).__name__}, "
                 f"not the sharded graph")

        # live write churn: adds + deletes absorbed by the sharded
        # tables (delta path), re-checked against the oracle
        rebuilds = ep.stats["rebuilds"]
        ep.store.write(touch("pod:team-a/p1#creator@user:bob",
                             "pod:team-a/p7#creator@user:carol"))
        ep.store.write(delete("pod:team-a/p0#creator@user:bob"))
        await assert_parity(clients, oracle, "after churn")
        if ep.stats["rebuilds"] != rebuilds:
            fail(f"write churn forced {ep.stats['rebuilds'] - rebuilds} "
                 f"full rebuild(s) — the sharded delta path regressed")

        # per-device HBM ledger rows for the sharded tables
        resp = await clients["alice"].get("/metrics")
        if resp.status != 200:
            fail(f"/metrics -> {resp.status}")
        text = resp.body.decode()
        devices = set()
        for line in text.splitlines():
            if (line.startswith("authz_device_shard_bytes{")
                    and 'kind="ell_main"' in line):
                devices.add(line.split('device="')[1].split('"')[0])
        if len(devices) != 2:
            fail(f"authz_device_shard_bytes{{kind=ell_main}} has rows for "
                 f"devices {sorted(devices)}, want exactly 2 (the 1x2 "
                 f"mesh's graph axis)")
    finally:
        await server.stop()
    print(f"mesh_smoke: OK (1x2 mesh, sharded graph, filtered-list "
          f"parity under churn, per-device ledger rows for devices "
          f"{sorted(devices)})")


if __name__ == "__main__":
    asyncio.run(main())
