"""A/B the staged (type-topological Gauss-Seidel) evaluate vs Jacobi on
the real multitenant-1m graph: executed sweeps + amortized wall time.

Run:  PYTHONPATH=/root/repo python scripts/probe_staged.py [reps]
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from spicedb_kubeapi_proxy_tpu.models import workloads as wl
from spicedb_kubeapi_proxy_tpu.ops.ell import compute_stages, make_ell_evaluate
from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef, parse_relationship


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    print("devices:", jax.devices(), flush=True)
    w = wl.multitenant_1m()
    schema = sch.parse_schema(w.schema_text)
    ep = JaxEndpoint(schema)
    ep.store.bulk_load([parse_relationship(r) for r in w.relationships])
    subjects = [SubjectRef("user", w.subjects[i]) for i in range(256)]
    with ep._lock:
        graph = ep._current_graph()
        q_arr, cols, _ = ep._encode_subjects(graph, subjects)
    prog = graph.prog
    rng = prog.slot_range(w.resource_type, w.permission)
    n_words = max(1, len(q_arr) // 32)
    kern = graph.kernel
    stages = compute_stages(prog)
    print(f"stages: {len(stages)} ranges {stages[:8]}", flush=True)

    q = jnp.asarray(q_arr)
    results = {}
    for name, st in (("jacobi", None), ("staged", stages)):
        evaluate = make_ell_evaluate(prog, kern.n_aux_rows, n_words,
                                     kern.num_iters,
                                     aux_passes=kern.aux_passes, stages=st)

        def run_lookup(q_idx, idx_main, idx_aux):
            x = evaluate(q_idx, idx_main, idx_aux)
            return jax.lax.dynamic_slice_in_dim(x, rng[0], rng[1], axis=0)

        fn = jax.jit(run_lookup)
        out = fn(q, graph.dev_main, graph.dev_aux)
        _ = int(np.asarray(out[0, 0]))  # force (tunnel: BUR can be a no-op)
        best = 1e9
        for _ in range(reps):
            t0 = time.perf_counter()
            o = fn(q, graph.dev_main, graph.dev_aux)
            _ = int(np.asarray(o[0, 0]))  # scalar fetch forces execution
            best = min(best, time.perf_counter() - t0)
        results[name] = (best, np.asarray(out))
        print(f"{name:8s} evaluate+slice: {best*1e3:7.1f} ms", flush=True)

        # executed sweeps
        from spicedb_kubeapi_proxy_tpu.ops.ell import (
            init_packed_state,
            make_ell_step,
        )
        step = make_ell_step(prog, kern.n_aux_rows,
                             aux_passes=kern.aux_passes, stages=st)

        def count_iters(q_idx, idx_main, idx_aux):
            x0 = init_packed_state(prog, kern.n_aux_rows, q_idx, n_words)

            def cond(s):
                return jnp.logical_and(s[1], s[2] < kern.num_iters)

            def body(s):
                x1 = step(s[0], x0, idx_main, idx_aux)
                return (x1, jnp.any(x1 != s[0]), s[2] + 1)

            return jax.lax.while_loop(cond, body,
                                      (x0, jnp.bool_(True), jnp.int32(0)))[2]

        it = int(jax.jit(count_iters)(q, graph.dev_main, graph.dev_aux))
        print(f"{name:8s} sweeps to fixpoint: {it}", flush=True)

    assert np.array_equal(results["jacobi"][1], results["staged"][1]), \
        "staged result differs from jacobi!"
    print("results identical; speedup "
          f"{results['jacobi'][0]/results['staged'][0]:.2f}x", flush=True)


if __name__ == "__main__":
    main()
