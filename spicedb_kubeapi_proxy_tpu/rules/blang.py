"""Template expression language for relationship templates ("blang").

A small, self-contained interpreter covering the subset of Bloblang that the
reference proxy's rule templates use (reference: pkg/rules/rules.go:1005-1051
compiles `{{ ... }}` template fields with a Bloblang environment, and
pkg/rules/env.go:13-58 registers the custom `split_name` / `split_namespace`
functions).  Supported forms, matching the expressions exercised by the
reference test corpus (pkg/rules/rules_test.go, tupleset_test.go):

- literals: strings ("..."), numbers, booleans, null, arrays ([a, b])
- `this` and implicit-this field paths: `user.name` == `this.user.name`
- field access `a.b.c`, indexing `a[0]`, `a["k"]`
- context capture: `expr.(name -> body)` — binds `name` to the value of
  `expr`; `this` inside `body` is unchanged (lexical named context)
- `let name = expr` statements (newline-separated), referenced as `$name`
- methods: `.map_each(expr)` / `.filter(expr)` (element bound to `this`),
  `.string()`, `.number()`, `.length()`, `.uppercase()`, `.lowercase()`,
  `.trim()`, `.contains(x)`, `.has_prefix(x)`, `.has_suffix(x)`,
  `.split(sep)`, `.join(sep)`, `.catch(fallback)`
- functions: registered per-environment (`split_name`, `split_namespace`)
- operators: `||` `&&` `==` `!=` `<` `<=` `>` `>=` `+` `-` `*` `/` `%` `!`,
  unary minus, and the catch/coalesce pipe `a | b` (null-or-error -> b)
- conditionals: `if cond { expr } else if cond { expr } else { expr }`

Evaluation is purely functional over plain Python data (dict/list/str/num).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Optional


class BlangError(Exception):
    """Compile- or eval-time error in a template expression."""


class BlangParseError(BlangError):
    pass


class BlangEvalError(BlangError):
    pass


_NULL = object()  # sentinel distinct from Python None (which means JSON null)


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_PUNCT = [
    "->", "==", "!=", "<=", ">=", "&&", "||",
    "(", ")", "[", "]", "{", "}", ".", ",", "|", "+", "-", "*", "/", "%",
    "<", ">", "!", "=", "$", ":", "?",
]

_KEYWORDS = {"if", "else", "let", "null", "true", "false", "this", "root"}


@dataclass
class Tok:
    kind: str  # 'ident' | 'num' | 'str' | 'punct' | 'kw' | 'eof' | 'nl'
    val: Any
    pos: int


def tokenize(src: str) -> list[Tok]:
    toks: list[Tok] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c == "\n":
            toks.append(Tok("nl", "\n", i))
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if c == "#":  # comment to end of line
            while i < n and src[i] != "\n":
                i += 1
            continue
        if c in "\"'":
            quote = c
            j = i + 1
            buf = []
            while j < n and src[j] != quote:
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", "\\": "\\", quote: quote}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise BlangParseError(f"unterminated string at {i}")
            toks.append(Tok("str", "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit():
            j = i
            while j < n and (src[j].isdigit() or (
                    src[j] == "." and j + 1 < n and src[j + 1].isdigit())):
                j += 1
            text = src[i:j]
            try:
                val = int(text) if "." not in text else float(text)
            except ValueError as e:
                raise BlangParseError(f"bad number {text!r} at {i}") from e
            toks.append(Tok("num", val, i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            toks.append(Tok("kw" if word in _KEYWORDS else "ident", word, i))
            i = j
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(Tok("punct", p, i))
                i += len(p)
                break
        else:
            raise BlangParseError(f"unexpected character {c!r} at {i}")
    toks.append(Tok("eof", None, n))
    return toks


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

class Node:
    __slots__ = ()


@dataclass
class Lit(Node):
    val: Any


@dataclass
class ArrayLit(Node):
    items: list


@dataclass
class ObjectLit(Node):
    items: list  # list of (key_node, value_node)


@dataclass
class This(Node):
    pass


@dataclass
class NameRef(Node):
    """Bare identifier: resolves to a named context if bound, else this.<name>."""
    name: str


@dataclass
class VarRef(Node):
    """`$name` — a `let` variable."""
    name: str


@dataclass
class Field(Node):
    base: Node
    name: str


@dataclass
class Index(Node):
    base: Node
    index: Node


@dataclass
class Call(Node):
    name: str
    args: list


@dataclass
class Method(Node):
    base: Node
    name: str
    args: list  # AST nodes; map_each/filter receive them unevaluated


@dataclass
class Capture(Node):
    base: Node
    name: str
    body: Node


@dataclass
class BinOp(Node):
    op: str
    left: Node
    right: Node


@dataclass
class Unary(Node):
    op: str
    operand: Node


@dataclass
class IfExpr(Node):
    cond: Node
    then: Node
    otherwise: Optional[Node]


@dataclass
class Mapping(Node):
    """A sequence of `let` statements followed by a final expression."""
    lets: list  # list of (name, Node)
    result: Node


# ---------------------------------------------------------------------------
# Parser (precedence climbing)
# ---------------------------------------------------------------------------

class _Parser:
    def __init__(self, toks: list[Tok]):
        self.toks = toks
        self.i = 0
        # While parsing a `let` statement's right-hand side, a newline at
        # bracket depth 0 terminates the expression instead of being skipped.
        self.stop_at_nl = False
        self.depth = 0

    def _skips_nl(self) -> bool:
        return not (self.stop_at_nl and self.depth == 0)

    def peek(self, skip_nl: bool = True) -> Tok:
        j = self.i
        while skip_nl and self._skips_nl() and self.toks[j].kind == "nl":
            j += 1
        return self.toks[j]

    def next(self, skip_nl: bool = True) -> Tok:
        while skip_nl and self._skips_nl() and self.toks[self.i].kind == "nl":
            self.i += 1
        t = self.toks[self.i]
        self.i += 1
        if t.kind == "punct":
            if t.val in ("(", "[", "{"):
                self.depth += 1
            elif t.val in (")", "]", "}"):
                self.depth -= 1
        return t

    def expect(self, kind: str, val: Any = None) -> Tok:
        t = self.next()
        if t.kind != kind or (val is not None and t.val != val):
            raise BlangParseError(f"expected {val or kind}, got {t.val!r} at {t.pos}")
        return t

    def at_punct(self, val: str) -> bool:
        t = self.peek()
        return t.kind == "punct" and t.val == val

    def eat_punct(self, val: str) -> bool:
        if self.at_punct(val):
            self.next()
            return True
        return False

    # mapping := (let ident = expr NL)* expr
    def parse_mapping(self) -> Node:
        lets: list[tuple[str, Node]] = []
        while True:
            t = self.peek()
            if t.kind == "kw" and t.val == "let":
                self.next()
                name = self.expect("ident").val
                self.expect("punct", "=")
                # the let RHS ends at the first newline outside brackets
                self.stop_at_nl = True
                try:
                    lets.append((name, self.parse_expr()))
                finally:
                    self.stop_at_nl = False
            else:
                break
        result = self.parse_expr()
        t = self.peek()
        if t.kind != "eof":
            raise BlangParseError(f"trailing input at {t.pos}: {t.val!r}")
        return Mapping(lets, result) if lets else result

    def parse_expr(self) -> Node:
        return self.parse_catch()

    def parse_catch(self) -> Node:
        left = self.parse_or()
        while self.at_punct("|") and not self.at_punct("||"):
            self.next()
            right = self.parse_or()
            left = BinOp("|", left, right)
        return left

    def parse_or(self) -> Node:
        left = self.parse_and()
        while self.at_punct("||"):
            self.next()
            left = BinOp("||", left, self.parse_and())
        return left

    def parse_and(self) -> Node:
        left = self.parse_cmp()
        while self.at_punct("&&"):
            self.next()
            left = BinOp("&&", left, self.parse_cmp())
        return left

    def parse_cmp(self) -> Node:
        left = self.parse_add()
        while True:
            t = self.peek()
            if t.kind == "punct" and t.val in ("==", "!=", "<", "<=", ">", ">="):
                self.next()
                left = BinOp(t.val, left, self.parse_add())
            else:
                return left

    def parse_add(self) -> Node:
        left = self.parse_mul()
        while True:
            t = self.peek()
            if t.kind == "punct" and t.val in ("+", "-"):
                self.next()
                left = BinOp(t.val, left, self.parse_mul())
            else:
                return left

    def parse_mul(self) -> Node:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "punct" and t.val in ("*", "/", "%"):
                self.next()
                left = BinOp(t.val, left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> Node:
        t = self.peek()
        if t.kind == "punct" and t.val in ("!", "-"):
            self.next()
            return Unary(t.val, self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self) -> Node:
        node = self.parse_primary()
        while True:
            if self.at_punct("."):
                self.next()
                if self.at_punct("("):
                    # context capture: .(name -> body)
                    self.next()
                    name = self.expect("ident").val
                    self.expect("punct", "->")
                    body = self.parse_expr()
                    self.expect("punct", ")")
                    node = Capture(node, name, body)
                    continue
                t = self.next()
                if t.kind not in ("ident", "kw"):
                    raise BlangParseError(f"expected field name at {t.pos}")
                name = t.val
                if self.at_punct("("):
                    node = Method(node, name, self._parse_args())
                else:
                    node = Field(node, name)
            elif self.at_punct("["):
                self.next()
                idx = self.parse_expr()
                self.expect("punct", "]")
                node = Index(node, idx)
            else:
                return node

    def _parse_args(self) -> list:
        self.expect("punct", "(")
        args: list[Node] = []
        if not self.at_punct(")"):
            args.append(self.parse_expr())
            while self.eat_punct(","):
                args.append(self.parse_expr())
        self.expect("punct", ")")
        return args

    def parse_primary(self) -> Node:
        t = self.peek()
        if t.kind == "str" or t.kind == "num":
            self.next()
            return Lit(t.val)
        if t.kind == "kw":
            if t.val in ("true", "false"):
                self.next()
                return Lit(t.val == "true")
            if t.val == "null":
                self.next()
                return Lit(None)
            if t.val in ("this", "root"):
                self.next()
                return This()
            if t.val == "if":
                return self._parse_if()
            raise BlangParseError(f"unexpected keyword {t.val!r} at {t.pos}")
        if t.kind == "ident":
            self.next()
            if self.at_punct("("):
                return Call(t.val, self._parse_args())
            return NameRef(t.val)
        if t.kind == "punct":
            if t.val == "$":
                self.next()
                name = self.expect("ident").val
                return VarRef(name)
            if t.val == "(":
                self.next()
                inner = self.parse_expr()
                self.expect("punct", ")")
                return inner
            if t.val == "[":
                self.next()
                items: list[Node] = []
                if not self.at_punct("]"):
                    items.append(self.parse_expr())
                    while self.eat_punct(","):
                        items.append(self.parse_expr())
                self.expect("punct", "]")
                return ArrayLit(items)
            if t.val == "{":
                self.next()
                pairs: list[tuple[Node, Node]] = []
                if not self.at_punct("}"):
                    pairs.append(self._parse_pair())
                    while self.eat_punct(","):
                        pairs.append(self._parse_pair())
                self.expect("punct", "}")
                return ObjectLit(pairs)
        raise BlangParseError(f"unexpected token {t.val!r} at {t.pos}")

    def _parse_pair(self) -> tuple[Node, Node]:
        key = self.parse_expr()
        self.expect("punct", ":")
        return key, self.parse_expr()

    def _parse_if(self) -> Node:
        self.expect("kw", "if")
        cond = self.parse_expr()
        self.expect("punct", "{")
        then = self.parse_expr()
        self.expect("punct", "}")
        otherwise: Optional[Node] = None
        t = self.peek()
        if t.kind == "kw" and t.val == "else":
            self.next()
            t2 = self.peek()
            if t2.kind == "kw" and t2.val == "if":
                otherwise = self._parse_if()
            else:
                self.expect("punct", "{")
                otherwise = self.parse_expr()
                self.expect("punct", "}")
        return IfExpr(cond, then, otherwise)


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------

@dataclass
class _Scope:
    this: Any
    names: dict  # named contexts from captures
    lets: dict   # $vars


def _truthy(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    raise BlangEvalError(f"expected boolean, got {type(v).__name__}")


def _to_string(v: Any) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        if math.isfinite(v) and v == int(v):
            return str(int(v))
        return repr(v)
    if v is None:
        return "null"
    raise BlangEvalError(f"cannot convert {type(v).__name__} to string")


class Environment:
    """An expression environment with registered global functions.

    Mirrors the role of the reference's custom Bloblang environment
    (pkg/rules/env.go:13-58).
    """

    def __init__(self) -> None:
        self._functions: dict[str, Callable[..., Any]] = {}

    def register_function(self, name: str, fn: Callable[..., Any]) -> None:
        self._functions[name] = fn

    def parse(self, src: str) -> "Executor":
        ast = _Parser(tokenize(src)).parse_mapping()
        return Executor(ast, self)


class Executor:
    """A compiled expression; query() evaluates it against input data."""

    def __init__(self, ast: Node, env: Environment):
        self._ast = ast
        self._env = env

    def query(self, data: Any) -> Any:
        scope = _Scope(this=data, names={}, lets={})
        return self._eval(self._ast, scope)

    # -- evaluation ---------------------------------------------------------

    def _eval(self, node: Node, s: _Scope) -> Any:
        m = getattr(self, "_eval_" + type(node).__name__, None)
        if m is None:
            raise BlangEvalError(f"unhandled node {type(node).__name__}")
        return m(node, s)

    def _eval_Lit(self, node: Lit, s: _Scope) -> Any:
        return node.val

    def _eval_ArrayLit(self, node: ArrayLit, s: _Scope) -> Any:
        return [self._eval(it, s) for it in node.items]

    def _eval_ObjectLit(self, node: ObjectLit, s: _Scope) -> Any:
        out = {}
        for k, v in node.items:
            key = self._eval(k, s)
            if not isinstance(key, str):
                raise BlangEvalError("object keys must be strings")
            out[key] = self._eval(v, s)
        return out

    def _eval_This(self, node: This, s: _Scope) -> Any:
        return s.this

    def _eval_NameRef(self, node: NameRef, s: _Scope) -> Any:
        if node.name in s.names:
            return s.names[node.name]
        return self._field(s.this, node.name)

    def _eval_VarRef(self, node: VarRef, s: _Scope) -> Any:
        if node.name not in s.lets:
            raise BlangEvalError(f"undefined variable ${node.name}")
        return s.lets[node.name]

    def _eval_Field(self, node: Field, s: _Scope) -> Any:
        return self._field(self._eval(node.base, s), node.name)

    @staticmethod
    def _field(base: Any, name: str) -> Any:
        if base is None:
            return None  # missing fields propagate null (caught by `|`)
        if isinstance(base, dict):
            return base.get(name)
        raise BlangEvalError(f"cannot access field {name!r} on {type(base).__name__}")

    def _eval_Index(self, node: Index, s: _Scope) -> Any:
        base = self._eval(node.base, s)
        idx = self._eval(node.index, s)
        if base is None:
            return None
        if isinstance(base, list):
            if not isinstance(idx, int) or isinstance(idx, bool):
                raise BlangEvalError("list index must be an integer")
            if -len(base) <= idx < len(base):
                return base[idx]
            raise BlangEvalError(f"index {idx} out of bounds")
        if isinstance(base, dict):
            if not isinstance(idx, str):
                raise BlangEvalError("map index must be a string")
            return base.get(idx)
        raise BlangEvalError(f"cannot index {type(base).__name__}")

    def _eval_Call(self, node: Call, s: _Scope) -> Any:
        fn = self._env._functions.get(node.name)
        if fn is None:
            raise BlangEvalError(f"unknown function {node.name!r}")
        args = [self._eval(a, s) for a in node.args]
        return fn(*args)

    def _eval_Capture(self, node: Capture, s: _Scope) -> Any:
        val = self._eval(node.base, s)
        inner = _Scope(this=s.this, names={**s.names, node.name: val}, lets=s.lets)
        return self._eval(node.body, inner)

    def _eval_IfExpr(self, node: IfExpr, s: _Scope) -> Any:
        if _truthy(self._eval(node.cond, s)):
            return self._eval(node.then, s)
        if node.otherwise is not None:
            return self._eval(node.otherwise, s)
        return None

    def _eval_Mapping(self, node: Mapping, s: _Scope) -> Any:
        lets = dict(s.lets)
        for name, expr in node.lets:
            lets[name] = self._eval(expr, _Scope(s.this, s.names, lets))
        return self._eval(node.result, _Scope(s.this, s.names, lets))

    def _eval_Unary(self, node: Unary, s: _Scope) -> Any:
        v = self._eval(node.operand, s)
        if node.op == "!":
            return not _truthy(v)
        if node.op == "-":
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise BlangEvalError("unary minus on non-number")
            return -v
        raise BlangEvalError(f"unknown unary op {node.op}")

    def _eval_BinOp(self, node: BinOp, s: _Scope) -> Any:
        op = node.op
        if op == "|":
            try:
                left = self._eval(node.left, s)
            except BlangEvalError:
                return self._eval(node.right, s)
            if left is None:
                return self._eval(node.right, s)
            return left
        if op == "&&":
            return _truthy(self._eval(node.left, s)) and _truthy(self._eval(node.right, s))
        if op == "||":
            return _truthy(self._eval(node.left, s)) or _truthy(self._eval(node.right, s))
        left = self._eval(node.left, s)
        right = self._eval(node.right, s)
        if op == "==":
            return self._eq(left, right)
        if op == "!=":
            return not self._eq(left, right)
        if op == "+":
            if isinstance(left, str) and isinstance(right, str):
                return left + right
            if self._both_numbers(left, right):
                return left + right
            if isinstance(left, list) and isinstance(right, list):
                return left + right
            raise BlangEvalError(
                f"cannot add {type(left).__name__} and {type(right).__name__}")
        if op in ("-", "*", "/", "%"):
            if not self._both_numbers(left, right):
                raise BlangEvalError(f"arithmetic on non-numbers ({op})")
            if op == "-":
                return left - right
            if op == "*":
                return left * right
            if op == "/":
                if right == 0:
                    raise BlangEvalError("division by zero")
                return left / right
            if right == 0:
                raise BlangEvalError("modulo by zero")
            return left % right
        if op in ("<", "<=", ">", ">="):
            if self._both_numbers(left, right) or (
                    isinstance(left, str) and isinstance(right, str)):
                return {"<": left < right, "<=": left <= right,
                        ">": left > right, ">=": left >= right}[op]
            raise BlangEvalError(f"cannot compare {type(left).__name__} and {type(right).__name__}")
        raise BlangEvalError(f"unknown operator {op}")

    @staticmethod
    def _both_numbers(a: Any, b: Any) -> bool:
        return (isinstance(a, (int, float)) and not isinstance(a, bool)
                and isinstance(b, (int, float)) and not isinstance(b, bool))

    @staticmethod
    def _eq(a: Any, b: Any) -> bool:
        if isinstance(a, bool) != isinstance(b, bool):
            return False
        return a == b

    # -- methods ------------------------------------------------------------

    def _eval_Method(self, node: Method, s: _Scope) -> Any:
        name = node.name
        if name in ("catch", "or"):
            # lazily evaluated: the fallback applies when the base errors
            # (catch/or) or resolves to null (or)
            if len(node.args) != 1:
                raise BlangEvalError(f"{name} expects 1 argument")
            try:
                base = self._eval(node.base, s)
            except BlangEvalError:
                return self._eval(node.args[0], s)
            if name == "or" and base is None:
                return self._eval(node.args[0], s)
            return base
        base = self._eval(node.base, s)

        if name in ("map_each", "filter"):
            if len(node.args) != 1:
                raise BlangEvalError(f"{name} expects 1 argument")
            if base is None:
                raise BlangEvalError(f"{name} on null")
            if not isinstance(base, list):
                raise BlangEvalError(f"{name} expects an array, got {type(base).__name__}")
            out = []
            for item in base:
                inner = _Scope(this=item, names=s.names, lets=s.lets)
                val = self._eval(node.args[0], inner)
                if name == "map_each":
                    out.append(val)
                elif _truthy(val):
                    out.append(item)
            return out

        arity = _METHOD_ARITY.get(name)
        if arity is None:
            raise BlangEvalError(f"unknown method {name!r}")
        lo, hi = arity
        if not (lo <= len(node.args) <= hi):
            raise BlangEvalError(
                f"{name} expects {lo if lo == hi else f'{lo}-{hi}'}"
                f" argument(s), got {len(node.args)}")
        args = [self._eval(a, s) for a in node.args]

        if name == "string":
            return _to_string(base)
        if name == "number":
            if isinstance(base, bool):
                raise BlangEvalError("cannot convert bool to number")
            if isinstance(base, (int, float)):
                return base
            if isinstance(base, str):
                try:
                    return int(base) if "." not in base else float(base)
                except ValueError as e:
                    raise BlangEvalError(f"cannot parse number from {base!r}") from e
            raise BlangEvalError(f"cannot convert {type(base).__name__} to number")
        if name == "length":
            if isinstance(base, (str, list, dict)):
                return len(base)
            raise BlangEvalError(f"length of {type(base).__name__}")
        if name == "uppercase":
            return self._str_method(base, str.upper)
        if name == "lowercase":
            return self._str_method(base, str.lower)
        if name == "trim":
            return self._str_method(base, str.strip)
        if name == "contains":
            if isinstance(base, str):
                return isinstance(args[0], str) and args[0] in base
            if isinstance(base, list):
                return any(self._eq(x, args[0]) for x in base)
            raise BlangEvalError(f"contains on {type(base).__name__}")
        if name in ("has_prefix", "has_suffix"):
            if not isinstance(args[0], str):
                raise BlangEvalError(f"{name} expects a string argument")
            if name == "has_prefix":
                return self._str_method(base, lambda x: x.startswith(args[0]))
            return self._str_method(base, lambda x: x.endswith(args[0]))
        if name == "split":
            if not isinstance(base, str) or not isinstance(args[0], str):
                raise BlangEvalError("split expects string.split(string)")
            if args[0] == "":
                return list(base)  # empty separator splits into characters
            return base.split(args[0])
        if name == "join":
            if not isinstance(base, list):
                raise BlangEvalError("join expects an array")
            sep = args[0] if args else ""
            if not all(isinstance(x, str) for x in base):
                raise BlangEvalError("join expects an array of strings")
            return sep.join(base)
        if name == "keys":
            if isinstance(base, dict):
                return sorted(base.keys())
            raise BlangEvalError("keys on non-map")
        if name == "values":
            if isinstance(base, dict):
                return [base[k] for k in sorted(base.keys())]
            raise BlangEvalError("values on non-map")
        if name == "sort":
            if isinstance(base, list):
                try:
                    return sorted(base)
                except TypeError as e:
                    raise BlangEvalError("cannot sort mixed-type array") from e
            raise BlangEvalError("sort on non-array")
        if name == "unique":
            if isinstance(base, list):
                seen, out = set(), []
                for x in base:
                    key = repr(x)
                    if key not in seen:
                        seen.add(key)
                        out.append(x)
                return out
            raise BlangEvalError("unique on non-array")
        if name == "slice":
            if not isinstance(base, (list, str)):
                raise BlangEvalError("slice on non-array/string")
            if not all(isinstance(a, int) and not isinstance(a, bool) for a in args):
                raise BlangEvalError("slice bounds must be integers")
            lo = args[0]
            hi = args[1] if len(args) == 2 else len(base)
            return base[lo:hi]
        if name == "exists":
            if isinstance(base, dict) and isinstance(args[0], str):
                return args[0] in base
            raise BlangEvalError("exists expects map.exists(string)")
        raise BlangEvalError(f"unknown method {name!r}")

    @staticmethod
    def _str_method(base: Any, fn: Callable[[str], Any]) -> Any:
        if not isinstance(base, str):
            raise BlangEvalError(f"string method on {type(base).__name__}")
        return fn(base)


# (min, max) argument counts for builtin methods; checked before dispatch so
# wrong-arity calls surface as BlangEvalError (catchable by `|`/.catch()).
_METHOD_ARITY = {
    "string": (0, 0), "number": (0, 0), "length": (0, 0),
    "uppercase": (0, 0), "lowercase": (0, 0), "trim": (0, 0),
    "keys": (0, 0), "values": (0, 0), "sort": (0, 0), "unique": (0, 0),
    "contains": (1, 1), "has_prefix": (1, 1), "has_suffix": (1, 1),
    "split": (1, 1), "join": (0, 1), "slice": (1, 2), "exists": (1, 1),
}
