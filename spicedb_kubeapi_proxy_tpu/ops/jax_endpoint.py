"""`jax://` endpoint: the TPU execution backend for checks and lookups.

Same host tuple store as `embedded://` (source of truth, watch, durable
semantics), but CheckPermission / CheckBulkPermissions / LookupResources
execute on device as batched boolean-SpMV reachability
(ops/graph_compile.py + ops/spmv.py).  The device graph is a cache:

- full (re)builds produce dst-sorted edge arrays (fast segment path);
- store deltas (dual-writes, watch traffic) are applied incrementally into
  padded edge-array slack via scatter updates (unsorted segment path) — a
  rebuild is only forced when a new object id appears or slack runs out;
- relationship expiration is enforced lazily: expired tuples are
  delta-removed before the next query.

Reads are fully consistent w.r.t. the store (reference check.go:41-45 uses
FullyConsistent): every query first drains pending deltas under the graph
lock, so the device CSR always reflects the committed store revision.
"""

from __future__ import annotations

import collections
import heapq
import threading
import time
from typing import Iterable, Optional

import jax.numpy as jnp
import numpy as np

from ..spicedb import schema as sch
from ..spicedb.endpoints import (
    Bootstrap,
    DEFAULT_BOOTSTRAP_SCHEMA,
    PermissionsEndpoint,
)
from ..spicedb.evaluator import Evaluator
from ..spicedb.store import TupleStore, Watcher
from ..spicedb.types import (
    CheckRequest,
    CheckResult,
    Permissionship,
    Precondition,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    WatchUpdate,
    WILDCARD,
)
from .graph_compile import GraphProgram, SELF_SLOT, compile_graph
from .spmv import KernelCache, bucket, pad_edges

_MIN_EDGE_BUCKET = 256
_MIN_BATCH_BUCKET = 8


class _DeviceGraph:
    """Compiled program + device edge arrays + incremental-update state."""

    def __init__(self, prog: GraphProgram, capacity: int, sorted_edges: bool,
                 num_iters: Optional[int] = None):
        self.prog = prog
        self.capacity = capacity
        self.num_iters = num_iters
        src, dst = pad_edges(prog, capacity)
        self.edge_src = jnp.asarray(src)
        self.edge_dst = jnp.asarray(dst)
        self.sorted_edges = sorted_edges
        e = len(prog.edge_src)
        self.free: list[int] = list(range(e, capacity))
        # tuple key -> positions occupied by that tuple's edges
        self.positions: dict[tuple, list] = {}
        self._kernels: dict[bool, KernelCache] = {}

    def kernel(self) -> KernelCache:
        key = self.sorted_edges
        k = self._kernels.get(key)
        if k is None:
            k = KernelCache(self.prog, num_iters=self.num_iters,
                            indices_sorted=key)
            self._kernels[key] = k
        return k


class JaxEndpoint(PermissionsEndpoint):
    def __init__(self, schema: sch.Schema, store: Optional[TupleStore] = None,
                 num_iters: Optional[int] = None):
        self.schema = schema
        self.store = store if store is not None else TupleStore()
        # oracle fallback for query endpoints outside the compiled universe
        self._oracle = Evaluator(schema, self.store)
        self._num_iters = num_iters
        self._lock = threading.RLock()
        self._graph: Optional[_DeviceGraph] = None
        # listener callbacks run while the STORE lock is held; they must
        # never take self._lock (ABBA deadlock with queries that hold
        # self._lock and read the store), so delta intake is a lock-free
        # deque append plus an invalidation flag.
        self._pending: collections.deque = collections.deque()
        self._graph_invalid = False
        self._expiry_heap: list = []  # (expires_at, rel key tuple)
        # current expiration per tuple key; heap entries not matching this
        # map are stale and skipped (lazy deletion)
        self._expiry_meta: dict = {}
        self._known_extra_subjects: dict[str, set] = {}
        self.stats = {"rebuilds": 0, "delta_batches": 0, "kernel_calls": 0}
        self.store.add_delta_listener(self._on_delta)
        self.store.add_reset_listener(self._on_reset)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_bootstrap(cls, bootstrap: Optional[Bootstrap] = None,
                       **kwargs) -> "JaxEndpoint":
        if bootstrap is None or not bootstrap.schema_text:
            schema_text = DEFAULT_BOOTSTRAP_SCHEMA
            rel_text = bootstrap.relationships_text if bootstrap else ""
        else:
            schema_text = bootstrap.schema_text
            rel_text = bootstrap.relationships_text
        ep = cls(sch.parse_schema(schema_text), **kwargs)
        bs = Bootstrap(schema_text=schema_text, relationships_text=rel_text)
        rels = bs.relationships()
        if rels:
            ep.store.bulk_load(rels)
        return ep

    # -- delta intake -------------------------------------------------------

    def _on_delta(self, update: WatchUpdate) -> None:
        # called under the store lock — must not acquire self._lock
        self._pending.append(update)

    def _on_reset(self) -> None:
        """bulk_load/delete_all invalidate the device graph wholesale
        (called under the store lock — must not acquire self._lock)."""
        self._graph_invalid = True

    # -- graph maintenance --------------------------------------------------

    def _edge_endpoints(self, prog: GraphProgram, rel: Relationship) -> Optional[list]:
        """(src, dst) pairs this tuple contributes, or None if an id is
        outside the compiled universe (forces rebuild)."""
        rt = rel.resource.type
        d = self.schema.definitions.get(rt)
        if d is None or rel.relation not in d.relations:
            return []
        dst = prog.state_index(rt, rel.relation, rel.resource.id)
        if dst is None:
            return None
        out = []
        st, sid, srel = rel.subject.type, rel.subject.id, rel.subject.relation
        if sid == WILDCARD:
            # wildcard masks are baked into the compiled program; changing
            # them requires a rebuild
            return None
        src = prog.subject_index(st, sid, srel)
        if src is None:
            return None
        out.append((src, dst))
        # arrow edges (specs recorded by the graph compiler)
        for (perm, k, target, slot) in prog.arrow_specs.get((rt, rel.relation), ()):
            if srel:
                continue
            target_def = self.schema.definitions.get(st)
            if target_def is None or not target_def.has_relation_or_permission(target):
                continue
            asrc = prog.state_index(st, target, sid)
            adst = prog.state_index(rt, slot, rel.resource.id)
            if asrc is None or adst is None:
                return None
            out.append((asrc, adst))
        return out

    def _rebuild(self) -> None:
        # a rebuild reflects the current store snapshot; any queued deltas
        # are subsumed by it
        self._drain_pending()
        self._graph_invalid = False
        tuples = self.store.read(None)
        extra = {t: set(ids) for t, ids in self._known_extra_subjects.items()}
        prog = compile_graph(self.schema, tuples, extra_subject_ids=extra)
        capacity = bucket(max(len(prog.edge_src) * 2, _MIN_EDGE_BUCKET))
        graph = _DeviceGraph(prog, capacity, sorted_edges=True,
                             num_iters=self._num_iters)
        # index tuple keys -> edge positions (edges were emitted in tuple
        # order then sorted; recover positions by scanning)
        pos_by_pair: dict[tuple, list] = {}
        for i, (s, dd) in enumerate(zip(prog.edge_src, prog.edge_dst)):
            pos_by_pair.setdefault((int(s), int(dd)), []).append(i)
        for rel in tuples:
            pairs = self._edge_endpoints(prog, rel)
            if not pairs:
                continue
            positions = []
            for pair in pairs:
                stack = pos_by_pair.get(pair)
                if stack:
                    positions.append(stack.pop())
            graph.positions[rel.key()] = positions
        self._reset_expiry(tuples)
        self._graph = graph
        self.stats["rebuilds"] += 1

    def _reset_expiry(self, tuples: list) -> None:
        self._expiry_heap = []
        self._expiry_meta = {}
        for rel in tuples:
            if rel.expires_at is not None:
                self._expiry_meta[rel.key()] = rel.expires_at
                heapq.heappush(self._expiry_heap, (rel.expires_at, rel.key()))

    def _set_expiry(self, key: tuple, expires_at) -> None:
        if expires_at is None:
            self._expiry_meta.pop(key, None)
        else:
            self._expiry_meta[key] = expires_at
            heapq.heappush(self._expiry_heap, (expires_at, key))

    def _drain_pending(self) -> list:
        """Atomically take all queued delta batches."""
        out = []
        while True:
            try:
                out.append(self._pending.popleft())
            except IndexError:
                return out

    def _apply_pending(self) -> None:
        """Drain store deltas into the device graph (under self._lock)."""
        if self._graph_invalid:
            self._graph_invalid = False
            self._graph = None
        graph = self._graph
        if graph is None:
            self._rebuild()
            return
        batches = self._drain_pending()
        if not batches and not (self._expiry_heap
                                and self._expiry_heap[0][0] <= time.time()):
            return

        updates: list[tuple] = []  # (pos, src, dst)
        needs_rebuild = False
        for batch in batches:
            for u in batch.updates:
                key = u.rel.key()
                if u.op == UpdateOp.DELETE:
                    if u.rel.subject.id == WILDCARD:
                        # wildcard contributions are baked into the compiled
                        # program's masks; only a rebuild removes them
                        needs_rebuild = True
                        break
                    self._set_expiry(key, None)
                    for pos in graph.positions.pop(key, ()):
                        updates.append((pos, graph.prog.dead_index,
                                        graph.prog.dead_index))
                        graph.free.append(pos)
                else:  # TOUCH
                    self._set_expiry(key, u.rel.expires_at)
                    if key in graph.positions:
                        continue  # edges already present; expiry updated above
                    pairs = self._edge_endpoints(graph.prog, u.rel)
                    if pairs is None:
                        needs_rebuild = True
                        break
                    positions = []
                    for (s, dd) in pairs:
                        if not graph.free:
                            needs_rebuild = True
                            break
                        pos = graph.free.pop()
                        updates.append((pos, s, dd))
                        positions.append(pos)
                    if needs_rebuild:
                        break
                    graph.positions[key] = positions
            if needs_rebuild:
                break
        # expire lazily AFTER batch processing so expirations registered by
        # the batches just drained take effect this query; heap entries whose
        # expiry no longer matches the current metadata are stale (tuple
        # deleted/re-touched) and skipped
        now = time.time()
        while (not needs_rebuild and self._expiry_heap
               and self._expiry_heap[0][0] <= now):
            exp, key = heapq.heappop(self._expiry_heap)
            if self._expiry_meta.get(key) != exp:
                continue
            del self._expiry_meta[key]
            if key[4] == WILDCARD:
                needs_rebuild = True
                break
            for pos in graph.positions.pop(key, ()):
                updates.append((pos, graph.prog.dead_index,
                                graph.prog.dead_index))
                graph.free.append(pos)

        if needs_rebuild:
            self._rebuild()
            return
        if updates:
            # a position freed and re-allocated within one drain appears
            # twice; scatter order for duplicate indices is undefined in
            # XLA, so collapse to last-write-wins first
            final: dict[int, tuple] = {}
            for (pos, s_, d_) in updates:
                final[pos] = (s_, d_)
            pos = jnp.asarray(list(final.keys()), jnp.int32)
            srcs = jnp.asarray([v[0] for v in final.values()], jnp.int32)
            dsts = jnp.asarray([v[1] for v in final.values()], jnp.int32)
            graph.edge_src = graph.edge_src.at[pos].set(srcs)
            graph.edge_dst = graph.edge_dst.at[pos].set(dsts)
            graph.sorted_edges = False
            self.stats["delta_batches"] += 1

    def _current_graph(self) -> _DeviceGraph:
        self._apply_pending()
        return self._graph

    # -- query encoding -----------------------------------------------------

    def _encode_subjects(self, graph: _DeviceGraph, subjects: list) -> tuple:
        """Dedupe subjects into query columns; returns (q_idx array,
        col_of_subject dict, unknown set)."""
        cols: dict = {}
        q: list[int] = []
        unknown: set = set()
        for s in subjects:
            if s in cols or s in unknown:
                continue
            idx = graph.prog.subject_index(s.type, s.id, s.relation)
            if idx is None:
                unknown.add(s)
                continue
            cols[s] = len(q)
            q.append(idx)
        b = bucket(max(len(q), 1), _MIN_BATCH_BUCKET)
        q_arr = np.full(b, graph.prog.dead_index, np.int32)
        q_arr[: len(q)] = q
        return q_arr, cols, unknown

    # -- verbs --------------------------------------------------------------

    def _check_batch_sync(self, reqs: list) -> list:
        with self._lock:
            # capture the revision BEFORE draining deltas so checked_at is
            # never newer than the evaluated snapshot (writes committing
            # during evaluation must not be attributed to the result)
            rev = self.store.revision
            graph = self._current_graph()
            q_arr, cols, unknown = self._encode_subjects(
                graph, [r.subject for r in reqs])
            gather_idx: list[int] = []
            gather_col: list[int] = []
            kernel_rows: list[int] = []  # positions in reqs served by kernel
            results: list[Optional[bool]] = [None] * len(reqs)
            for i, r in enumerate(reqs):
                if r.subject in unknown:
                    # outside the compiled universe: oracle fallback (only
                    # wildcard-derived permissions can apply)
                    results[i] = self._oracle.check(r.resource, r.permission,
                                                    r.subject)
                    continue
                state_idx = graph.prog.state_index(
                    r.resource.type, r.permission, r.resource.id)
                if state_idx is None:
                    d = self.schema.definitions.get(r.resource.type)
                    if d is None or not d.has_relation_or_permission(r.permission):
                        # surface schema errors like the oracle does
                        results[i] = self._oracle.check(
                            r.resource, r.permission, r.subject)
                    else:
                        results[i] = False  # unknown object: no tuples
                    continue
                gather_idx.append(state_idx)
                gather_col.append(cols[r.subject])
                kernel_rows.append(i)
            if kernel_rows:
                g = bucket(len(gather_idx), _MIN_BATCH_BUCKET)
                gi = np.zeros(g, np.int32)
                gc = np.zeros(g, np.int32)
                gi[: len(gather_idx)] = gather_idx
                gc[: len(gather_col)] = gather_col
                out = graph.kernel().checks(q_arr, gi, gc, graph.edge_src,
                                            graph.edge_dst)
                self.stats["kernel_calls"] += 1
                for j, row in enumerate(kernel_rows):
                    results[row] = bool(out[j])
        return [CheckResult(
            permissionship=(Permissionship.HAS_PERMISSION if r
                            else Permissionship.NO_PERMISSION),
            checked_at=rev) for r in results]

    async def check_permission(self, req: CheckRequest) -> CheckResult:
        return self._check_batch_sync([req])[0]

    async def check_bulk_permissions(self, reqs: list) -> list:
        if not reqs:
            return []
        return self._check_batch_sync(reqs)

    def _lookup_sync(self, resource_type: str, permission: str,
                     subject: SubjectRef) -> list:
        self.schema.definition(resource_type)  # raises like the oracle
        with self._lock:
            graph = self._current_graph()
            rng = graph.prog.slot_range(resource_type, permission)
            if rng is None:
                return self._oracle.lookup_resources(resource_type, permission,
                                                     subject)
            q_arr, cols, unknown = self._encode_subjects(graph, [subject])
            if subject in unknown:
                return self._oracle.lookup_resources(resource_type, permission,
                                                     subject)
            col = cols[subject]
            bitmap = graph.kernel().lookup(rng[0], rng[1], q_arr,
                                           graph.edge_src, graph.edge_dst)
            self.stats["kernel_calls"] += 1
            ids = graph.prog.object_ids[resource_type]
        return [ids[i] for i in np.nonzero(bitmap[:, col])[0]]

    async def lookup_resources(self, resource_type: str, permission: str,
                               subject: SubjectRef) -> list:
        return self._lookup_sync(resource_type, permission, subject)

    def _lookup_batch_sync(self, resource_type: str, permission: str,
                           subjects: list) -> list:
        self.schema.definition(resource_type)
        with self._lock:
            graph = self._current_graph()
            rng = graph.prog.slot_range(resource_type, permission)
            if rng is None:
                return [self._oracle.lookup_resources(resource_type, permission, s)
                        for s in subjects]
            q_arr, cols, unknown = self._encode_subjects(graph, subjects)
            bitmap = graph.kernel().lookup(rng[0], rng[1], q_arr,
                                           graph.edge_src, graph.edge_dst)
            self.stats["kernel_calls"] += 1
            ids = graph.prog.object_ids[resource_type]
            out = []
            for s in subjects:
                if s in unknown:
                    out.append(self._oracle.lookup_resources(
                        resource_type, permission, s))
                else:
                    out.append([ids[i] for i in
                                np.nonzero(bitmap[:, cols[s]])[0]])
        return out

    async def lookup_resources_batch(self, resource_type: str, permission: str,
                                     subjects: list) -> list:
        if not subjects:
            return []
        return self._lookup_batch_sync(resource_type, permission, subjects)

    async def read_relationships(self, flt: RelationshipFilter) -> list:
        return self.store.read(flt)

    async def write_relationships(self, updates: Iterable[RelationshipUpdate],
                                  preconditions: Iterable[Precondition] = ()) -> int:
        return self.store.write(updates, preconditions)

    async def delete_relationships(self, flt: RelationshipFilter,
                                   preconditions: Iterable[Precondition] = ()) -> int:
        rev, _ = self.store.delete_by_filter(flt, preconditions)
        return rev

    def watch(self, object_types: Optional[Iterable[str]] = None) -> Watcher:
        return self.store.subscribe(object_types)

    # -- maintenance hooks --------------------------------------------------

    def register_query_subjects(self, subjects: dict) -> None:
        """Pre-register subject ids ({type: iterable}) so queries about them
        hit the kernel instead of the oracle fallback on first contact."""
        with self._lock:
            changed = False
            for t, ids in subjects.items():
                bucket_set = self._known_extra_subjects.setdefault(t, set())
                new = set(ids) - bucket_set
                if new:
                    bucket_set.update(new)
                    changed = True
            if changed:
                self._graph = None  # force rebuild on next query

    def force_rebuild(self) -> None:
        with self._lock:
            self._rebuild()
