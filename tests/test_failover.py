"""Replication fault-tolerance suite (ISSUE 11): leader failover with
incarnation fencing, follower fan-out trees, and the replication fault
matrix (spicedb/replication/failover.py).

Proves the acceptance bar:
- kill -9 the leader -> promotion completes well under one flight
  window and the promoted node takes writes;
- every acknowledged dual-write before the kill is readable after
  failover (zero lost): shipped writes ride the promotion, unshipped
  ones ride the rejoining ex-leader's tail replay;
- a healed partition with the old leader resurrected converges to
  exactly one writable leader (fencing tripwire: stale manifests
  rejected by followers, stale leaders refuse update verbs);
- no injected fault (segment fetch, manifest poll, checkpoint
  bootstrap, promotion critical section, partition) hangs anything;
- the Replication gate off reproduces single-node behavior.
"""

import asyncio
import json
import os
import random
import shutil
import tempfile
import time

import pytest

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.replication import (
    MIN_REVISION_HEADER,
    StaleLeaderError,
    failover,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils.failpoints import (
    KIND_PANIC,
    KIND_REFUSE,
    FailPointPanic,
    disable_all,
    enable_failpoint,
)
from spicedb_kubeapi_proxy_tpu.utils.features import GATES

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
definition pod {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources: {tpl: "namespace:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-pods}
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
lock: Optimistic
check: [{tpl: "namespace:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"
"""

N_NS = 10


@pytest.fixture(autouse=True)
def reset_gates_and_failpoints():
    yield
    GATES.reset()
    disable_all()


@pytest.fixture
def tmp():
    d = tempfile.mkdtemp(prefix="failover-test-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


class LeaderLink:
    """In-process transport resolving the target proxy's CURRENT handler
    on every call; swappable for leader-restart scenarios."""

    def __init__(self, proxy=None):
        self.proxy = proxy

    async def round_trip(self, req):
        if self.proxy is None:
            raise ConnectionError("link not bound")
        return await self.proxy.handler(req)

    def set_leader(self, proxy):
        self.proxy = proxy


class DeadTransport:
    async def round_trip(self, req):
        raise ConnectionError("peer is gone")


def make_leader(tmp, sub="leader", seed_ns=True, kube=None, **opt_kw):
    kube = kube or FakeKubeApiServer()
    if seed_ns:
        for i in range(N_NS):
            kube.seed("", "v1", "namespaces",
                      {"metadata": {"name": f"ns{i}"}})
    leader = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        data_dir=os.path.join(tmp, sub), wal_fsync="never", **opt_kw))
    if seed_ns:
        leader.endpoint.store.bulk_load([
            parse_relationship(f"namespace:ns{i}#creator@user:alice")
            for i in range(0, N_NS, 2)])
    return leader, kube


def make_follower(leader, kube, **opt_kw):
    transport = LeaderLink(leader)
    follower = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        replicate_from="http://leader.test",
        leader_transport=transport, **opt_kw))
    return follower, transport


def churn(leader, i):
    op = UpdateOp.DELETE if i % 3 == 2 else UpdateOp.TOUCH
    rel = parse_relationship(
        f"namespace:ns{i % N_NS}#viewer@user:u{i % 5}")
    return leader.endpoint.write_relationships(
        [RelationshipUpdate(op, rel)])


async def list_ns(proxy, user, headers=None):
    client = proxy.get_embedded_client(user)
    resp = await client.get("/api/v1/namespaces", headers=headers or [])
    return resp, (sorted(i["metadata"]["name"]
                         for i in json.loads(resp.body).get("items", []))
                  if resp.status == 200 else None)


async def assert_parity(a, b, users=("alice", "u0", "u1", "nobody")):
    for user in users:
        ra, ia = await list_ns(a, user)
        rb, ib = await list_ns(b, user)
        assert ra.status == rb.status == 200
        assert ia == ib, f"divergence for {user}: {ia} != {ib}"


# -- incarnation & manifest ---------------------------------------------------


def test_manifest_carries_incarnation_and_chain(tmp):
    leader, _ = make_leader(tmp)

    async def go():
        client = leader.get_embedded_client("alice")
        man = json.loads((await client.get("/replication/manifest")).body)
        assert man["incarnation"] == 1  # fresh data dir
        assert man["fenced"] is None
        assert man["chain"] == {"path": [man["leader_id"]],
                                "lag_revisions": 0.0, "lag_seconds": 0.0}
        st = json.loads((await client.get("/replication/status")).body)
        assert st["role"] == "leader"
        assert st["incarnation"] == 1 and st["fenced_by"] is None

    asyncio.run(go())
    # restart-in-place bumps the epoch by one and extends the lineage
    leader2, _ = make_leader(tmp, seed_ns=False)
    hub = leader2.replication_hub
    assert hub.incarnation == 2
    from spicedb_kubeapi_proxy_tpu.spicedb.replication.leader import (
        leader_lineage,
    )
    lineage = leader_lineage(leader2.persistence.data_dir)
    assert leader.replication_hub.leader_id in lineage
    assert hub.leader_id in lineage


# -- promotion ---------------------------------------------------------------


def test_promote_follower_becomes_writable_leader(tmp):
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"))
    fol.enable_dual_writes()

    async def go():
        for i in range(6):
            await churn(leader, i)
        await fol.replication.sync_once()
        shipped = fol.replication.store.revision
        old_inc = fol.replication.max_incarnation

        # kill -9: the leader object is simply abandoned.  Promotion is
        # a privileged control action: a plain principal gets 403, the
        # replication identity / system:masters succeeds.
        resp = await fol.get_embedded_client("mallory").post(
            "/replication/promote", {})
        assert resp.status == 403
        assert fol.replication is not None  # nothing happened
        client = fol.get_embedded_client("admin",
                                         groups=["system:masters"])
        resp = await client.post("/replication/promote", {})
        assert resp.status == 200, resp.body
        info = json.loads(resp.body)
        assert info["revision"] == shipped
        assert info["incarnation"] == old_inc + 2  # promotion mint
        assert fol.replication is None
        assert fol.replication_hub is not None
        assert fol.replication_hub.fenced["revision"] == shipped

        # /debug + /status agree on the new role
        st = json.loads((await client.get("/replication/status")).body)
        assert st["role"] == "leader" and st["incarnation"] == old_inc + 2
        dbg = json.loads((await client.get("/debug/replication")).body)
        assert dbg["role"] == "leader"

        # the promoted node takes writes LOCALLY (no forwarding)
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p1", "namespace": "ns0"}}
        resp = await fol.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", pod)
        assert resp.status in (200, 201), resp.body
        assert resp.headers.get("X-Authz-Forwarded-To") == ""
        assert fol.endpoint.store.has_exact(parse_relationship(
            "pod:ns0/p1#creator@user:alice"))

        # a second promote is a 409: already the leader
        resp = await client.post("/replication/promote", {})
        assert resp.status == 409

        # the promoted log is bootstrappable: a FRESH follower anchors
        # on the promotion checkpoint and tails the new segments
        g, _ = make_follower(fol, kube)
        await g.replication.sync_once()
        assert g.replication.store.revision == fol.endpoint.store.revision
        assert g.replication.max_incarnation == old_inc + 2
        await assert_parity(fol, g)

    asyncio.run(go())


def test_promotion_crash_rolls_back_to_intact_follower(tmp):
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"))

    async def go():
        for i in range(4):
            await churn(leader, i)
        await fol.replication.sync_once()
        _, before = await list_ns(fol, "u1")
        enable_failpoint("replPromote", 1)
        with pytest.raises(FailPointPanic):
            await failover.promote_follower(fol)
        # still an intact follower: no hub, reads serve, tail resumes
        assert fol.replication is not None
        assert fol.replication_hub is None
        resp, after = await list_ns(fol, "u1")
        assert resp.status == 200 and after == before
        await churn(leader, 99)
        await fol.replication.sync_once()
        assert (fol.replication.store.revision
                == leader.endpoint.store.revision)
        # disarmed, the same promotion succeeds
        disable_all()
        info = await failover.promote_follower(fol)
        assert fol.replication_hub is not None
        assert info["revision"] == fol.endpoint.store.revision

    asyncio.run(go())


def test_promote_requires_follower_and_gate(tmp):
    GATES.set("Replication", False)
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "p"),
                           serve_replication=True)
    # gate off: no replication objects at all, single-node behavior
    assert fol.replication is None and fol.fanout_hub is None
    assert leader.replication_hub is None

    async def go():
        resp = await fol.get_embedded_client(
            "a", groups=["system:masters"]).post(
            "/replication/promote", {})
        assert resp.status == 503
        resp = await fol.get_embedded_client("a").get(
            "/replication/status")
        assert resp.status == 503

    asyncio.run(go())


# -- zero lost acknowledged writes across failover ---------------------------


def test_rejoin_replays_unshipped_tail_zero_lost(tmp):
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"))

    async def go():
        for i in range(6):
            await churn(leader, i)
        await fol.replication.sync_once()
        shipped = fol.replication.store.revision

        # acknowledged on the leader, never shipped to the follower
        await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns9#viewer@user:lostwrite"))])

        # kill -9 the leader; promote the follower at the SHIPPED
        # revision (never guessing at unshipped writes)
        info = await failover.promote_follower(fol)
        assert info["revision"] == shipped
        assert not fol.endpoint.store.has_exact(parse_relationship(
            "namespace:ns9#viewer@user:lostwrite"))

        # post-failover write on the new leader
        await fol.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns1#viewer@user:afterfail"))])

        # resurrect the ex-leader over its old data dir, with the new
        # leader among its peers: the startup fence probe demotes it
        # and replays the unshipped tail
        link = LeaderLink(fol)
        old2, _ = make_leader(
            tmp, seed_ns=False, kube=kube,
            replica_peers=["http://new.test"],
            peer_transports={"http://new.test": link})
        # recovery restored the acknowledged-but-unshipped write
        assert old2.endpoint.store.has_exact(parse_relationship(
            "namespace:ns9#viewer@user:lostwrite"))
        assert old2.replication_hub.incarnation < info["incarnation"]

        mon = failover.FenceMonitor(old2)
        assert await mon.check_once() == "demoted"
        assert old2.replication_hub is None
        assert old2.replication is not None

        # ZERO LOST: the unshipped write landed on the new leader via
        # the rejoin replay, next to the post-failover write
        assert fol.endpoint.store.has_exact(parse_relationship(
            "namespace:ns9#viewer@user:lostwrite"))
        assert fol.endpoint.store.has_exact(parse_relationship(
            "namespace:ns1#viewer@user:afterfail"))
        # and the rejoined ex-leader converged to the new leader
        assert (old2.endpoint.store.revision
                == fol.endpoint.store.revision)
        await assert_parity(fol, old2,
                            users=("alice", "u1", "lostwrite",
                                   "afterfail"))
        # writes on the rejoined ex-leader forward to the new leader
        fol.enable_dual_writes()
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p2", "namespace": "ns0"}}
        resp = await old2.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", pod)
        assert resp.status in (200, 201), resp.body
        assert resp.headers.get("X-Authz-Forwarded-To") == "leader"
        assert fol.endpoint.store.has_exact(parse_relationship(
            "pod:ns0/p2#creator@user:alice"))

    asyncio.run(go())


def test_healed_partition_converges_to_one_writable_leader(tmp):
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"))
    other, _ = make_follower(leader, kube)
    fol.enable_dual_writes()

    async def go():
        for i in range(5):
            await churn(leader, i)
        await fol.replication.sync_once()
        await other.replication.sync_once()

        # partition: the leader dies, the follower promotes
        info = await failover.promote_follower(fol)
        new_inc = info["incarnation"]
        # `other` adopts the new leader (election loser path)
        other.opts.peer_transports = {"http://new.test": LeaderLink(fol)}
        other.opts.replica_peers = ["http://new.test"]
        other.repoint_leader("http://new.test")
        await other.replication.sync_once()
        assert other.replication.max_incarnation == new_inc

        # the partition heals: the old leader resurrects over its dir
        # (no peers configured — it doesn't know about the promotion)
        old2, _ = make_leader(tmp, seed_ns=False, kube=kube)
        assert old2.replication_hub.incarnation < new_inc

        # a follower still pointed at the resurrected ex-leader refuses
        # its stale manifest and keeps serving its adopted state...
        _, before = await list_ns(other, "u1")
        other.replication.repoint(LeaderLink(old2), "http://old.test")
        with pytest.raises(StaleLeaderError):
            await other.replication.sync_once()
        assert other.replication.stats["fenced_polls"] == 1
        resp, after = await list_ns(other, "u1")
        assert resp.status == 200 and after == before

        # ...and its poll carried the newer incarnation: the ex-leader
        # is now fenced and refuses update verbs — exactly ONE writable
        # leader even before any demotion runs
        assert old2.replication_hub.fenced_by["incarnation"] == new_inc
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "px", "namespace": "ns0"}}
        resp = await old2.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", pod)
        assert resp.status == 503
        assert b"superseded" in resp.body
        # fenced reads stay degraded-but-200
        resp, _ = await list_ns(old2, "u1")
        assert resp.status == 200
        ready = await old2.get_embedded_client("x").get("/readyz")
        assert ready.status == 200 and b"fenced" in ready.body
        # the new leader takes the same write
        resp = await fol.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", pod)
        assert resp.status in (200, 201), resp.body

        # full convergence: the fenced ex-leader demotes into the fleet
        old2.opts.peer_transports = {"http://new.test": LeaderLink(fol)}
        old2.opts.replica_peers = ["http://new.test"]
        mon = failover.FenceMonitor(old2)
        assert await mon.check_once() == "demoted"
        assert old2.replication_hub is None
        await assert_parity(fol, old2)

    asyncio.run(go())


def test_rejoin_endpoint_requires_privilege(tmp):
    leader, kube = make_leader(tmp)

    async def go():
        rev = leader.endpoint.store.revision
        body = {"from_leader_id": "x", "from_incarnation": 1,
                "updates": [["t", "namespace:ns0#viewer@user:evil"]]}
        # an ordinary authenticated principal must NOT be able to write
        # tuples through the rejoin control endpoint
        resp = await leader.get_embedded_client("mallory").post(
            "/replication/rejoin", body)
        assert resp.status == 403
        assert leader.endpoint.store.revision == rev
        assert not leader.endpoint.store.has_exact(parse_relationship(
            "namespace:ns0#viewer@user:evil"))
        # the replication identity may (that is the rejoin path)
        resp = await leader.get_embedded_client("system:replica").post(
            "/replication/rejoin", body)
        assert resp.status == 200
        assert json.loads(resp.body)["applied"] == 1

    asyncio.run(go())


def test_rejoin_replays_checkpoint_reclaimed_window(tmp):
    """A pre-crash checkpoint can reclaim the WAL segments holding the
    unshipped tail: the rejoin then replays the surviving EFFECTS from
    the recovered store (revision-stamped tuples) instead of silently
    losing them."""
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"))

    async def go():
        for i in range(4):
            await churn(leader, i)
        await fol.replication.sync_once()
        shipped = fol.replication.store.revision
        # unshipped writes... then a checkpoint RECLAIMS their segments
        await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns8#viewer@user:ckptlost"))])
        leader.persistence.checkpoint()
        assert leader.persistence._last_ckpt_revision > shipped
        # kill -9 the leader; promote the follower at the shipped rev
        info = await failover.promote_follower(fol)
        assert info["revision"] == shipped
        # resurrect + demote: the WAL stream past `shipped` is gone,
        # but the effects replay recovers the write
        link = LeaderLink(fol)
        old2, _ = make_leader(
            tmp, seed_ns=False, kube=kube,
            replica_peers=["http://new.test"],
            peer_transports={"http://new.test": link})
        mon = failover.FenceMonitor(old2)
        assert await mon.check_once() == "demoted"
        assert fol.endpoint.store.has_exact(parse_relationship(
            "namespace:ns8#viewer@user:ckptlost"))
        assert (old2.endpoint.store.revision
                == fol.endpoint.store.revision)
        await assert_parity(fol, old2, users=("alice", "ckptlost"))

    asyncio.run(go())


def test_equal_epoch_tie_breaks_on_larger_leader_id(tmp):
    """Two sides of a partition promoting simultaneously mint the same
    epoch: the (incarnation, leader_id) total order makes exactly ONE
    of them lose — never both (zero writable leaders) and never a
    per-follower split."""
    leader, _ = make_leader(tmp)
    hub = leader.replication_hub
    small_id = "leader-0000-aaaa"
    big_id = "leader-9999-zzzz"

    class FakeReq:
        def __init__(self, inc, lid):
            from spicedb_kubeapi_proxy_tpu.proxy.httpcore import Headers
            self.headers = Headers([
                ("X-Replication-Incarnation", str(inc)),
                ("X-Replication-Leader-Id", lid)])

    # the hub only loses an epoch tie to a LARGER id...
    hub.leader_id = big_id
    hub.observe_poll_headers(FakeReq(hub.incarnation, small_id))
    assert hub.fenced_by is None
    # ...and loses it to a larger one
    hub.observe_poll_headers(FakeReq(hub.incarnation, big_id + "x"))
    assert hub.fenced_by is not None

    # follower side: same order — an equal-epoch smaller id is stale,
    # an equal-epoch larger id is adopted
    kube = FakeKubeApiServer()
    fol, _ = make_follower(leader, kube)
    fol.replication.max_incarnation = 7
    fol.replication.max_leader_id = big_id

    class FakeTransport:
        def __init__(self, inc, lid):
            self.inc, self.lid = inc, lid

        async def round_trip(self, req):
            from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
                json_response,
            )
            return json_response(200, {
                "leader_id": self.lid, "incarnation": self.inc,
                "revision": 0, "checkpoint": None, "segments": [],
                "sidecars": [], "chain": {"path": [self.lid],
                                          "lag_revisions": 0,
                                          "lag_seconds": 0}})

    async def go():
        from spicedb_kubeapi_proxy_tpu.spicedb.replication import (
            StaleLeaderError as SLE,
        )
        fol.replication.transport = FakeTransport(7, small_id)
        with pytest.raises(SLE):
            await fol.replication._fetch_manifest(wait=False)
        fol.replication.transport = FakeTransport(7, big_id + "x")
        await fol.replication._fetch_manifest(wait=False)
        assert fol.replication.max_leader_id == big_id + "x"

    asyncio.run(go())


# -- election ----------------------------------------------------------------


def _make_election_pair(tmp, kube, leader):
    link_a, link_b = LeaderLink(), LeaderLink()
    fa, _ = make_follower(
        leader, kube, replica_id="node-a",
        promote_data_dir=os.path.join(tmp, "pa"),
        replica_peers=["http://b.test"],
        peer_transports={"http://b.test": link_b})
    fb, _ = make_follower(
        leader, kube, replica_id="node-b",
        promote_data_dir=os.path.join(tmp, "pb"),
        replica_peers=["http://a.test"],
        peer_transports={"http://a.test": link_a})
    link_a.set_leader(fa)
    link_b.set_leader(fb)
    return fa, fb


def test_election_highest_revision_wins_and_loser_repoints(tmp):
    leader, kube = make_leader(tmp)
    fa, fb = _make_election_pair(tmp, kube, leader)

    async def go():
        for i in range(4):
            await churn(leader, i)
        await fb.replication.sync_once()
        for i in range(4, 8):
            await churn(leader, i)
        await fa.replication.sync_once()  # A strictly ahead of B
        assert (fa.replication.store.revision
                > fb.replication.store.revision)

        wd_a = failover.LeaderLossWatchdog(fa, grace_s=0.0)
        wd_b = failover.LeaderLossWatchdog(fb, grace_s=0.0)
        # B sees a better candidate (A, higher revision): defers
        assert await wd_b.run_election() == "deferred"
        assert fb.replication is not None
        # A wins and promotes
        assert await wd_a.run_election() == "promoted"
        assert fa.replication_hub is not None
        # B's next pass finds the promoted leader and repoints
        assert await wd_b.run_election() == "repointed"
        assert fb.opts.replicate_from == "http://a.test"
        await fb.replication.sync_once()
        assert (fb.replication.store.revision
                == fa.endpoint.store.revision)
        # a write on the new leader replicates to the repointed loser
        await fa.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns2#viewer@user:postelect"))])
        await fb.replication.sync_once()
        assert fb.replication.store.has_exact(parse_relationship(
            "namespace:ns2#viewer@user:postelect"))
        await assert_parity(fa, fb)

    asyncio.run(go())


def test_election_tie_breaks_on_smallest_replica_id(tmp):
    leader, kube = make_leader(tmp)
    fa, fb = _make_election_pair(tmp, kube, leader)

    async def go():
        for i in range(4):
            await churn(leader, i)
        await fa.replication.sync_once()
        await fb.replication.sync_once()
        assert (fa.replication.store.revision
                == fb.replication.store.revision)
        wd_a = failover.LeaderLossWatchdog(fa, grace_s=0.0)
        wd_b = failover.LeaderLossWatchdog(fb, grace_s=0.0)
        # node-b defers to node-a (same revision, smaller id)
        assert await wd_b.run_election() == "deferred"
        assert await wd_a.run_election() == "promoted"
        assert fa.replication_hub is not None

    asyncio.run(go())


def test_watchdog_probe_prevents_false_promotion(tmp):
    """An idle tail parked in a long-poll has a stale `last_success`;
    the watchdog must confirm loss with a direct probe instead of
    promoting past a perfectly healthy leader."""
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"))

    async def go():
        await fol.replication.sync_once()
        wd = failover.LeaderLossWatchdog(fol, grace_s=0.05)
        # stale success (as during an idle 25s long-poll), live leader
        fol.replication._last_success = time.monotonic() - 60.0
        assert await wd.check_once() == "healthy"
        assert fol.replication_hub is None  # no false promotion
        assert wd.stats.get("probes_ok") == 1
        # the successful probe refreshed the loss clock: the next tick
        # is healthy WITHOUT re-probing (no probe churn per tick)
        assert await wd.check_once() == "healthy"
        assert wd.stats.get("probes_ok") == 1
        # same staleness with the leader actually gone: election fires
        fol.replication.transport = DeadTransport()
        fol.replication._last_success = time.monotonic() - 60.0
        assert await wd.check_once() == "promoted"
        assert fol.replication_hub is not None

    asyncio.run(go())


def test_watchdog_failover_completes_within_flight_window(tmp):
    flight_window_s = 5.0
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube,
                           promote_data_dir=os.path.join(tmp, "promote"),
                           flight_window_s=flight_window_s)

    async def go():
        for i in range(4):
            await churn(leader, i)
        await fol.replication.sync_once()
        # kill -9: both the tail and any forwarding path die
        fol.replication.transport = DeadTransport()
        fol._leader_transport = DeadTransport()
        wd = failover.LeaderLossWatchdog(fol, grace_s=0.15,
                                         interval_s=0.05)
        t0 = time.monotonic()
        wd.start()
        try:
            while (fol.replication_hub is None
                   and time.monotonic() - t0 < flight_window_s):
                await asyncio.sleep(0.02)
            elapsed = time.monotonic() - t0
            assert fol.replication_hub is not None, \
                "promotion did not happen"
            assert elapsed < flight_window_s, \
                f"failover took {elapsed:.2f}s (window {flight_window_s}s)"
            # the promoted node is immediately writable
            rev = await fol.endpoint.write_relationships([
                RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                    "namespace:ns3#viewer@user:postwd"))])
            assert rev > 0
        finally:
            await wd.stop()

    asyncio.run(go())


# -- fan-out trees -----------------------------------------------------------


def _make_chain(tmp, kube, leader):
    mid, _ = make_follower(
        leader, kube, serve_replication=True,
        mirror_dir=os.path.join(tmp, "mirror"), replica_id="mid")
    leaf, _ = make_follower(mid, kube, replica_wait_ms=30.0)
    return mid, leaf


def test_fanout_chain_parity_and_chain_lag(tmp):
    leader, kube = make_leader(tmp)
    leader.persistence.checkpoint()  # bootstrap via mirrored checkpoint
    mid, leaf = _make_chain(tmp, kube, leader)

    async def go():
        for i in range(6):
            await churn(leader, i)
        await mid.replication.sync_once()
        await leaf.replication.sync_once()
        assert (leaf.replication.store.revision
                == leader.endpoint.store.revision)
        await assert_parity(leader, leaf)
        # provenance: the leaf sees the full upstream path
        assert (leaf.replication.upstream_chain["path"]
                == [leader.replication_hub.leader_id, "mid"])
        dbg = json.loads((await leaf.get_embedded_client("a").get(
            "/debug/replication")).body)
        assert dbg["upstream_path"] == [
            leader.replication_hub.leader_id, "mid"]
        mid_dbg = json.loads((await mid.get_embedded_client("a").get(
            "/debug/replication")).body)
        assert mid_dbg["fanout"]["serves_replication"]
        # incarnation passes through unchanged down the chain
        assert (leaf.replication.max_incarnation
                == leader.replication_hub.incarnation)

        # chain lag is additive: the mid falls behind, the (locally
        # caught-up) leaf reports the mid's hop in its own lag
        for i in range(6, 11):
            await churn(leader, i)
        await mid.replication._fetch_manifest(wait=False)  # sees lag
        assert mid.replication.lag_revisions() > 0
        await leaf.replication.sync_once()
        assert (leaf.replication.lag_revisions()
                >= mid.replication.lag_revisions())

        # the mid catches up; the chain drains to parity end to end
        await mid.replication.sync_once()
        await leaf.replication.sync_once()
        assert (leaf.replication.store.revision
                == leader.endpoint.store.revision)
        assert leaf.replication.lag_revisions() == 0.0
        await assert_parity(leader, leaf)

    asyncio.run(go())


def test_fanout_write_forwards_up_the_chain(tmp):
    leader, kube = make_leader(tmp)
    leader.enable_dual_writes()
    mid, leaf = _make_chain(tmp, kube, leader)

    async def go():
        await mid.replication.sync_once()
        await leaf.replication.sync_once()
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "deep", "namespace": "ns0"}}
        resp = await leaf.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", pod)
        assert resp.status in (200, 201), resp.body
        assert resp.headers.get("X-Authz-Forwarded-To") == "leader"
        # the dual-write landed on the ROOT leader...
        assert leader.endpoint.store.has_exact(parse_relationship(
            "pod:ns0/deep#creator@user:alice"))
        # ...and replicates back down through the tree
        await mid.replication.sync_once()
        await leaf.replication.sync_once()
        assert leaf.replication.store.has_exact(parse_relationship(
            "pod:ns0/deep#creator@user:alice"))

    asyncio.run(go())


# -- ZedToken propagation (satellite) ----------------------------------------


def test_min_revision_propagates_through_forwarded_reads(tmp):
    leader, kube = make_leader(tmp)
    mid, leaf = _make_chain(tmp, kube, leader)
    mid.opts.replica_wait_ms = 30.0

    async def go():
        await mid.replication.sync_once()
        await leaf.replication.sync_once()
        rev = await leader.endpoint.write_relationships([
            RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
                "namespace:ns5#viewer@user:zed"))])
        # neither hop has applied `rev`: the leaf waits, forwards to the
        # mid; the mid's gate sees the SAME token (propagated), waits,
        # forwards to the leader — the answer is fresh, never stale.  A
        # dropped header would have served the mid's stale store (no
        # ns5 for zed) instead.
        resp, items = await list_ns(
            leaf, "zed", headers=[(MIN_REVISION_HEADER, str(rev))])
        assert resp.status == 200
        assert resp.headers.get("X-Authz-Forwarded-To") == "leader"
        assert items == ["ns5"]

    asyncio.run(go())


def test_min_revision_propagates_on_forwarded_writes(tmp):
    leader, kube = make_leader(tmp, replica_wait_ms=50.0)
    leader.enable_dual_writes()
    fol, _ = make_follower(leader, kube)

    async def go():
        await fol.replication.sync_once()
        rev = leader.endpoint.store.revision
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "w1", "namespace": "ns0"}}
        # satisfiable token rides the forwarded write and succeeds
        resp = await fol.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", pod,
            headers=[(MIN_REVISION_HEADER, str(rev))])
        assert resp.status in (200, 201), resp.body
        # an unsatisfiable token fails LOUDLY on the leader — proof the
        # header crossed the forward hop instead of being dropped
        resp = await fol.get_embedded_client("alice").post(
            "/api/v1/namespaces/ns0/pods", dict(
                pod, metadata={"name": "w2", "namespace": "ns0"}),
            headers=[(MIN_REVISION_HEADER,
                      str(leader.endpoint.store.revision + 50))])
        assert resp.status == 503
        assert b"not available on this leader" in resp.body

    asyncio.run(go())


def test_leader_honors_min_revision_waits_then_503(tmp):
    leader, _ = make_leader(tmp, replica_wait_ms=500.0)

    async def go():
        client = leader.get_embedded_client("u1")
        rev = leader.endpoint.store.revision

        async def poke():
            await asyncio.sleep(0.05)
            await churn(leader, 0)

        task = asyncio.ensure_future(poke())
        resp, _ = await list_ns(
            leader, "u0", headers=[(MIN_REVISION_HEADER, str(rev + 1))])
        await task
        assert resp.status == 200  # waited for the concurrent commit
        # far-ahead token: bounded wait, then a loud 503 — never a
        # below-token answer (post-failover safety)
        leader.opts.replica_wait_ms = 30.0
        resp = await client.get(
            "/api/v1/namespaces",
            headers=[(MIN_REVISION_HEADER,
                      str(leader.endpoint.store.revision + 10))])
        assert resp.status == 503
        # malformed token: 400
        resp = await client.get(
            "/api/v1/namespaces",
            headers=[(MIN_REVISION_HEADER, "banana")])
        assert resp.status == 400

    asyncio.run(go())


# -- fault matrix -------------------------------------------------------------


def test_fault_matrix_no_hang_anywhere(tmp):
    """Every injected replication fault fails FAST (no hangs), never
    stops the follower from serving its adopted state, and recovery
    after disarm converges to parity."""
    leader, kube = make_leader(tmp)
    leader.persistence.checkpoint()

    async def drive(follower, fault, kind, pre_churn, fresh):
        for i in range(pre_churn):
            await churn(leader, random.randrange(1000))
        if not fresh:
            await follower.replication.sync_once()
            await churn(leader, random.randrange(1000))
        _, before = await list_ns(follower, "u1")
        enable_failpoint(fault, 1, kind=kind)
        with pytest.raises(Exception):
            await asyncio.wait_for(follower.replication.sync_once(),
                                   timeout=3.0)
        # still serving (bounded staleness) mid-fault
        resp, after = await list_ns(follower, "u1")
        assert resp.status == 200 and after == before
        disable_all()
        await follower.replication.sync_once()
        assert (follower.replication.store.revision
                == leader.endpoint.store.revision)
        await assert_parity(leader, follower)

    async def go():
        cases = [
            # (failpoint, kind, fresh follower?)
            ("replManifestPoll", KIND_PANIC, False),
            ("replManifestPoll", KIND_REFUSE, False),  # partition
            ("replLeaderLink", KIND_REFUSE, False),    # partition
            ("replServeManifest", KIND_REFUSE, False),  # leader side
            ("replSegmentFetch", KIND_PANIC, False),
            ("replCheckpointFetch", KIND_PANIC, True),
            ("replBootstrapAdopt", KIND_PANIC, True),
            ("replBootstrapFinish", KIND_PANIC, True),
        ]
        for fault, kind, fresh in cases:
            follower, _ = make_follower(leader, kube)
            await drive(follower, fault, kind, pre_churn=2, fresh=fresh)

    asyncio.run(go())


def test_torn_bootstrap_never_serves_half_adopted_store(tmp):
    """Satellite: a follower that crashes mid-checkpoint-adoption
    restarts cleanly from the manifest — the store is either the old
    state or the fully-adopted checkpoint, never in between."""
    leader, kube = make_leader(tmp)

    async def go():
        for i in range(6):
            await churn(leader, i)
        leader.persistence.checkpoint()

        # crash BEFORE adoption: nothing adopted, /readyz stays 503
        f1, _ = make_follower(leader, kube)
        enable_failpoint("replBootstrapAdopt", 1)
        with pytest.raises(FailPointPanic):
            await f1.replication.sync_once()
        assert f1.replication.store.revision == 0
        assert not f1.replication.ever_bootstrapped
        ready = await f1.get_embedded_client("x").get("/readyz")
        assert ready.status == 503
        disable_all()
        await f1.replication.sync_once()
        assert (f1.replication.store.revision
                == leader.endpoint.store.revision)
        await assert_parity(leader, f1)

        # crash AFTER adoption but before the cursor/flags land: the
        # retry re-adopts idempotently from the manifest
        f2, _ = make_follower(leader, kube)
        enable_failpoint("replBootstrapFinish", 1)
        with pytest.raises(FailPointPanic):
            await f2.replication.sync_once()
        assert not f2.replication.bootstrapped
        rev_mid = f2.replication.store.revision
        assert rev_mid in (0, leader.persistence._last_ckpt_revision)
        disable_all()
        await f2.replication.sync_once()
        assert (f2.replication.store.revision
                == leader.endpoint.store.revision)
        await assert_parity(leader, f2)

    asyncio.run(go())


# -- jittered backoff (satellite) --------------------------------------------


def test_backoff_is_jittered_exponential_with_cap():
    from spicedb_kubeapi_proxy_tpu.spicedb.replication.follower import (
        ReplicaFollower,
    )
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    fol = ReplicaFollower(TupleStore(), DeadTransport(),
                          retry_backoff_s=1.0, retry_backoff_cap_s=15.0,
                          rng=random.Random(42))
    cur = 1.0
    sleeps = []
    for _ in range(8):
        sleep_s, cur2 = fol._next_backoff(cur)
        assert cur / 2 <= sleep_s < cur, (sleep_s, cur)
        assert cur2 == min(cur * 2.0, 15.0)
        sleeps.append(sleep_s)
        cur = cur2
    assert cur == 15.0  # capped
    # jitter: the draws are not a deterministic halving/doubling ladder
    ratios = {round(s / b, 4)
              for s, b in zip(sleeps, [1, 2, 4, 8, 15, 15, 15, 15])}
    assert len(ratios) > 1


def test_run_loop_backoff_jitters_between_retries(tmp):
    leader, kube = make_leader(tmp)
    fol, _ = make_follower(leader, kube)
    fol.replication._rng = random.Random(7)

    async def go():
        await fol.replication.sync_once()
        fol.replication.transport = DeadTransport()
        sleeps = []
        real_sleep = asyncio.sleep

        async def fake_sleep(s, *a, **kw):
            sleeps.append(s)
            if len(sleeps) >= 6:
                raise asyncio.CancelledError
            await real_sleep(0)

        asyncio.sleep = fake_sleep
        try:
            with pytest.raises(asyncio.CancelledError):
                await fol.replication.run()
        finally:
            asyncio.sleep = real_sleep
        assert len(sleeps) == 6
        # jittered: distinct values, each inside its doubling band
        bands = [1, 2, 4, 8, 15, 15]
        for s, b in zip(sleeps, bands):
            assert b / 2 <= s < b, (s, b)
        assert len({round(s / b, 4)
                    for s, b in zip(sleeps, bands)}) > 1
        assert fol.replication.stats["poll_errors"] >= 6

    asyncio.run(go())
