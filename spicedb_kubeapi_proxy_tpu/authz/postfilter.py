"""PostFilter: per-item bulk checks on list responses
(reference pkg/authz/postfilter.go).

Each returned item resolves every PostFilter CheckPermissionTemplate against
an item-scoped input; one CheckBulkPermissions covers all items, and an item
is kept only if all of its checks pass.  Items whose templates fail to
resolve keep going (the check is skipped), matching the reference's
tolerance (postfilter.go:90-96).
"""

from __future__ import annotations

import json

from ..rules.engine import ResolveInput, new_resolve_input, resolve_rel
from ..spicedb.endpoints import PermissionsEndpoint
from .check import check_request_from_rel


async def filter_list_response(body: bytes, filtered_rules: list,
                               input: ResolveInput,
                               endpoint: PermissionsEndpoint) -> bytes:
    """Returns the filtered body (reference postfilter.go:17-55)."""
    try:
        decoded = json.loads(body)
    except ValueError as e:
        raise ValueError(f"failed to parse list response: {e}") from e
    items = decoded.get("items")
    if not isinstance(items, list) or not items:
        return body

    bulk_reqs = []
    item_to_requests: dict[int, list] = {}
    for idx, item in enumerate(items):
        if not isinstance(item, dict):
            continue
        meta = item.get("metadata") or {}
        obj = {"metadata": {"name": meta.get("name", ""),
                            "namespace": meta.get("namespace", "")}}
        item_input = new_resolve_input(input.request, input.user, obj, b"", {})
        for r in filtered_rules:
            for f in r.post_filter:
                try:
                    rel = resolve_rel(f.rel, item_input)
                except Exception:
                    continue  # skip this check, don't fail the operation
                item_to_requests.setdefault(idx, []).append(len(bulk_reqs))
                bulk_reqs.append(check_request_from_rel(rel))

    if not bulk_reqs:
        return body

    results = await endpoint.check_bulk_permissions(bulk_reqs)
    allowed_items = []
    for idx, item in enumerate(items):
        indices = item_to_requests.get(idx)
        if indices is None:
            allowed_items.append(item)
            continue
        if all(results[i].allowed for i in indices):
            allowed_items.append(item)
    decoded["items"] = allowed_items
    return json.dumps(decoded).encode()
