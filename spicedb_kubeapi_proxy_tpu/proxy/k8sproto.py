"""Kubernetes protobuf envelope codec (wire-level, schema-free).

The reference decodes/re-encodes negotiated protobuf bodies through the
k8s runtime codec factory (reference pkg/authz/responsefilterer.go:241-301,
rejecting protobuf only for unrecognized GVKs at 278-280).  This build works
at the protobuf WIRE level instead of generated codecs, exploiting the
layout conventions shared by every native Kubernetes API type (see
k8s.io/apimachinery/pkg/runtime/generated.proto and
pkg/apis/meta/v1/generated.proto):

- a serialized body is the 4-byte magic `k8s\x00` + a `runtime.Unknown`
  message: typeMeta=1 (apiVersion=1, kind=2), raw=2, contentEncoding=3,
  contentType=4;
- every list type is `{ ListMeta metadata = 1; repeated Item items = 2; }`;
- every object type carries `ObjectMeta metadata = 1`, and ObjectMeta is
  `{ name = 1; generateName = 2; namespace = 3; ... }`.

Filtering a list therefore never re-encodes items: disallowed `items`
records are SPLICED OUT of the raw bytes (field-2 length-delimited records
are dropped wholesale; everything else is copied verbatim), which both
preserves unknown fields byte-exactly and avoids needing any type schema.
Bodies that don't follow the conventions raise K8sProtoError — the
behavioral analog of the reference's reject-unrecognized path.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

K8S_MAGIC = b"k8s\x00"


class K8sProtoError(ValueError):
    pass


# -- protobuf wire primitives -------------------------------------------------

def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        if i >= len(buf):
            raise K8sProtoError("truncated varint")
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 63:
            raise K8sProtoError("varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def records(buf: bytes) -> Iterator[tuple]:
    """Yield (field_no, wire_type, record_start, record_end, value) for each
    top-level record.  `value` is the payload bytes for length-delimited
    fields, the int for varints, raw bytes otherwise."""
    i = 0
    n = len(buf)
    while i < n:
        start = i
        key, i = _read_varint(buf, i)
        field_no = key >> 3
        wt = key & 7
        if wt == 0:  # varint
            v, i = _read_varint(buf, i)
            yield (field_no, wt, start, i, v)
        elif wt == 1:  # fixed64
            if i + 8 > n:
                raise K8sProtoError("truncated fixed64")
            yield (field_no, wt, start, i + 8, buf[i: i + 8])
            i += 8
        elif wt == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            if i + ln > n:
                raise K8sProtoError("truncated length-delimited field")
            yield (field_no, wt, start, i + ln, buf[i: i + ln])
            i += ln
        elif wt == 5:  # fixed32
            if i + 4 > n:
                raise K8sProtoError("truncated fixed32")
            yield (field_no, wt, start, i + 4, buf[i: i + 4])
            i += 4
        else:
            raise K8sProtoError(f"unsupported wire type {wt}")


def field_bytes(buf: bytes, field_no: int) -> Optional[bytes]:
    """Last occurrence of a length-delimited field, or None."""
    out = None
    for f, wt, _, _, v in records(buf):
        if f == field_no and wt == 2:
            out = v
    return out


def _ld(field_no: int, payload: bytes) -> bytes:
    return _write_varint(field_no << 3 | 2) + _write_varint(len(payload)) + payload


# -- the k8s envelope ---------------------------------------------------------

def is_k8s_proto(body: bytes) -> bool:
    return body.startswith(K8S_MAGIC)


def decode_unknown(body: bytes) -> tuple:
    """`k8s\x00` + runtime.Unknown -> (api_version, kind, raw,
    content_type)."""
    if not body.startswith(K8S_MAGIC):
        raise K8sProtoError("missing k8s protobuf magic prefix")
    buf = body[len(K8S_MAGIC):]
    api_version = kind = content_type = ""
    raw = b""
    for f, wt, _, _, v in records(buf):
        if f == 1 and wt == 2:  # TypeMeta
            for f2, wt2, _, _, v2 in records(v):
                if f2 == 1 and wt2 == 2:
                    api_version = v2.decode("utf-8")
                elif f2 == 2 and wt2 == 2:
                    kind = v2.decode("utf-8")
        elif f == 2 and wt == 2:
            raw = v
        elif f == 4 and wt == 2:
            content_type = v.decode("utf-8")
    return api_version, kind, raw, content_type


def encode_unknown(api_version: str, kind: str, raw: bytes,
                   content_type: str = "") -> bytes:
    type_meta = _ld(1, api_version.encode()) + _ld(2, kind.encode())
    out = _ld(1, type_meta) + _ld(2, raw)
    if content_type:
        out += _ld(4, content_type.encode())
    return K8S_MAGIC + out


def object_meta(obj_raw: bytes) -> tuple:
    """(namespace, name) from a serialized object's ObjectMeta (field 1;
    name=1, namespace=3 per meta/v1 generated.proto)."""
    meta = field_bytes(obj_raw, 1)
    if meta is None:
        return "", ""
    name = namespace = ""
    for f, wt, _, _, v in records(meta):
        if f == 1 and wt == 2:
            name = v.decode("utf-8")
        elif f == 3 and wt == 2:
            namespace = v.decode("utf-8")
    return namespace, name


def filter_list_raw(raw: bytes,
                    is_allowed: Callable[[str, str], bool]) -> bytes:
    """Drop disallowed `items` (field 2) records by byte-splicing; all other
    fields (ListMeta, unknown extensions) are copied verbatim."""
    out = bytearray()
    for f, wt, start, end, v in records(raw):
        if f == 2 and wt == 2:
            namespace, name = object_meta(v)
            if not is_allowed(namespace, name):
                continue
        out += raw[start:end]
    return bytes(out)


def iter_list_items(raw: bytes) -> Iterator[bytes]:
    for f, wt, _, _, v in records(raw):
        if f == 2 and wt == 2:
            yield v


# -- Table support ------------------------------------------------------------
# meta/v1 Table: { ListMeta metadata=1; columnDefinitions=2; rows=3 }
# TableRow:      { cells(RawExtension)=1; conditions=2; object(RawExtension)=3 }
# RawExtension:  { bytes raw = 1 }  (the object raw is itself `k8s\x00`+Unknown
# for proto-negotiated tables)

def _table_row_meta(row: bytes) -> tuple:
    obj_ext = field_bytes(row, 3)
    if obj_ext is None:
        return "", ""
    obj_raw = field_bytes(obj_ext, 1)
    if obj_raw is None:
        return "", ""
    if obj_raw.startswith(K8S_MAGIC):
        _, _, obj_raw, _ = decode_unknown(obj_raw)
    return object_meta(obj_raw)


def filter_table_raw(raw: bytes,
                     is_allowed: Callable[[str, str], bool]) -> bytes:
    """Drop disallowed Table rows (field 3) by byte-splicing."""
    out = bytearray()
    for f, wt, start, end, v in records(raw):
        if f == 3 and wt == 2:
            namespace, name = _table_row_meta(v)
            if not is_allowed(namespace, name):
                continue
        out += raw[start:end]
    return bytes(out)


# -- watch stream support -----------------------------------------------------
# Protobuf watch streams are length-delimited frames (4-byte big-endian
# length prefix, k8s.io/apimachinery/pkg/util/framer); each payload is a
# RAW-serialized metav1.WatchEvent { type = 1; object(RawExtension) = 2 }
# whose object.raw is a full `k8s\x00` envelope (the apiserver's embedded
# watch encoder re-envelopes the object with the negotiated serializer).
# The reference decodes these via its negotiated streaming codec
# (responsefilterer.go:500-506); this is the wire-level equivalent.

def decode_watch_event(payload: bytes) -> tuple:
    """(event_type, api_version, kind, obj_raw) from a raw-serialized
    metav1.WatchEvent payload (no length prefix, no envelope).  The embedded
    object's `k8s\\x00` envelope is stripped when present so `obj_raw` is
    directly usable with object_meta()."""
    event_type = ""
    obj_raw = b""
    api_version = kind = ""
    for f, wt, _, _, v in records(payload):
        if f == 1 and wt == 2:
            event_type = v.decode("utf-8")
        elif f == 2 and wt == 2:
            obj_raw = field_bytes(v, 1) or b""
    if obj_raw.startswith(K8S_MAGIC):
        api_version, kind, obj_raw, _ = decode_unknown(obj_raw)
    return event_type, api_version, kind, obj_raw


def table_first_row_meta(table_raw: bytes) -> tuple:
    """(namespace, name) of the first row's object in a serialized
    meta/v1 Table (watch Table events carry one row per event)."""
    for f, wt, _, _, v in records(table_raw):
        if f == 3 and wt == 2:
            return _table_row_meta(v)
    return "", ""


def encode_watch_event(event_type: str, obj_envelope: bytes) -> bytes:
    """A framed watch event (4-byte length prefix included) for the fake
    apiserver / tests.  `obj_envelope` is a full `k8s\\x00` envelope."""
    payload = _ld(1, event_type.encode()) + _ld(2, _ld(1, obj_envelope))
    return len(payload).to_bytes(4, "big") + payload


# -- encode helpers (used by the fake apiserver to SERVE protobuf) ------------

def encode_object_meta(name: str, namespace: str = "",
                       extra_json: Optional[dict] = None) -> bytes:
    out = _ld(1, name.encode())
    if namespace:
        out += _ld(3, namespace.encode())
    return out


def encode_object(api_version: str, kind: str, name: str,
                  namespace: str = "") -> bytes:
    """A minimal serialized object: just ObjectMeta (field 1)."""
    return _ld(1, encode_object_meta(name, namespace))


def encode_list(api_version: str, kind: str, items: list) -> bytes:
    """items: serialized object payloads (encode_object outputs)."""
    raw = _ld(1, b"")  # empty ListMeta
    for item in items:
        raw += _ld(2, item)
    return encode_unknown(api_version, kind, raw,
                          "application/vnd.kubernetes.protobuf")


def encode_table(row_objects: list) -> bytes:
    """A serialized meta/v1 Table envelope.  `row_objects` are the per-row
    object payloads — either plain serialized objects or full `k8s\\x00`
    envelopes (the real apiserver nests envelopes; _table_row_meta handles
    both).  Each becomes rows[i].object.raw (RawExtension field 1)."""
    raw = _ld(1, b"")  # empty ListMeta
    for obj in row_objects:
        raw += _ld(3, _ld(3, _ld(1, obj)))  # row{ object{ raw } }
    return encode_unknown("meta.k8s.io/v1", "Table", raw,
                          "application/vnd.kubernetes.protobuf")
