"""Durable relationship store: WAL + checkpoints + crash recovery.

`PersistenceManager` owns one data directory:

    <data-dir>/
      MANIFEST.json            newest durable checkpoint (atomic rename)
      checkpoints/ckpt-*.npz   columnar checkpoints (checkpoint.py)
      wal/seg-*.wal            CRC-framed record segments (wal.py)
      wal/snap-*.npz           bulk-load snapshot sidecars

Lifecycle:

    mgr = PersistenceManager(data_dir, ...)
    store = mgr.recover()       # checkpoint load + WAL tail replay
    mgr.attach(store)           # journal every commit from here on
    ... create_endpoint(..., store=store); serve ...
    await mgr.start()           # periodic checkpoint loop
    await mgr.stop()            # final checkpoint + close

Journaling rides the store's commit listeners, which fire synchronously
under the store lock: the WAL observes exactly the committed revision
order, and no reader can see a revision the WAL hasn't.  Record
vocabulary (compact JSON, see wal.py for framing):

    {"k":"d","r":REV,"u":[["t"|"d", rel_string],...],"i":[idem_ids]}
    {"k":"s","r":REV,"f":"snap-REV.npz"}     columnar bulk load (sidecar
                                             written+fsynced BEFORE the
                                             record referencing it)
    {"k":"b","r":REV,"u":[rel_string,...]}   object-path bulk load
    {"k":"c","r":REV}                        delete_all

`"i"` carries the dual-write idempotency-key activity ids present in the
batch (workflow:*#idempotency_key@activity:*): after a crash the
recovered store still holds those tuples, which is what lets a replayed
`write_to_spicedb` activity detect an already-applied write
(authz/distributedtx/activity.py) instead of double-writing.

Recovery restores the revision counter (`TupleStore.adopt_recovery_state`
sets the checkpoint's exact revision; `apply_recovery_batch` advances it
once per replayed record, cross-checked against each record's stamp),
so ZedTokens (checked_at), decision-cache epochs, and watch revisions
stay continuous across a restart — a revision is never reused for
different state.  Expirations ride along (the expiry column + rel-string
suffixes), so `TupleStore.expiry_schedule()` reseeds the decision-cache
and device-graph expiry heaps with pre-crash deadlines.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
import weakref
from typing import Optional

import numpy as np

from ...utils import metrics as m
from ...utils import tracing
from ...utils.failpoints import FailPointPanic
from ..columnar import _COLS, ColumnarSnapshot
from ..store import TupleStore
from ..types import RelationshipUpdate, UpdateOp, parse_relationship
from . import checkpoint as ckpt
from .wal import (
    DEFAULT_SEGMENT_BYTES,
    FSYNC_INTERVAL,
    SegmentedWal,
    WalCorruptionError,
)

logger = logging.getLogger("spicedb_kubeapi_proxy_tpu.persist")

DEFAULT_CHECKPOINT_INTERVAL = 300.0

# dual-write idempotency-key tuple shape (activity.py): recovery
# coordination metadata carried in delta records
_IDEM_TYPE = "workflow"
_IDEM_RELATION = "idempotency_key"


class PersistenceUnavailableError(RuntimeError):
    """A WAL append failed earlier in this process.  The aborted commit
    never became visible (the store journals BEFORE mutating), but the
    failed append may still have landed a complete frame on disk — the
    revision number it named cannot safely be reused for different
    state, so the store fails stop: writes keep erroring until a
    restart re-derives the truth from the log."""


# gate-off = no manager exists (the server requires --data-dir AND the
# DurableStore gate before constructing one): nothing journals or counts
class PersistenceManager:  # noqa: A004(built behind gate)
    """Segmented WAL + periodic columnar checkpoints over one data dir."""

    def __init__(self, data_dir: str,
                 fsync: str = FSYNC_INTERVAL,
                 fsync_interval: float = 1.0,
                 checkpoint_interval: float = DEFAULT_CHECKPOINT_INTERVAL,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 clock=time.time,
                 registry: Optional[m.Registry] = None):
        if checkpoint_interval <= 0:
            raise ValueError("checkpoint_interval must be > 0")
        self.data_dir = data_dir
        self.checkpoint_interval = checkpoint_interval
        self._clock = clock
        self.ckpt_dir = os.path.join(data_dir, ckpt.CHECKPOINT_DIR)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self.wal = SegmentedWal(os.path.join(data_dir, "wal"),
                                fsync=fsync, fsync_interval=fsync_interval,
                                segment_bytes=segment_bytes,
                                registry=registry)
        self._store: Optional[TupleStore] = None
        self._task: Optional[asyncio.Task] = None
        self._flush_task: Optional[asyncio.Task] = None
        self._wal_failed = False
        # checkpoint cycles must not overlap: stop()'s final checkpoint
        # can race an in-flight periodic one (task.cancel does not stop
        # the executor thread), and two concurrent _reclaim passes could
        # delete each other's just-published checkpoint file
        self._ckpt_lock = threading.Lock()
        self.recovered = False
        self.recovery_info: dict = {}
        self._last_ckpt_revision = 0
        self._last_ckpt_unix: Optional[float] = None
        registry = registry or m.REGISTRY
        self._ckpt_hist = registry.histogram(
            "authz_checkpoint_seconds",
            "Wall time of one store checkpoint (capture + serialize + "
            "manifest + reclaim)",
            buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0))
        self._ckpt_total = registry.counter(
            "authz_checkpoints_total", "Completed store checkpoints")
        ref = weakref.ref(self)

        def _age() -> float:
            mgr = ref()
            if mgr is None or mgr._last_ckpt_unix is None:
                return -1.0
            return time.time() - mgr._last_ckpt_unix

        registry.gauge(
            "authz_checkpoint_age_seconds",
            "Seconds since the newest durable checkpoint (-1 = none yet)",
            callback=_age)
        def _segments() -> float:
            mgr = ref()
            return float(mgr.wal.segment_count()) if mgr is not None else 0.0

        def _wal_bytes() -> float:
            mgr = ref()
            return float(mgr.wal.total_bytes()) if mgr is not None else 0.0

        registry.gauge(
            "authz_wal_segments",
            "Live write-ahead-log segment files", callback=_segments)
        registry.gauge(
            "authz_wal_bytes",
            "Total bytes across live write-ahead-log segments",
            callback=_wal_bytes)

    # -- recovery ------------------------------------------------------------

    def recover(self) -> TupleStore:
        """Build a TupleStore from the newest valid checkpoint plus the
        WAL tail; restores the revision counter.  Safe on an empty data
        dir (returns a fresh store at revision 0, `recovered` False —
        the bootstrap-once signal)."""
        store = TupleStore(clock=self._clock)
        info = {"checkpoint_revision": 0, "replayed_records": 0,
                "replayed_updates": 0, "idempotency_keys": 0,
                "torn_records": 0}
        t0 = time.perf_counter()
        with tracing.request_trace(op="store_recovery") as tr:
            with tracing.span("recovery.checkpoint_load", phase=True):
                manifest = ckpt.read_manifest(self.data_dir)
                if manifest is not None:
                    self._load_checkpoint(store, manifest, info)
            with tracing.span("recovery.wal_replay", phase=True):
                self._replay_wal(store, info)
            info["torn_records"] = self.wal.torn_records
            info["revision"] = store.revision
            tr.attrs.update(revision=store.revision)
        tracing.RECORDER.record(tr)
        phases = tr.phase_durations()
        info["checkpoint_load_s"] = round(
            phases.get("recovery.checkpoint_load", 0.0), 6)
        info["wal_replay_s"] = round(
            phases.get("recovery.wal_replay", 0.0), 6)
        info["total_s"] = round(time.perf_counter() - t0, 6)
        self.recovered = store.revision > 0
        self.recovery_info = info
        if self.recovered:
            logger.info(
                "recovered store at revision %d (checkpoint rev %d, %d WAL "
                "records, %d torn) in %.3fs", store.revision,
                info["checkpoint_revision"], info["replayed_records"],
                info["torn_records"], info["total_s"])
        return store

    def _load_checkpoint(self, store: TupleStore, manifest: dict,
                         info: dict) -> None:
        path = os.path.join(self.ckpt_dir, manifest["checkpoint"])
        snap, overlay, meta = ckpt.load_columnar_file(path)
        # wholesale adoption at EXACTLY the manifest revision — loading
        # base + overlay as separate revision-bumping steps would strand
        # low-revision checkpoints (e.g. rev 1 with a caveated overlay)
        store.adopt_recovery_state(snap if len(snap) else None, overlay,
                                   int(manifest["revision"]))
        info["checkpoint_revision"] = int(manifest["revision"])
        info["checkpoint_tuples"] = len(snap) + len(overlay)
        self._last_ckpt_revision = int(manifest["revision"])
        self._last_ckpt_unix = manifest.get("created_unix")

    def _replay_wal(self, store: TupleStore, info: dict) -> None:
        for rec in self.wal.replay():
            rev = int(rec["r"])
            if rev <= store.revision:
                continue  # covered by the checkpoint
            if rev != store.revision + 1:
                raise WalCorruptionError(
                    f"revision gap in WAL: store at {store.revision}, "
                    f"next record {rev}")
            kind = rec["k"]
            if kind == "d":
                updates = [
                    RelationshipUpdate(
                        UpdateOp.DELETE if op == "d" else UpdateOp.TOUCH,
                        parse_relationship(s))
                    for op, s in rec.get("u", ())]
                store.apply_recovery_batch(updates)
                info["replayed_updates"] += len(updates)
                info["idempotency_keys"] += len(rec.get("i", ()))
            elif kind == "s":
                snap, overlay, _ = ckpt.load_columnar_file(
                    os.path.join(self.wal.dir, rec["f"]))
                store.bulk_load_snapshot(snap)
                info["replayed_updates"] += len(snap) + len(overlay)
            elif kind == "b":
                rels = [parse_relationship(s) for s in rec.get("u", ())]
                store.bulk_load(rels)
                info["replayed_updates"] += len(rels)
            elif kind == "c":
                store.delete_all()
            else:
                raise WalCorruptionError(f"unknown WAL record kind {kind!r}")
            if store.revision != rev:
                raise WalCorruptionError(
                    f"replay of kind {kind!r} landed at revision "
                    f"{store.revision}, record says {rev}")
            info["replayed_records"] += 1

    # -- journaling ----------------------------------------------------------

    def attach(self, store: TupleStore) -> None:
        """Start journaling `store`'s commits.  Attach BEFORE applying
        bootstrap data so the bootstrap itself is durable."""
        if self._store is not None:
            raise RuntimeError("already attached")
        self._store = store
        store.add_commit_listener(self._on_commit)

    def detach(self) -> None:
        if self._store is not None:
            self._store.remove_commit_listener(self._on_commit)
            self._store = None

    def _on_commit(self, kind: str, revision: int, payload) -> None:
        # runs synchronously under the store lock (store.py `_commit`)
        if self._wal_failed:
            raise PersistenceUnavailableError(
                "a previous WAL append failed; refusing further writes "
                "(the failed append may or may not be on disk, so its "
                "revision cannot be reused — restart to re-derive the "
                "truth from the log)")
        try:
            self._journal_commit(kind, revision, payload)
        except FailPointPanic:
            raise  # simulated crash: the test abandons this process
        except Exception:
            # the commit aborts un-applied (the store journals before
            # mutating), but a complete frame for `revision` may still
            # sit on disk: re-issuing that revision with different
            # state would make replay silently skip it — fail stop
            self._wal_failed = True
            logger.exception(
                "WAL append failed at revision %d; store is no longer "
                "durable, refusing further writes", revision)
            raise

    def _journal_commit(self, kind: str, revision: int, payload) -> None:
        if kind == "delta":
            ops = []
            idem = []
            for u in payload:
                delete = u.op == UpdateOp.DELETE
                ops.append(["d" if delete else "t", u.rel.rel_string()])
                if (not delete and u.rel.resource.type == _IDEM_TYPE
                        and u.rel.relation == _IDEM_RELATION):
                    idem.append(u.rel.subject.id)
            rec = {"k": "d", "r": revision, "u": ops}
            if idem:
                rec["i"] = idem
        elif kind == "snapshot":
            fname = f"snap-{revision:012d}.npz"
            self._save_sidecar(payload, fname)
            rec = {"k": "s", "r": revision, "f": fname}
        elif kind == "bulk":
            rec = {"k": "b", "r": revision,
                   "u": [r.rel_string() for r in payload]}
        elif kind == "clear":
            rec = {"k": "c", "r": revision}
        else:  # pragma: no cover - future store commit kinds
            raise ValueError(f"unknown commit kind {kind!r}")
        self.wal.append(json.dumps(rec, separators=(",", ":")).encode(),
                        kind=kind)

    def _save_sidecar(self, snap: ColumnarSnapshot, fname: str) -> None:
        """Persist a bulk-loaded snapshot next to the WAL; written and
        fsynced BEFORE the record referencing it, so a record present in
        the stream implies a readable sidecar."""
        cols = {name: getattr(snap, name) for name in _COLS}
        ckpt.save_columnar_file(
            os.path.join(self.wal.dir, fname), snap.pool, cols,
            snap.expiry, overlay=[], meta={"revision": 0})

    # -- checkpoints ---------------------------------------------------------

    def checkpoint(self) -> Optional[dict]:
        """One checkpoint cycle: capture the store state + seal the WAL
        under the store lock, serialize outside it, publish the manifest
        atomically, reclaim covered segments.  Returns the manifest, or
        None when the store hasn't advanced since the last checkpoint."""
        store = self._store
        if store is None:
            raise RuntimeError("not attached to a store")
        with self._ckpt_lock:
            if store.revision in (0, self._last_ckpt_revision):
                # nothing new: no timer observation — the histogram must
                # only measure real cycles or its mean collapses to the
                # no-op cost on idle stores (revisions only grow, so the
                # re-read under the store lock below stays != last)
                return None
            return self._checkpoint_locked(store)

    def _checkpoint_locked(self, store: TupleStore) -> dict:
        with m.Timer(self._ckpt_hist):
            with store.lock:
                revision = store.revision
                view = store.columnar_view()
                rels = None if view is not None else store.read(None)
                watermark = self.wal.cut()
            # serialization runs OUTSIDE the store lock: the snapshot
            # arrays are immutable and the captured row indices / overlay
            # list are private copies, so writers proceed concurrently
            if view is not None:
                snap, rows, overlay = view
                cols = {name: getattr(snap, name)[rows] for name in _COLS}
                expiry = snap.expiry[rows]
                pool = snap.pool
                overlay_strings = [r.rel_string() for r in overlay]
            else:
                plain = [r for r in rels if r.caveat is None]
                overlay_strings = [r.rel_string() for r in rels
                                   if r.caveat is not None]
                csnap = ColumnarSnapshot.from_relationships(plain)
                cols = {name: getattr(csnap, name) for name in _COLS}
                expiry = csnap.expiry
                pool = csnap.pool
            fname = ckpt.checkpoint_name(revision)
            ckpt.save_columnar_file(
                os.path.join(self.ckpt_dir, fname), pool, cols,
                np.asarray(expiry, dtype=np.float64), overlay_strings,
                meta={"revision": revision, "watermark": watermark},
                failpoint="checkpointBeforeRename")
            manifest = ckpt.default_manifest(revision, fname, watermark)
            ckpt.write_manifest(self.data_dir, manifest,
                                failpoint="manifestBeforeRename")
            self._last_ckpt_revision = revision
            self._last_ckpt_unix = manifest["created_unix"]
            self._ckpt_total.inc()
            self._reclaim(fname, watermark, revision)
        logger.info("checkpoint at revision %d (watermark seg %d)",
                    revision, watermark)
        return manifest

    def _reclaim(self, current_ckpt: str, watermark: int,
                 revision: int) -> None:
        self.wal.reclaim(watermark, revision)
        for name in os.listdir(self.ckpt_dir):
            if name != current_ckpt and (name.startswith("ckpt-")
                                         or name.endswith(".tmp")):
                try:
                    os.unlink(os.path.join(self.ckpt_dir, name))
                except OSError:
                    pass

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        """Start the periodic checkpoint loop and (for the `interval`
        fsync policy) the idle-flush task that bounds the loss window
        when no further append arrives to trigger the fsync."""
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())
        if (self.wal.fsync_policy == FSYNC_INTERVAL
                and (self._flush_task is None or self._flush_task.done())):
            self._flush_task = asyncio.ensure_future(self._flush_loop())

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.wal.fsync_interval)
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.wal.fsync_if_dirty)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("idle WAL fsync failed")

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.checkpoint_interval)
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.checkpoint)
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("periodic checkpoint failed")

    async def stop(self, final_checkpoint: bool = True) -> None:
        for attr in ("_task", "_flush_task"):
            task = getattr(self, attr)
            setattr(self, attr, None)
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        if final_checkpoint and self._store is not None:
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self.checkpoint)
            except Exception:
                logger.exception("final checkpoint failed")
        self.close()

    def close(self) -> None:
        """Detach + close the WAL (clean shutdown; crash tests simply
        abandon the manager instead)."""
        self.detach()
        self.wal.close()
