"""Dispatch-path fault-injection kill matrix (utils/failpoints.py).

Every injected fault on the dispatch/rebuild pipeline must (1) fail the
waiters it strands FAST — bounded by a timeout, never a hang; (2) keep
the arena pool and HBM ledger invariant; (3) leave the system serving
correct answers afterwards (a crashed background rebuild leaves the old
generation up).  Sites: drain-task death (both before dispatch and
between two-phase start/finish), readback-waiter death, arena-pool
poisoning, and a rebuild-executor crash.
"""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import devtel
from spicedb_kubeapi_proxy_tpu.utils.failpoints import (
    FailPointPanic,
    disable_all,
    enable_failpoint,
)

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""

WAIT_S = 10  # fail-fast bound: every stranded waiter resolves within this


@pytest.fixture(autouse=True)
def _clean_failpoints():
    disable_all()
    yield
    disable_all()


def make(n_docs=8, **batch_kw):
    schema = sch.parse_schema(SCHEMA)
    jx = JaxEndpoint(schema, store=TupleStore())
    jx.store.write([
        RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"doc:d{i}#viewer@user:u{i % 4}")) for i in range(n_docs)])
    oracle = Evaluator(schema, jx.store)
    return BatchingEndpoint(jx, **batch_kw), jx, oracle


def check(user, doc="d0"):
    return CheckRequest(resource=ObjectRef("doc", doc), permission="view",
                        subject=SubjectRef("user", user))


async def fanout(ep, n=6):
    """n concurrent lookups on distinct subjects + n checks; returns the
    per-task results/exceptions (never hangs past WAIT_S)."""
    tasks = [asyncio.create_task(ep.lookup_resources(
        "doc", "view", SubjectRef("user", f"u{i % 4}")))
        for i in range(n)]
    tasks += [asyncio.create_task(ep.check_permission(check(f"u{i % 4}",
                                                           f"d{i % 8}")))
              for i in range(n)]
    done = await asyncio.wait_for(
        asyncio.gather(*tasks, return_exceptions=True), timeout=WAIT_S)
    return done


def assert_serving_correctly(ep, oracle):
    async def run():
        for u in ("u0", "u1", "u2"):
            got = sorted(await ep.lookup_resources(
                "doc", "view", SubjectRef("user", u)))
            want = sorted(oracle.lookup_resources(
                "doc", "view", SubjectRef("user", u)))
            assert got == want, (u, got, want)

    asyncio.run(run())


def arena_ledger_consistent(jx):
    """Ledger invariant: at most one registered arena per (gen, bucket)
    name, and the per-generation total matches what register() summed —
    i.e. no double-count and no stranded negative entries."""
    gen = jx._devtel_gen
    with devtel.LEDGER._lock:
        entries = {k: v for k, v in devtel.LEDGER._buffers.items()
                   if k[0] == gen and k[1] == "state_arena"}
        names = [k[2] for k in entries]
        assert len(names) == len(set(names))
        assert all(v >= 0 for v in entries.values())
    return True


class TestKillMatrix:
    def test_drain_death_fails_every_waiter_fast(self):
        ep, jx, oracle = make()

        async def run():
            enable_failpoint("dispatchDrain", 1)
            results = await fanout(ep)
            # the dying drain failed its waiters promptly — every task
            # resolved (to the panic or a drain-cancel error), none hung
            failures = [r for r in results if isinstance(r, Exception)]
            assert failures, "drain death produced no failures?"
            assert all(isinstance(r, (FailPointPanic, RuntimeError))
                       for r in failures), results

        asyncio.run(run())
        # disarmed: a fresh drain task serves correctly again
        disable_all()
        assert_serving_correctly(ep, oracle)
        assert arena_ledger_consistent(jx)

    def test_drain_death_between_start_and_finish(self):
        # pipeline window >= 1 so started-but-unfinished batches exist
        ep, jx, oracle = make(pipeline_depth=3)

        async def run():
            enable_failpoint("dispatchDrainBeforeFinish", 1)
            results = await fanout(ep, n=8)
            failures = [r for r in results if isinstance(r, Exception)]
            # started batches joined `pending` before the blocking
            # finish, so the drain's death failed them too — fast
            assert failures, "no waiter observed the drain death"

        asyncio.run(run())
        disable_all()
        assert_serving_correctly(ep, oracle)
        assert arena_ledger_consistent(jx)

    def test_readback_waiter_death_discards_arena_and_recovers(self):
        ep, jx, oracle = make(pipeline_depth=2)
        # prime: one pipelined call allocates + pools the arena
        assert_serving_correctly(ep, oracle)

        async def run():
            enable_failpoint("readbackWaiter", 1)
            results = await fanout(ep, n=4)
            # the dispatcher's per-member retry absorbs the failed fused
            # finish: callers still get ANSWERS, not exceptions
            failures = [r for r in results if isinstance(r, Exception)]
            assert not failures, failures

        asyncio.run(run())
        # the poisoned arena was discarded (on_error) — never re-pooled
        # into later calls — and the ledger stayed consistent
        assert arena_ledger_consistent(jx)
        disable_all()
        assert_serving_correctly(ep, oracle)
        assert arena_ledger_consistent(jx)

    def test_arena_take_poisoning_fails_fast_then_recovers(self):
        ep, jx, oracle = make(pipeline_depth=2)
        assert_serving_correctly(ep, oracle)

        async def run():
            # poison several takes: the pipelined dispatch degrades to
            # the serial fused path (no arenas) and still answers
            enable_failpoint("arenaTake", 4)
            results = await fanout(ep, n=4)
            failures = [r for r in results if isinstance(r, Exception)]
            assert not failures, failures

        asyncio.run(run())
        disable_all()
        assert_serving_correctly(ep, oracle)
        assert arena_ledger_consistent(jx)

    def test_rebuild_executor_crash_leaves_old_generation_serving(self):
        ep, jx, oracle = make()
        assert_serving_correctly(ep, oracle)
        gen_before = jx._devtel_gen
        total_before = devtel.LEDGER.generation_bytes(gen_before)
        failures_before = jx.stats["rebuild_failures"]

        enable_failpoint("rebuildExecutor", 1)
        # wildcard write forces a rebuild; the background build crashes
        jx.store.write([RelationshipUpdate(UpdateOp.TOUCH,
                                           parse_relationship(
                                               "doc:dw#viewer@user:*"))])
        # answers stay exact THROUGH the crash: quarantined pairs route
        # to the oracle, everything else rides the old generation
        assert_serving_correctly(ep, oracle)
        for _ in range(200):
            if jx.stats["rebuild_failures"] > failures_before:
                break
            import time
            time.sleep(0.01)
        assert jx.stats["rebuild_failures"] == failures_before + 1
        # old generation untouched in the ledger
        assert jx._devtel_gen == gen_before
        assert devtel.LEDGER.generation_bytes(gen_before) == total_before
        # failpoint consumed: the retry (re-armed by the next query via
        # wait_rebuilds) succeeds and clears the quarantine
        disable_all()
        assert jx.wait_rebuilds()
        assert not jx._stale_pairs
        assert jx._devtel_gen != gen_before
        assert_serving_correctly(ep, oracle)

    def test_matrix_sweep_no_hang_anywhere(self):
        """Belt-and-braces: arm every site in sequence under the same
        traffic shape; the only universal invariant is NO HANG and full
        recovery after disarm."""
        for site in ("dispatchDrain", "dispatchDrainBeforeFinish",
                     "readbackWaiter", "arenaTake", "rebuildExecutor"):
            ep, jx, oracle = make(pipeline_depth=3)
            assert_serving_correctly(ep, oracle)
            enable_failpoint(site, 2)
            asyncio.run(fanout(ep, n=6))  # bounded by WAIT_S internally
            disable_all()
            assert_serving_correctly(ep, oracle)
            assert arena_ledger_consistent(jx)
            assert jx.wait_rebuilds()
