"""Systematic concurrency tier (SURVEY §5 race-detection note; reference
keeps goroutine-safety via xsync.Map/mutexed readers and a dedicated
RESTMapper race test).  Here: mixed concurrent traffic — writers, bulk
checkers, lookups, watch consumers, dispatcher-fused callers — hammering
one endpoint, with invariants checked throughout:

- no deadlock (everything completes under a timeout);
- revisions are monotone non-decreasing per caller;
- a check result is always consistent with SOME store state, never a
  torn mix (the graph lock snapshots revision before evaluating);
- the final store state equals the deterministic replay of all writes;
- watch consumers observe every write exactly once (no loss, no dupes).
"""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition doc {
  relation viewer: user | group#member
  relation banned: user
  permission view = viewer - banned
}
"""

N_DOCS = 24
N_USERS = 12


def seed_rels():
    out = []
    for d in range(N_DOCS):
        out.append(f"doc:d{d}#viewer@user:u{d % N_USERS}")
        out.append(f"doc:d{d}#viewer@group:g{d % 3}#member")
    for u in range(N_USERS):
        out.append(f"group:g{u % 3}#member@user:u{u}")
    return out


@pytest.mark.parametrize("endpoint_url", ["embedded://", "jax://"])
def test_mixed_concurrent_traffic(endpoint_url):
    ep = create_endpoint(endpoint_url, Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])
    batching = BatchingEndpoint(ep)
    writes_done: list = []

    async def writer(i):
        for j in range(10):
            rel = f"doc:d{(i * 7 + j) % N_DOCS}#viewer@user:w{i}"
            await ep.write_relationships([RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship(rel))])
            writes_done.append(rel)
            await asyncio.sleep(0)

    async def checker(i):
        last_rev = -1
        for j in range(15):
            res = await ep.check_bulk_permissions([
                CheckRequest(ObjectRef("doc", f"d{(i + k) % N_DOCS}"),
                             "view", SubjectRef("user", f"u{k % N_USERS}"))
                for k in range(8)])
            revs = {r.checked_at for r in res}
            assert len(revs) == 1, "torn bulk check across revisions"
            rev = revs.pop()
            assert rev >= last_rev, "revision went backwards"
            last_rev = rev
            await asyncio.sleep(0)

    async def fused_looker(i):
        for j in range(10):
            ids = await batching.lookup_resources(
                "doc", "view", SubjectRef("user", f"u{(i + j) % N_USERS}"))
            assert isinstance(ids, list)
            await asyncio.sleep(0)

    async def go():
        watcher = ep.watch(["doc"])
        seen: list = []

        async def consume():
            while True:
                upd = await watcher.next(timeout=2.0)
                if upd is None:
                    # next() returns None on timeout AND close — only a
                    # real close ends the stream (a slow box / cold JIT
                    # can stall >2s mid-run without losing events)
                    if watcher.closed:
                        return
                    continue
                for u in upd.updates:
                    seen.append(u.rel.rel_string())

        consumer = asyncio.ensure_future(consume())
        tasks = ([writer(i) for i in range(4)]
                 + [checker(i) for i in range(4)]
                 + [fused_looker(i) for i in range(4)])
        await asyncio.wait_for(asyncio.gather(*tasks), 60)
        # drain the watch tail, then close
        await asyncio.sleep(0.3)
        watcher.close()
        await asyncio.wait_for(consumer, 10)

        # every write observed exactly once (TOUCH of distinct rels)
        assert sorted(seen) == sorted(writes_done)

        # final checks agree with the deterministic end state
        for rel in writes_done:
            user = rel.split("@user:")[1]
            doc = rel.split("#")[0].split(":")[1]
            res = await ep.check_permission(CheckRequest(
                ObjectRef("doc", doc), "view", SubjectRef("user", user)))
            assert res.allowed, (doc, user)

    asyncio.run(go())


@pytest.mark.parametrize("endpoint_url", ["embedded://", "jax://"])
def test_checked_at_tracks_evaluated_snapshot(endpoint_url):
    """checked_at must name the revision the evaluated graph reflects —
    after a write drains, checks carry that write's revision."""
    ep = create_endpoint(endpoint_url, Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    async def go():
        req = CheckRequest(ObjectRef("doc", "d0"), "view",
                           SubjectRef("user", "u0"))
        res = await ep.check_permission(req)
        assert res.checked_at == ep.store.revision
        await ep.write_relationships([RelationshipUpdate(
            UpdateOp.TOUCH,
            parse_relationship("doc:d0#viewer@user:fresh"))])
        r1 = ep.store.revision
        res = await ep.check_permission(CheckRequest(
            ObjectRef("doc", "d0"), "view", SubjectRef("user", "fresh")))
        assert res.allowed
        assert res.checked_at == r1
    asyncio.run(go())


def test_device_batches_do_not_block_event_loop(monkeypatch):
    """A fused device batch (kernel + transfer + unpack) can take hundreds
    of ms on big graphs; it must run OFF the event loop so concurrent
    requests, watch frames, and health probes keep flowing.

    The stall bound is CALIBRATED, not a wall-clock constant: the old
    fixed 0.3s tripped marginally (0.35-0.46s) in ~half of full-suite
    runs purely from gc/scheduler pauses unrelated to the device batch
    (PR 5 known flake).  An ambient phase measures this box's tick
    jitter with NO batch in flight and the bound scales from it —
    floored at 0.35s (in-suite gc bursts were measured at 0.35-0.46s
    with a quiet calibration phase, so a quiet ambient must not lower
    the bound into that noise band) and capped at 0.48s (still below
    the 0.5s device window, so a genuinely blocked loop can never pass).
    A bad-luck gc burst gets two retries before the test fails; a
    blocked loop (the 0.5s sleep landing ON the loop) fails every
    attempt deterministically."""
    import time as _time

    ep = create_endpoint("jax://", Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    def slow_batch(reqs):
        _time.sleep(0.5)  # stand-in for a long kernel+transfer window
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            CheckResult,
            Permissionship,
        )
        return [CheckResult(permissionship=Permissionship.NO_PERMISSION,
                            checked_at=0) for _ in reqs]

    monkeypatch.setattr(ep, "_check_batch_sync", slow_batch)

    def max_gap(ticks):
        return max((b - a for a, b in zip(ticks, ticks[1:])), default=1.0)

    async def go():
        async def ticker(out):
            while True:
                out.append(asyncio.get_running_loop().time())
                await asyncio.sleep(0.02)

        # phase 1: ambient tick jitter, no device batch in flight —
        # whatever stalls show here (gc, a loaded CI box) are the
        # environment's fault, not the off-loop dispatch's
        ambient_ticks: list = []
        t = asyncio.ensure_future(ticker(ambient_ticks))
        await asyncio.sleep(0.3)
        t.cancel()
        ambient = max_gap(ambient_ticks) if len(ambient_ticks) > 1 else 0.02

        # phase 2: the same ticker through the 0.5s device window
        ticks: list = []
        t = asyncio.ensure_future(ticker(ticks))
        await ep.check_bulk_permissions([CheckRequest(
            ObjectRef("doc", "d0"), "view", SubjectRef("user", "u0"))])
        t.cancel()
        assert len(ticks) >= 10, (
            f"event loop starved: only {len(ticks)} ticks during the batch")
        # a blocked loop gaps ~0.5s regardless of calibration; ambient
        # noise scales the bound instead of tripping it — but the bound
        # is CAPPED below the 0.5s device window, so a gc burst landing
        # in the calibration phase can never inflate it past the very
        # signal this test exists to detect
        return max_gap(ticks), min(max(0.35, 4 * ambient), 0.48)

    stall, bound = asyncio.run(go())
    for _retry in range(2):
        if stall < bound:
            break
        # retries: a gen-2 gc burst inside the measured window is
        # indistinguishable from a stall in one sample but cannot recur
        # across attempts; a genuinely blocked loop fails all three
        stall, bound = asyncio.run(go())
    assert stall < bound, (
        f"loop stalled {stall:.3f}s (calibrated bound {bound:.3f}s)")


@pytest.mark.parametrize("endpoint_url", ["jax://"])
def test_concurrent_writes_during_rebuild(endpoint_url):
    """Writes racing graph rebuilds (bulk_load invalidation) must never
    deadlock or lose updates."""
    ep = create_endpoint(endpoint_url, Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    async def rebuilder():
        for _ in range(3):
            ep.store.bulk_load(
                [parse_relationship(r) for r in seed_rels()])
            await asyncio.sleep(0.01)

    async def writer_checker():
        for j in range(12):
            rel = f"doc:d{j % N_DOCS}#viewer@user:rw"
            await ep.write_relationships([RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship(rel))])
            res = await ep.check_permission(CheckRequest(
                ObjectRef("doc", f"d{j % N_DOCS}"), "view",
                SubjectRef("user", "rw")))
            assert res.allowed  # read-your-writes through rebuilds
            await asyncio.sleep(0)

    async def go():
        await asyncio.wait_for(
            asyncio.gather(rebuilder(), writer_checker(), writer_checker()),
            60)

    asyncio.run(go())


@pytest.mark.parametrize("endpoint_url", ["jax://", "jax://?mesh=2x4"])
def test_lookups_race_spare_assigning_writes(endpoint_url):
    """Round-4 regression net: lookups (kernel + id materialization run
    OUTSIDE the endpoint lock on a snapshot) race writes that create
    brand-new object ids (in-place renames of the program's id maps via
    the spare pool).  Invariants: no placeholder id (NUL-prefixed) ever
    leaks into results; every id returned was a doc id the store has
    seen; once a create's write returns, subsequent lookups must include
    it (read-your-writes through the drain)."""
    if "mesh" in endpoint_url:
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
    ep = create_endpoint(endpoint_url + ("&" if "?" in endpoint_url
                                         else "?") + "dispatch=direct",
                         Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    async def go():
        errors = []
        created = []  # ids whose write has returned
        stop = asyncio.Event()

        async def writer():
            for k in range(60):
                rel = f"doc:new-{k}#viewer@user:u0"
                await ep.write_relationships([RelationshipUpdate(
                    UpdateOp.TOUCH, parse_relationship(rel))])
                created.append(f"new-{k}")
                await asyncio.sleep(0)
            stop.set()

        def diag():
            inner_ep = getattr(ep, "inner", ep)
            try:  # best-effort: races rebuilds repopulating these dicts
                st = dict(getattr(inner_ep, "stats", {}))
                pool = {t: len(v) for t, v in
                        list(getattr(inner_ep, "_spare_pool", {}).items())}
            except RuntimeError:
                st, pool = "racing-rebuild", {}
            return f"stats={st} pool={pool} created={len(created)}"

        async def reader():
            while not stop.is_set():
                mark = len(created)
                ids = await ep.lookup_resources(
                    "doc", "view", SubjectRef("user", "u0"))
                got = set(ids)
                if any("\x00" in i for i in got):
                    bad = [i for i in got if chr(0) in i]
                    inner_ep = getattr(ep, "inner", ep)
                    with inner_ep._lock:
                        # leak family: placeholder still unassigned in the
                        # CURRENT index => the kernel lit a dead row;
                        # renamed away => a stale id view was used
                        try:
                            fam = {n: inner_ep._graph.prog
                                   .object_index["doc"]
                                   .get(n, "renamed-away")
                                   for n in bad[:6]}
                        except AttributeError:  # mid-rebuild window
                            fam = "graph-rebuilding"
                    errors.append(
                        f"placeholder leak: {bad[:6]} families={fam} "
                        f"[{diag()}]")
                    return
                # read-your-writes: ids created before the call started
                missing = [c for c in created[:mark] if c not in got]
                if missing:
                    errors.append(f"missing created ids: {missing} "
                                  f"(got {len(got)}) [{diag()}]")
                    return
                await asyncio.sleep(0)

        await asyncio.wait_for(
            asyncio.gather(writer(), *[reader() for _ in range(4)]), 120)
        assert not errors, errors[:3]
        final = set(await ep.lookup_resources(
            "doc", "view", SubjectRef("user", "u0")))
        assert all(f"new-{k}" in final for k in range(60)), \
            f"final lookup incomplete [{diag()}]"
        # suppression events are HANDLED (the endpoint re-captures and
        # returns the correct result; see _lookup_sync) — strict result
        # invariants above are the real tripwire, the counter is the
        # observability signal for how often the race fires
        inner_ep = getattr(ep, "inner", ep)
        suppressed = inner_ep.stats.get("placeholder_suppressed", 0)
        if suppressed:
            print(f"\nNOTE: id-view race fired and was self-healed "
                  f"(suppressed={suppressed}) [{diag()}]", flush=True)

    asyncio.run(go())
