"""Minimal protobuf wire-format codecs for the authzed.api.v1 subset.

The remote endpoint (`grpc://`, reference options.go:331-368) and the
standalone authz gRPC server speak the seven verbs the proxy consumes
(SURVEY.md §5). gRPC only needs `request_serializer` /
`response_deserializer` callables, so rather than depending on generated
stubs (no authzed package and no egress in this environment), the handful
of messages are encoded/decoded directly in the protobuf wire format:
varint tags, length-delimited submessages.

Field numbers follow the public authzed.api.v1 protos.  Wire compatibility
is pinned by golden fixtures (tests/test_wire_golden.py): literal
hand-assembled byte strings plus cross-validation against the real
protobuf runtime via dynamic descriptors mirroring authzed.api.v1 —
byte-identical encoding for the request messages, parse-identical both
directions for the rest:

  ObjectReference        { object_type=1, object_id=2 }
  SubjectReference       { object=1, optional_relation=2 }
  Relationship           { resource=1, relation=2, subject=3,
                           optional_caveat=4 (ContextualizedCaveat{
                             caveat_name=1, context=2 (Struct) }),
                           optional_expires_at=5 (Timestamp) }
  ZedToken               { token=1 }
  Consistency            { fully_consistent=4 }   (always sent)
  RelationshipFilter     { resource_type=1, optional_resource_id=2,
                           optional_relation=3, optional_subject_filter=4 }
  SubjectFilter          { subject_type=1, optional_subject_id=2,
                           optional_relation=3 { relation=1 } }
  Precondition           { operation=1, filter=2 }
  RelationshipUpdate     { operation=1, relationship=2 }
  CheckPermissionRequest { consistency=1, resource=2, permission=3, subject=4 }
  CheckPermissionResponse{ checked_at=1, permissionship=2 }
  CheckBulkPermissionsRequest  { consistency=1, items=2 }
  CheckBulkPermissionsRequestItem { resource=1, permission=2, subject=3 }
  CheckBulkPermissionsResponse { checked_at=1, pairs=2 }
  CheckBulkPermissionsPair     { request=1, item=2 { permissionship=1 } }
  LookupResourcesRequest { consistency=1, resource_object_type=2,
                           permission=3, subject=4 }
  LookupResourcesResponse{ looked_up_at=1, resource_object_id=2,
                           permissionship=3 }
  ReadRelationshipsRequest { consistency=1, relationship_filter=2 }
  ReadRelationshipsResponse{ read_at=1, relationship=2 }
  WriteRelationshipsRequest{ updates=1, optional_preconditions=2 }
  WriteRelationshipsResponse{ written_at=1 }
  DeleteRelationshipsRequest{ relationship_filter=1, optional_preconditions=2 }
  DeleteRelationshipsResponse{ deleted_at=1 }
  WatchRequest           { optional_object_types=1 }
  WatchResponse          { updates=1, changes_through=2 }

Permissionship enum: 1=NO_PERMISSION, 2=HAS_PERMISSION, 3=CONDITIONAL.
RelationshipUpdate.Operation: 1=CREATE, 2=TOUCH, 3=DELETE.
Precondition.Operation: 1=MUST_NOT_MATCH, 2=MUST_MATCH.
"""

from __future__ import annotations

import math
import struct
from typing import Iterator, Optional

from .types import (
    CheckRequest,
    CheckResult,
    ObjectRef,
    Permissionship,
    Precondition,
    PreconditionOp,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectFilter,
    SubjectRef,
    UpdateOp,
)

# -- wire primitives ---------------------------------------------------------


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def _len_field(field: int, payload: bytes) -> bytes:
    if not payload:
        return b""
    return _tag(field, 2) + _varint(len(payload)) + payload


def _len_field_present(field: int, payload: bytes) -> bytes:
    """Like _len_field but emits the field even when the payload is empty
    (submessage presence, e.g. an empty RelationFilter meaning
    'direct subjects only')."""
    return _tag(field, 2) + _varint(len(payload)) + payload


def _str_field(field: int, value: str) -> bytes:
    return _len_field(field, value.encode("utf-8"))


def _varint_field(field: int, value: int) -> bytes:
    if not value:
        return b""
    return _tag(field, 0) + _varint(value)


def _read_varint(buf: bytes, pos: int) -> tuple:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def fields(buf: bytes) -> Iterator[tuple]:
    """Yield (field_number, wire_type, value) over a message buffer.
    Length-delimited values come back as bytes; varints as int."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            value, pos = _read_varint(buf, pos)
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            value = buf[pos: pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            value = buf[pos: pos + 4]
            pos += 4
        elif wt == 1:  # fixed64
            value = buf[pos: pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, value


def _submessages(buf: bytes, field: int) -> list:
    return [v for f, wt, v in fields(buf) if f == field and wt == 2]


def _first(buf: bytes, field: int, default=None):
    for f, wt, v in fields(buf):
        if f == field:
            return v
    return default


def _first_str(buf: bytes, field: int, default: str = "") -> str:
    v = _first(buf, field)
    return v.decode("utf-8") if isinstance(v, bytes) else default


# -- core types --------------------------------------------------------------


def enc_object(ref: ObjectRef) -> bytes:
    return _str_field(1, ref.type) + _str_field(2, ref.id)


def dec_object(buf: bytes) -> ObjectRef:
    return ObjectRef(_first_str(buf, 1), _first_str(buf, 2))


def enc_subject(ref: SubjectRef) -> bytes:
    return (_len_field(1, enc_object(ObjectRef(ref.type, ref.id)))
            + _str_field(2, ref.relation))


def dec_subject(buf: bytes) -> SubjectRef:
    obj = dec_object(_first(buf, 1, b""))
    return SubjectRef(obj.type, obj.id, _first_str(buf, 2))


def _enc_timestamp(unix_seconds: float) -> bytes:
    seconds = int(math.floor(unix_seconds))
    nanos = int(round((unix_seconds - seconds) * 1e9))
    return _varint_field(1, seconds) + _varint_field(2, nanos)


def _dec_timestamp(buf: bytes) -> float:
    seconds = _first(buf, 1, 0)
    nanos = _first(buf, 2, 0)
    return float(seconds) + float(nanos) / 1e9


# -- google.protobuf.Struct (caveat context) ---------------------------------
# Value oneof: null_value=1 (varint), number_value=2 (double/fixed64),
# string_value=3, bool_value=4 (varint), struct_value=5, list_value=6.
# Oneof fields must be emitted even for zero values, so the generic
# zero-dropping helpers are bypassed here.

def _enc_value(v) -> bytes:
    if v is None:
        return _tag(1, 0) + _varint(0)
    if isinstance(v, bool):
        return _tag(4, 0) + _varint(1 if v else 0)
    if isinstance(v, (int, float)):
        return _tag(2, 1) + struct.pack("<d", float(v))
    if isinstance(v, str):
        return _len_field_present(3, v.encode("utf-8"))
    if isinstance(v, dict):
        return _len_field_present(5, _enc_struct(v))
    if isinstance(v, (list, tuple)):
        payload = b"".join(_len_field_present(1, _enc_value(x)) for x in v)
        return _len_field_present(6, payload)
    raise ValueError(f"unsupported caveat context value {type(v).__name__}")


def _dec_value(buf: bytes):
    for f, wt, v in fields(buf):
        if f == 1:
            return None
        if f == 2:
            num = struct.unpack("<d", v)[0]
            # integral doubles come back as ints so JSON contexts
            # round-trip exactly ({"n": 1} -> 1, not 1.0)
            return int(num) if num.is_integer() else num
        if f == 3:
            return v.decode("utf-8")
        if f == 4:
            return bool(v)
        if f == 5:
            return _dec_struct(v)
        if f == 6:
            return [_dec_value(x) for x in _submessages(v, 1)]
    return None


def _enc_struct(d: dict) -> bytes:
    # Struct{ map<string, Value> fields = 1 }; map entries are
    # { key=1, value=2 } submessages
    out = b""
    for k, v in d.items():
        entry = _str_field(1, k) + _len_field_present(2, _enc_value(v))
        out += _len_field_present(1, entry)
    return out


def _dec_struct(buf: bytes) -> dict:
    out = {}
    for entry in _submessages(buf, 1):
        key = _first_str(entry, 1)
        val = _first(entry, 2, b"")
        out[key] = _dec_value(val)
    return out


def _enc_caveat(caveat) -> bytes:
    """ContextualizedCaveat{ caveat_name=1, context=2 (Struct) }."""
    out = _str_field(1, caveat.name)
    ctx = caveat.context()
    if ctx:
        out += _len_field_present(2, _enc_struct(ctx))
    return out


def _dec_caveat(buf: bytes):
    from .types import CaveatRef
    ctx_buf = _first(buf, 2)
    return CaveatRef.make(
        _first_str(buf, 1),
        _dec_struct(ctx_buf) if ctx_buf is not None else None)


def enc_relationship(rel: Relationship) -> bytes:
    out = (_len_field(1, enc_object(rel.resource))
           + _str_field(2, rel.relation)
           + _len_field(3, enc_subject(rel.subject)))
    if rel.caveat is not None:
        out += _len_field_present(4, _enc_caveat(rel.caveat))
    if rel.expires_at is not None:
        out += _len_field(5, _enc_timestamp(rel.expires_at))
    return out


def dec_relationship(buf: bytes) -> Relationship:
    cav = _first(buf, 4)
    ts = _first(buf, 5)
    return Relationship(
        resource=dec_object(_first(buf, 1, b"")),
        relation=_first_str(buf, 2),
        subject=dec_subject(_first(buf, 3, b"")),
        caveat=_dec_caveat(cav) if cav is not None else None,
        expires_at=_dec_timestamp(ts) if ts is not None else None,
    )


def enc_zedtoken(revision: int) -> bytes:
    return _str_field(1, str(revision))


def dec_zedtoken(buf: Optional[bytes]) -> int:
    if not buf:
        return 0
    try:
        return int(_first_str(buf, 1) or 0)
    except ValueError:
        return 0


def enc_consistency_full() -> bytes:
    return _varint_field(4, 1)  # fully_consistent = true


def enc_rel_filter(flt: RelationshipFilter) -> bytes:
    out = (_str_field(1, flt.resource_type)
           + _str_field(2, flt.resource_id)
           + _str_field(3, flt.relation))
    if flt.subject is not None:
        sub = (_str_field(1, flt.subject.type)
               + _str_field(2, flt.subject.id))
        if flt.subject.relation is not None:
            sub += _len_field_present(3, _str_field(1, flt.subject.relation))
        out += _len_field_present(4, sub)
    return out


def dec_rel_filter(buf: bytes) -> RelationshipFilter:
    sub = _first(buf, 4)
    subject = None
    if sub is not None:
        rel_wrap = _first(sub, 3)
        subject = SubjectFilter(
            type=_first_str(sub, 1),
            id=_first_str(sub, 2),
            relation=(_first_str(rel_wrap, 1) if rel_wrap is not None else None),
        )
    return RelationshipFilter(
        resource_type=_first_str(buf, 1),
        resource_id=_first_str(buf, 2),
        relation=_first_str(buf, 3),
        subject=subject,
    )


_PRECOND_OP = {PreconditionOp.MUST_NOT_MATCH: 1, PreconditionOp.MUST_MATCH: 2}
_PRECOND_OP_R = {v: k for k, v in _PRECOND_OP.items()}


def enc_precondition(p: Precondition) -> bytes:
    return (_varint_field(1, _PRECOND_OP[p.op])
            + _len_field(2, enc_rel_filter(p.filter)))


def dec_precondition(buf: bytes) -> Precondition:
    return Precondition(
        op=_PRECOND_OP_R.get(_first(buf, 1, 2), PreconditionOp.MUST_MATCH),
        filter=dec_rel_filter(_first(buf, 2, b"")),
    )


_UPDATE_OP = {UpdateOp.CREATE: 1, UpdateOp.TOUCH: 2, UpdateOp.DELETE: 3}
_UPDATE_OP_R = {v: k for k, v in _UPDATE_OP.items()}


def enc_update(u: RelationshipUpdate) -> bytes:
    return (_varint_field(1, _UPDATE_OP[u.op])
            + _len_field(2, enc_relationship(u.rel)))


def dec_update(buf: bytes) -> RelationshipUpdate:
    return RelationshipUpdate(
        op=_UPDATE_OP_R.get(_first(buf, 1, 2), UpdateOp.TOUCH),
        rel=dec_relationship(_first(buf, 2, b"")),
    )


_PERMISSIONSHIP = {
    Permissionship.NO_PERMISSION: 1,
    Permissionship.HAS_PERMISSION: 2,
    Permissionship.CONDITIONAL_PERMISSION: 3,
}
_PERMISSIONSHIP_R = {v: k for k, v in _PERMISSIONSHIP.items()}


# -- requests/responses ------------------------------------------------------


def enc_check_request(req: CheckRequest) -> bytes:
    return (_len_field(1, enc_consistency_full())
            + _len_field(2, enc_object(req.resource))
            + _str_field(3, req.permission)
            + _len_field(4, enc_subject(req.subject)))


def dec_check_request(buf: bytes) -> CheckRequest:
    return CheckRequest(
        resource=dec_object(_first(buf, 2, b"")),
        permission=_first_str(buf, 3),
        subject=dec_subject(_first(buf, 4, b"")),
    )


def enc_check_response(res: CheckResult) -> bytes:
    return (_len_field(1, enc_zedtoken(res.checked_at))
            + _varint_field(2, _PERMISSIONSHIP[res.permissionship]))


def dec_check_response(buf: bytes) -> CheckResult:
    return CheckResult(
        permissionship=_PERMISSIONSHIP_R.get(
            _first(buf, 2, 1), Permissionship.NO_PERMISSION),
        checked_at=dec_zedtoken(_first(buf, 1)),
    )


def enc_bulk_request(reqs: list) -> bytes:
    out = _len_field(1, enc_consistency_full())
    for r in reqs:
        item = (_len_field(1, enc_object(r.resource))
                + _str_field(2, r.permission)
                + _len_field(3, enc_subject(r.subject)))
        out += _len_field(2, item)
    return out


def dec_bulk_request(buf: bytes) -> list:
    out = []
    for item in _submessages(buf, 2):
        out.append(CheckRequest(
            resource=dec_object(_first(item, 1, b"")),
            permission=_first_str(item, 2),
            subject=dec_subject(_first(item, 3, b"")),
        ))
    return out


def enc_bulk_response(revision: int, results: list) -> bytes:
    out = _len_field(1, enc_zedtoken(revision))
    for res in results:
        item = _varint_field(1, _PERMISSIONSHIP[res.permissionship])
        out += _len_field(2, _len_field(2, item))
    return out


def dec_bulk_response(buf: bytes) -> list:
    rev = dec_zedtoken(_first(buf, 1))
    out = []
    for pair in _submessages(buf, 2):
        item = _first(pair, 2, b"")
        out.append(CheckResult(
            permissionship=_PERMISSIONSHIP_R.get(
                _first(item, 1, 1), Permissionship.NO_PERMISSION),
            checked_at=rev,
        ))
    return out


def enc_lookup_request(resource_type: str, permission: str,
                       subject: SubjectRef) -> bytes:
    return (_len_field(1, enc_consistency_full())
            + _str_field(2, resource_type)
            + _str_field(3, permission)
            + _len_field(4, enc_subject(subject)))


def dec_lookup_request(buf: bytes) -> tuple:
    return (_first_str(buf, 2), _first_str(buf, 3),
            dec_subject(_first(buf, 4, b"")))


def enc_lookup_response(revision: int, resource_id: str) -> bytes:
    return (_len_field(1, enc_zedtoken(revision))
            + _str_field(2, resource_id)
            + _varint_field(3, 2))  # HAS_PERMISSION (conditional are skipped)


def dec_lookup_response(buf: bytes) -> tuple:
    """(resource_id, permissionship).  FAIL CLOSED like the check
    decoders: an absent permissionship field (proto3 zero = UNSPECIFIED)
    or an unknown enum value decodes as NO_PERMISSION, so it can never
    slip past the client's HAS-only filter into an allowed-set."""
    return (_first_str(buf, 2),
            _PERMISSIONSHIP_R.get(_first(buf, 3, 0),
                                  Permissionship.NO_PERMISSION))


def enc_read_request(flt: Optional[RelationshipFilter]) -> bytes:
    out = _len_field(1, enc_consistency_full())
    if flt is not None:
        out += _len_field_present(2, enc_rel_filter(flt))
    return out


def dec_read_request(buf: bytes) -> Optional[RelationshipFilter]:
    flt = _first(buf, 2)
    return dec_rel_filter(flt) if flt is not None else None


def enc_read_response(revision: int, rel: Relationship) -> bytes:
    return (_len_field(1, enc_zedtoken(revision))
            + _len_field(2, enc_relationship(rel)))


def dec_read_response(buf: bytes) -> Relationship:
    return dec_relationship(_first(buf, 2, b""))


def enc_write_request(updates: list, preconditions: list) -> bytes:
    out = b""
    for u in updates:
        out += _len_field(1, enc_update(u))
    for p in preconditions:
        out += _len_field(2, enc_precondition(p))
    return out


def dec_write_request(buf: bytes) -> tuple:
    return ([dec_update(u) for u in _submessages(buf, 1)],
            [dec_precondition(p) for p in _submessages(buf, 2)])


def enc_write_response(revision: int) -> bytes:
    return _len_field(1, enc_zedtoken(revision))


def dec_write_response(buf: bytes) -> int:
    return dec_zedtoken(_first(buf, 1))


def enc_delete_request(flt: RelationshipFilter, preconditions: list) -> bytes:
    out = _len_field_present(1, enc_rel_filter(flt))
    for p in preconditions:
        out += _len_field(2, enc_precondition(p))
    return out


def dec_delete_request(buf: bytes) -> tuple:
    return (dec_rel_filter(_first(buf, 1, b"")),
            [dec_precondition(p) for p in _submessages(buf, 2)])


def enc_delete_response(revision: int) -> bytes:
    return _len_field(1, enc_zedtoken(revision))


def dec_delete_response(buf: bytes) -> int:
    return dec_zedtoken(_first(buf, 1))


def enc_watch_request(object_types: Optional[list]) -> bytes:
    out = b""
    for t in object_types or ():
        out += _str_field(1, t)
    return out


def dec_watch_request(buf: bytes) -> Optional[list]:
    types = [v.decode("utf-8") for f, wt, v in fields(buf)
             if f == 1 and wt == 2]
    return types or None


def enc_watch_response(revision: int, updates: list) -> bytes:
    out = b""
    for u in updates:
        out += _len_field(1, enc_update(u))
    out += _len_field(2, enc_zedtoken(revision))
    return out


def dec_watch_response(buf: bytes) -> tuple:
    """(revision, [RelationshipUpdate])"""
    return (dec_zedtoken(_first(buf, 2)),
            [dec_update(u) for u in _submessages(buf, 1)])
