"""The first-class bench scenario workloads (ROADMAP item 5, extended
by the Leopard group-explosion shape) and their fuzzer bias profiles.

Each scenario ships twice:

- as a bench config (`bench.py --config caveat-heavy | wildcard-public |
  ephemeral-grants | group-explosion`, riding `--all`) with a
  HOST-ORACLE PARITY REFEREE:
  every churn round re-derives a reference frontier with the recursive
  evaluator over the same store and counts divergences (acceptance: 0);
- as a (SchemaBias, DeltaBias) pair that steers the random fuzzer's
  generators toward the scenario's shape, so the budgeted search
  (scripts/fuzz_smoke.py --budget-seconds --scenario X) keeps hammering
  the same seam with schemas nobody hand-wrote.

The workloads:

- **caveat-heavy**   CEL-caveated tuples at scale: decided-true /
  decided-false / undecidable contexts on membership + assignment +
  ban relations.  The bench records WHICH side decided the caveats
  (`caveat_path`): `device-bitplane` when the tri-state planes carried
  the load, `host-postfilter` when residual oracle routing did.
- **wildcard-public**  wildcard-heavy public resources (`user:*`): a
  fraction of docs world-readable, churn FLIPS wildcards on and off —
  the graph-rebuild path the kernels cannot absorb in place.
- **ephemeral-grants** PAuth-style task-scoped grants: short-TTL
  expiring tuples at high churn against the store's fake clock —
  stressing the PR 3 expiry heap + decision-cache invalidation, PR 8
  rebuild absorption, and (via the fuzzer's follower roles) PR 9/11
  replica expiry reseeding all at once.
- **group-explosion / nested-groups**  deep recursive group nesting at
  scale: 100k groups chained depth 8+ under pure union/userset/arrow
  rewrites — the exact shape the Leopard materialized closure index
  (ops/leopard.py) flattens to one AND+popcount.  The bench config is
  named `group-explosion`; the fuzzer bias profile steering the random
  generators toward the same shape (membership-only subgraphs, deep
  userset chains, near-zero caveats/wildcards) is `nested-groups`.
"""

from __future__ import annotations

import random

from ..models.workloads import Workload
from .delta_gen import DeltaBias
from .schema_gen import SchemaBias

CAVEAT_HEAVY_SCHEMA = """
caveat within_quota(used int, quota int) { used < quota }
caveat min_level(level int) { level > 2 }
definition user {}
definition group {
  relation member: user | group#member | user with within_quota
}
definition doc {
  relation assigned: user | group#member | user with within_quota
  relation approved: group#member | user with min_level
  relation banned: user | user with min_level
  permission view = assigned & approved - banned
}
"""

WILDCARD_PUBLIC_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition doc {
  relation public: user:*
  relation viewer: user | group#member
  relation banned: user
  permission view = (viewer + public) - banned
}
"""

EPHEMERAL_GRANTS_SCHEMA = """
definition user {}
definition task {
  relation runner: user
}
definition doc {
  relation owner: user
  relation grant: user with expiration | task
  permission view = owner + grant + grant->runner
}
"""


GROUP_EXPLOSION_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition doc {
  relation viewer: group#member | user
  permission view = viewer
}
"""


def _ctx(rng: random.Random):
    roll = rng.random()
    if roll < 0.3:
        return '[caveat:within_quota:{"used": 1, "quota": 5}]'   # true
    if roll < 0.5:
        return '[caveat:within_quota:{"used": 9, "quota": 5}]'   # false
    return '[caveat:within_quota:{"used": 1}]'                   # undecidable


def caveat_heavy(n_docs: int = 3000, n_users: int = 400, n_groups: int = 40,
                 caveat_fraction: float = 0.5, seed: int = 12) -> Workload:
    rng = random.Random(seed)
    rels = set()
    for u in range(n_users):
        cav = _ctx(rng) if rng.random() < caveat_fraction else ""
        rels.add(f"group:g{u % n_groups}#member@user:u{u}{cav}")
    for d in range(n_docs):
        g = rng.randrange(n_groups)
        if rng.random() < caveat_fraction:
            rels.add(f"doc:d{d}#assigned@user:u{rng.randrange(n_users)}"
                     f"{_ctx(rng)}")
        else:
            rels.add(f"doc:d{d}#assigned@group:g{g}#member")
        if rng.random() < 0.3:
            lvl = rng.randrange(6)
            rels.add(f"doc:d{d}#approved@user:u{rng.randrange(n_users)}"
                     f'[caveat:min_level:{{"level": {lvl}}}]')
        rels.add(f"doc:d{d}#approved@group:g{g}#member")
        if rng.random() < 0.2:
            rels.add(f"doc:d{d}#banned@user:u{rng.randrange(n_users)}")
    return Workload(name="caveat-heavy", schema_text=CAVEAT_HEAVY_SCHEMA,
                    relationships=sorted(rels),
                    subjects=[f"u{i}" for i in range(n_users)],
                    resource_type="doc", permission="view",
                    expected_objects=n_docs)


def wildcard_public(n_docs: int = 4000, n_users: int = 400,
                    n_groups: int = 40, public_fraction: float = 0.25,
                    seed: int = 13) -> Workload:
    rng = random.Random(seed)
    rels = set()
    for u in range(n_users):
        rels.add(f"group:g{u % n_groups}#member@user:u{u}")
    for d in range(n_docs):
        if rng.random() < public_fraction:
            rels.add(f"doc:d{d}#public@user:*")
        rels.add(f"doc:d{d}#viewer@group:g{rng.randrange(n_groups)}#member")
        if rng.random() < 0.15:
            rels.add(f"doc:d{d}#banned@user:u{rng.randrange(n_users)}")
    return Workload(name="wildcard-public", schema_text=WILDCARD_PUBLIC_SCHEMA,
                    relationships=sorted(rels),
                    subjects=[f"u{i}" for i in range(n_users)],
                    resource_type="doc", permission="view",
                    expected_objects=n_docs)


def ephemeral_grants(n_docs: int = 3000, n_users: int = 300,
                     n_tasks: int = 60, grant_fraction: float = 0.5,
                     now: float = 0.0, ttl_s: float = 30.0,
                     seed: int = 14) -> Workload:
    """Short-TTL grants are stamped relative to `now` (the bench passes
    its fake clock's origin); half the granted docs also carry durable
    owner/task routes so expiry changes answers, not just sizes."""
    rng = random.Random(seed)
    rels = set()
    for t in range(n_tasks):
        rels.add(f"task:t{t}#runner@user:u{rng.randrange(n_users)}")
    for d in range(n_docs):
        rels.add(f"doc:d{d}#owner@user:u{rng.randrange(n_users)}")
        if rng.random() < grant_fraction:
            u = rng.randrange(n_users)
            exp = now + ttl_s * (0.2 + 0.8 * rng.random())
            rels.add(f"doc:d{d}#grant@user:u{u}[expiration:{exp}]")
        if rng.random() < 0.2:
            rels.add(f"doc:d{d}#grant@task:t{rng.randrange(n_tasks)}")
    return Workload(name="ephemeral-grants",
                    schema_text=EPHEMERAL_GRANTS_SCHEMA,
                    relationships=sorted(rels),
                    subjects=[f"u{i}" for i in range(n_users)],
                    resource_type="doc", permission="view",
                    expected_objects=n_docs)


def group_explosion(n_groups: int = 100_000, n_users: int = 2_000,
                    n_docs: int = 5_000, depth: int = 8,
                    seed: int = 15) -> Workload:
    """Leopard's headline shape: `n_groups` groups arranged in disjoint
    membership chains of length `depth` (every user membership enters at
    the chain TAIL, so reaching a chain-head group — and any doc shared
    with it — costs `depth` userset hops), docs shared with chain
    heads.  Pure union/userset rewrites: every pair is
    Leopard-eligible, so the index collapses the depth-8 walk to one
    closure-plane probe."""
    rng = random.Random(seed)
    n_chains = max(1, n_groups // depth)
    rels = set()
    for c in range(n_chains):
        base = c * depth
        for i in range(depth - 1):
            # members of g{base+i+1} are members of g{base+i}: the
            # chain HEAD (g{base}) is `depth` hops from the user
            rels.add(f"group:g{base + i}#member"
                     f"@group:g{base + i + 1}#member")
        rels.add(f"group:g{base + depth - 1}#member@user:u{c % n_users}")
    for d in range(n_docs):
        head = rng.randrange(n_chains) * depth
        rels.add(f"doc:d{d}#viewer@group:g{head}#member")
    return Workload(name="group-explosion",
                    schema_text=GROUP_EXPLOSION_SCHEMA,
                    relationships=sorted(rels),
                    subjects=[f"u{i}" for i in range(n_users)],
                    resource_type="doc", permission="view",
                    expected_objects=n_docs)


# fuzzer bias profiles: the budgeted random search steered toward each
# scenario's shape (scripts/fuzz_smoke.py --scenario)
SCENARIO_BIASES = {
    "caveat-heavy": (
        SchemaBias(caveat=0.6, wildcard=0.05, expiration=0.05),
        DeltaBias(caveat_boost=3.0, short_ttl=0.05, expired=0.05)),
    "wildcard-public": (
        SchemaBias(wildcard=0.45, caveat=0.05, expiration=0.05),
        DeltaBias(wildcard_boost=3.0, delete=0.4)),
    "ephemeral-grants": (
        SchemaBias(expiration=0.5, caveat=0.08, wildcard=0.05),
        DeltaBias(short_ttl=0.6, expired=0.1, advance=0.35)),
    # the Leopard shape: membership-only subgraphs (deep usersets and
    # arrows, near-zero caveat/wildcard/expiration so fragments stay
    # eligible, SOME exclusion/intersection so the planner's
    # ineligibility edges get hammered too) under delete-heavy churn
    # (the quarantine -> background re-close path)
    "nested-groups": (
        SchemaBias(userset=0.65, arrow=0.6, caveat=0.04, wildcard=0.03,
                   expiration=0.04, exclusion=0.08, intersection=0.06),
        DeltaBias(delete=0.4, caveat_boost=0.3, wildcard_boost=0.3,
                  short_ttl=0.05, expired=0.05, bulk=0.12)),
}

# the fixed-seed leopard smoke cells run the same shape universe at the
# smoke size cap (cheap kernel compiles, same contract as SMOKE_BIAS)
NESTED_GROUPS_SMOKE_BIAS = SchemaBias(
    userset=0.65, arrow=0.6, caveat=0.04, wildcard=0.03, expiration=0.04,
    exclusion=0.08, intersection=0.06, n_types=(2, 2, 2),
    n_rels=(2, 2, 3), n_perms=(1, 1, 2), expr_depth=1)

SCENARIO_WORKLOADS = {
    "caveat-heavy": caveat_heavy,
    "wildcard-public": wildcard_public,
    "ephemeral-grants": ephemeral_grants,
    "group-explosion": group_explosion,
}
