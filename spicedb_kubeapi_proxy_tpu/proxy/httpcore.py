"""Minimal asyncio HTTP/1.1 core (h11-based): server, client, transports.

The proxy's serving and upstream layers.  Keeps full control over streaming
(watch responses are long-lived chunked streams whose frames must be relayed
byte-exactly — reference pkg/authz/frames.go) and over encoding ownership
(the proxy strips the client's Accept-Encoding and handles upstream gzip
itself — reference pkg/proxy/server.go:98-108).

Two transports implement the upstream seam:
- HandlerTransport: direct in-process dispatch to a Handler (the reference's
  pkg/inmemory round tripper)
- H11Transport: real TCP/TLS connections.
"""

from __future__ import annotations

import asyncio
import gzip as gzip_mod
import ssl
from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable, Optional
from urllib.parse import urlsplit

import h11


class Headers:
    """Case-insensitive multi-value header collection."""

    def __init__(self, items: Optional[list] = None):
        self._items: list[tuple[str, str]] = []
        for k, v in items or []:
            self.add(k, v)

    def add(self, key: str, value: str) -> None:
        self._items.append((str(key), str(value)))

    def set(self, key: str, value: str) -> None:
        self.remove(key)
        self.add(key, value)

    def remove(self, key: str) -> None:
        lk = key.lower()
        self._items = [(k, v) for k, v in self._items if k.lower() != lk]

    def get(self, key: str, default: str = "") -> str:
        lk = key.lower()
        for k, v in self._items:
            if k.lower() == lk:
                return v
        return default

    def get_all(self, key: str) -> list:
        lk = key.lower()
        return [v for k, v in self._items if k.lower() == lk]

    def items(self) -> list:
        return list(self._items)

    def to_dict(self) -> dict:
        """{name: [values]} with canonical casing of first occurrence."""
        out: dict[str, list] = {}
        for k, v in self._items:
            out.setdefault(k, []).append(v)
        return out

    def __contains__(self, key: str) -> bool:
        return any(k.lower() == key.lower() for k, _ in self._items)

    def copy(self) -> "Headers":
        return Headers(self._items)


@dataclass
class Request:
    method: str
    target: str               # path + optional ?query
    headers: Headers = field(default_factory=Headers)
    body: bytes = b""
    # request-scoped context values (request_info, user, response filterer…)
    context: dict = field(default_factory=dict)
    peer_cert: Optional[dict] = None  # TLS client certificate, if any
    peer_cert_der: Optional[bytes] = None  # same certificate, DER bytes

    @property
    def path(self) -> str:
        return urlsplit(self.target).path


@dataclass
class Response:
    status: int = 200
    headers: Headers = field(default_factory=Headers)
    body: bytes = b""
    # set for streaming responses (watch); consumed exactly once
    stream: Optional[AsyncIterator[bytes]] = None

    @property
    def is_stream(self) -> bool:
        return self.stream is not None


Handler = Callable[[Request], Awaitable[Response]]


def json_response(status: int, obj, content_type: str = "application/json") -> Response:
    import json
    body = json.dumps(obj).encode()
    resp = Response(status=status, body=body)
    resp.headers.set("Content-Type", content_type)
    return resp


# ---------------------------------------------------------------------------
# Transports
# ---------------------------------------------------------------------------

class Transport:
    async def round_trip(self, req: Request) -> Response:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class HandlerTransport(Transport):
    """In-process dispatch (reference pkg/inmemory/transport.go)."""

    def __init__(self, handler: Handler):
        self.handler = handler

    async def round_trip(self, req: Request) -> Response:
        return await self.handler(req)


class H11Transport(Transport):
    """One TCP/TLS connection per request (no pooling); handles gzip
    decompression so response filtering always sees plaintext."""

    def __init__(self, base_url: str,
                 ssl_context: Optional[ssl.SSLContext] = None):
        split = urlsplit(base_url)
        self.scheme = split.scheme or "http"
        self.host = split.hostname or "localhost"
        self.port = split.port or (443 if self.scheme == "https" else 80)
        self.ssl_context = ssl_context

    async def round_trip(self, req: Request) -> Response:
        ssl_ctx = None
        if self.scheme == "https":
            ssl_ctx = self.ssl_context
            if ssl_ctx is None:
                ssl_ctx = ssl.create_default_context()
        reader, writer = await asyncio.open_connection(
            self.host, self.port, ssl=ssl_ctx)
        conn = h11.Connection(our_role=h11.CLIENT)

        headers = [(k, v) for k, v in req.headers.items()
                   if k.lower() not in ("host", "content-length",
                                        "transfer-encoding", "connection",
                                        "accept-encoding")]
        headers.append(("Host", f"{self.host}:{self.port}"))
        headers.append(("Content-Length", str(len(req.body))))
        # the transport owns encoding negotiation (reference activity.go:
        # 208-215, server.go:98-108): ask for gzip on its own behalf and
        # decompress transparently below, so callers always see plaintext.
        # Watch streams are relayed frame-by-frame without buffering, so
        # no gzip there.
        if "watch" not in urlsplit(req.target).query:
            headers.append(("Accept-Encoding", "gzip"))

        writer.write(conn.send(h11.Request(
            method=req.method.encode(), target=req.target.encode(),
            headers=[(k.encode(), v.encode()) for k, v in headers])))
        if req.body:
            writer.write(conn.send(h11.Data(data=req.body)))
        writer.write(conn.send(h11.EndOfMessage()))
        await writer.drain()

        async def next_event():
            while True:
                event = conn.next_event()
                if event is h11.NEED_DATA:
                    data = await reader.read(65536)
                    conn.receive_data(data)
                    continue
                return event

        event = await next_event()
        if not isinstance(event, h11.Response):
            writer.close()
            raise ConnectionError(f"unexpected h11 event {event!r}")
        resp = Response(status=event.status_code)
        for k, v in event.headers:
            resp.headers.add(k.decode(), v.decode())

        content_type = resp.headers.get("Content-Type", "")
        is_watch = "watch" in urlsplit(req.target).query and (
            "json" in content_type or content_type == "")

        if is_watch:
            async def stream():
                try:
                    while True:
                        ev = await next_event()
                        if isinstance(ev, h11.Data):
                            yield bytes(ev.data)
                        elif isinstance(ev, (h11.EndOfMessage,
                                             h11.ConnectionClosed)):
                            return
                finally:
                    writer.close()

            resp.stream = stream()
            return resp

        chunks = []
        while True:
            ev = await next_event()
            if isinstance(ev, h11.Data):
                chunks.append(bytes(ev.data))
            elif isinstance(ev, (h11.EndOfMessage, h11.ConnectionClosed)):
                break
        writer.close()
        resp.body = b"".join(chunks)
        if resp.headers.get("Content-Encoding").lower() == "gzip":
            resp.body = gzip_mod.decompress(resp.body)
            resp.headers.remove("Content-Encoding")
            resp.headers.set("Content-Length", str(len(resp.body)))
        return resp


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class HttpServer:
    """asyncio HTTP/1.1 server driving a Handler; supports TLS with optional
    client-certificate auth and streaming (chunked) responses."""

    def __init__(self, handler: Handler,
                 ssl_context: Optional[ssl.SSLContext] = None):
        self.handler = handler
        self.ssl_context = ssl_context
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(
            self._track_conn, host, port, ssl=self.ssl_context)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # long-lived watch connections would block wait_closed forever
            for task in list(self._conn_tasks):
                task.cancel()
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except asyncio.TimeoutError:
                pass
            self._server = None

    async def _track_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        try:
            await self._serve_conn(reader, writer)
        except asyncio.CancelledError:
            pass
        finally:
            self._conn_tasks.discard(task)

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        peer_cert = peer_cert_der = None
        ssl_obj = writer.get_extra_info("ssl_object")
        if ssl_obj is not None:
            try:
                peer_cert = ssl_obj.getpeercert()
                peer_cert_der = ssl_obj.getpeercert(True)
            except ValueError:
                peer_cert = peer_cert_der = None
        conn = h11.Connection(our_role=h11.SERVER)
        try:
            while True:
                event = await self._next_event(conn, reader)
                if isinstance(event, h11.ConnectionClosed) or event is None:
                    return
                if not isinstance(event, h11.Request):
                    return
                req = Request(
                    method=event.method.decode(),
                    target=event.target.decode(),
                    headers=Headers([(k.decode(), v.decode())
                                     for k, v in event.headers]),
                    peer_cert=peer_cert,
                    peer_cert_der=peer_cert_der,
                )
                body = bytearray()
                while True:
                    ev = await self._next_event(conn, reader)
                    if isinstance(ev, h11.Data):
                        body.extend(ev.data)
                    elif isinstance(ev, h11.EndOfMessage):
                        break
                    elif ev is None or isinstance(ev, h11.ConnectionClosed):
                        return
                req.body = bytes(body)

                try:
                    resp = await self.handler(req)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # panic recovery boundary
                    resp = json_response(500, {
                        "kind": "Status", "apiVersion": "v1",
                        "status": "Failure",
                        "message": f"internal error: {e}",
                        "code": 500})

                await self._write_response(conn, writer, resp)
                if conn.our_state is h11.MUST_CLOSE or resp.is_stream:
                    return
                conn.start_next_cycle()
        except (ConnectionResetError, BrokenPipeError, h11.RemoteProtocolError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    async def _next_event(conn: h11.Connection, reader: asyncio.StreamReader):
        while True:
            event = conn.next_event()
            if event is h11.NEED_DATA:
                data = await reader.read(65536)
                if not data and conn.their_state is h11.IDLE:
                    return None
                conn.receive_data(data)
                continue
            return event

    @staticmethod
    async def _write_response(conn: h11.Connection,
                              writer: asyncio.StreamWriter,
                              resp: Response) -> None:
        headers = [(k, v) for k, v in resp.headers.items()
                   if k.lower() not in ("content-length", "transfer-encoding",
                                        "connection", "date")]
        if resp.is_stream:
            headers.append(("Transfer-Encoding", "chunked"))
            writer.write(conn.send(h11.Response(
                status_code=resp.status,
                headers=[(k.encode(), v.encode()) for k, v in headers])))
            await writer.drain()
            try:
                async for chunk in resp.stream:
                    if chunk:
                        writer.write(conn.send(h11.Data(data=chunk)))
                        await writer.drain()
            finally:
                try:
                    writer.write(conn.send(h11.EndOfMessage()))
                    await writer.drain()
                except Exception:
                    pass
            return
        headers.append(("Content-Length", str(len(resp.body))))
        writer.write(conn.send(h11.Response(
            status_code=resp.status,
            headers=[(k.encode(), v.encode()) for k, v in headers])))
        if resp.body:
            writer.write(conn.send(h11.Data(data=resp.body)))
        writer.write(conn.send(h11.EndOfMessage()))
        await writer.drain()
