"""Admission control (utils/admission.py, docs/performance.md "Overload
& rebuild behavior"): bounded dispatcher queues fail fast with 429
semantics, dual-writes are exempt, the load shedder rejects read-only
traffic on queue-depth/SLO-burn signals, and the proxy chain surfaces it
all as kube-style 429 + Retry-After with /readyz degraded-but-200."""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import admission
from spicedb_kubeapi_proxy_tpu.utils.admission import (
    AdmissionRejectedError,
    LoadShedder,
)
from spicedb_kubeapi_proxy_tpu.utils.features import GATES
from spicedb_kubeapi_proxy_tpu.utils.metrics import REGISTRY

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""


class GatedEndpoint(EmbeddedEndpoint):
    """Embedded endpoint whose fused calls block on an event, so tests
    can hold a batch in flight while queues build deterministically."""

    def __init__(self, schema):
        super().__init__(schema)
        self.gate = asyncio.Event()
        self.gate.set()

    async def check_bulk_permissions(self, reqs):
        await self.gate.wait()
        return await super().check_bulk_permissions(reqs)

    async def lookup_resources_batch(self, resource_type, permission,
                                     subjects):
        await self.gate.wait()
        return await super().lookup_resources_batch(
            resource_type, permission, subjects)


def make(max_queue_depth=2, n_docs=6):
    inner = GatedEndpoint(sch.parse_schema(SCHEMA))
    inner.store.write([
        RelationshipUpdate(op=UpdateOp.TOUCH, rel=parse_relationship(
            f"doc:d{i}#viewer@user:u{i % 3}")) for i in range(n_docs)])
    return BatchingEndpoint(inner, max_batch=64,
                            max_queue_depth=max_queue_depth), inner


def check(user, doc="d0"):
    return CheckRequest(resource=ObjectRef("doc", doc), permission="view",
                        subject=SubjectRef("user", user))


def rejected_total():
    return sum(REGISTRY.get(
        "authz_admission_rejected_total").snapshot().values())


async def hold_batch_inflight(ep, inner):
    """Close the inner gate and park one check batch in execution so
    subsequent arrivals accumulate in the dispatcher queue."""
    inner.gate.clear()
    first = asyncio.create_task(ep.check_permission(check("u0")))
    for _ in range(50):
        await asyncio.sleep(0.001)
        if ep.stats["inflight_batch"]:
            break
    assert ep.stats["inflight_batch"] == 1
    return first


class TestQueueBounds:
    def test_check_queue_bound_rejects_fast(self):
        ep, inner = make(max_queue_depth=2)

        async def run():
            first = await hold_batch_inflight(ep, inner)
            # depth bound 2: two queued checks admit, the third rejects
            q1 = asyncio.create_task(ep.check_permission(check("u1")))
            q2 = asyncio.create_task(ep.check_permission(check("u2")))
            await asyncio.sleep(0.005)
            before = rejected_total()
            with pytest.raises(AdmissionRejectedError) as ei:
                await ep.check_permission(check("u0", "d3"))
            assert ei.value.reason == "queue_limit"
            assert ei.value.retry_after_s > 0
            assert rejected_total() == before + 1
            assert ep.stats["admission_rejected"] >= 1
            inner.gate.set()
            # admitted work completes correctly after the rejection
            assert (await first).allowed
            assert (await q1).allowed is False or (await q1) is not None
            await q2

        asyncio.run(run())

    def test_bulk_check_admitted_or_rejected_whole(self):
        ep, inner = make(max_queue_depth=3)

        async def run():
            first = await hold_batch_inflight(ep, inner)
            # the bound limits BACKLOG, not request size: a bulk larger
            # than the bound arriving at an EMPTY queue admits whole
            # (rejecting it would make large lists permanently
            # unservable — retry could never succeed)
            big = asyncio.create_task(ep.check_bulk_permissions(
                [check(f"w{i}") for i in range(5)]))
            await asyncio.sleep(0.005)
            assert ep.stats["check_queue_depth"] == 5
            # but with a backlog standing, a bulk that would grow it
            # past the bound rejects WHOLE: nothing half-queued
            with pytest.raises(AdmissionRejectedError):
                await ep.check_bulk_permissions(
                    [check(f"u{i}") for i in range(4)])
            assert ep.stats["check_queue_depth"] == 5
            inner.gate.set()
            assert len(await big) == 5
            await first

        asyncio.run(run())

    def test_lookup_bound_and_singleflight_followers_free(self):
        ep, inner = make(max_queue_depth=1)

        async def run():
            first = await hold_batch_inflight(ep, inner)
            lead = asyncio.create_task(ep.lookup_resources(
                "doc", "view", SubjectRef("user", "u0")))
            await asyncio.sleep(0.005)
            # identical query: singleflight follower, no queue entry,
            # admitted despite the bound being full
            follow = asyncio.create_task(ep.lookup_resources(
                "doc", "view", SubjectRef("user", "u0")))
            await asyncio.sleep(0.005)
            assert not follow.done()
            # distinct query needs a new queue entry: rejected
            with pytest.raises(AdmissionRejectedError):
                await ep.lookup_resources("doc", "view",
                                          SubjectRef("user", "u1"))
            inner.gate.set()
            assert sorted(await lead) == sorted(await follow)
            assert ep.stats["singleflight_hits"] == 1
            await first

        asyncio.run(run())

    def test_lookup_bulk_larger_than_bound_admits_whole_when_idle(self):
        """The whole-batch admit at the door must not be undone by the
        per-leader admit inside _enqueue_lookup: a 10-subject batch
        against bound 4 at an idle queue admits WHOLE (rejecting at
        subject 5 would strand the first 4 leaders and make large
        batches permanently unservable)."""
        ep, inner = make(max_queue_depth=4)

        async def run():
            subs = [SubjectRef("user", f"u{i}") for i in range(10)]
            out = await ep.lookup_resources_batch("doc", "view", subs)
            assert len(out) == 10

        asyncio.run(run())

    def test_exempt_context_bypasses_bound(self):
        ep, inner = make(max_queue_depth=1)

        async def run():
            first = await hold_batch_inflight(ep, inner)
            q1 = asyncio.create_task(ep.check_permission(check("u1")))
            await asyncio.sleep(0.005)
            # bound full — but a dual-write's authorization is exempt
            with admission.exempt():
                exempt_task = asyncio.create_task(
                    ep.check_permission(check("u2")))
            await asyncio.sleep(0.005)
            assert not exempt_task.done()
            inner.gate.set()
            await asyncio.gather(first, q1, exempt_task)

        asyncio.run(run())

    def test_gate_off_disables_bounds(self):
        ep, inner = make(max_queue_depth=1)
        GATES.set("AdmissionControl", False)
        try:
            async def run():
                first = await hold_batch_inflight(ep, inner)
                tasks = [asyncio.create_task(
                    ep.check_permission(check(f"u{i}"))) for i in range(5)]
                await asyncio.sleep(0.005)
                inner.gate.set()
                await asyncio.gather(first, *tasks)

            asyncio.run(run())
        finally:
            GATES.set("AdmissionControl", True)

    def test_unbounded_default_never_rejects(self):
        ep, inner = make(max_queue_depth=0)

        async def run():
            first = await hold_batch_inflight(ep, inner)
            tasks = [asyncio.create_task(
                ep.check_permission(check(f"u{i % 3}"))) for i in range(32)]
            await asyncio.sleep(0.005)
            inner.gate.set()
            await asyncio.gather(first, *tasks)

        asyncio.run(run())


class TestLoadShedder:
    def test_sheds_reads_on_queue_depth(self):
        depth = {"check_queue_depth": 5, "lr_queue_depth": 3}
        s = LoadShedder(shed_queue_depth=8, retry_after_s=2.0,
                        stats_fn=lambda: depth)
        assert s.check("list") == "queue_depth"
        assert s.shedding_recently()
        # update verbs are never shed
        assert s.check("create") is None
        assert s.check("delete") is None
        depth["check_queue_depth"] = 0
        assert s.check("list") is None

    def test_sheds_reads_on_slo_burn(self):
        burning = [{"slo": "latency_p99"}]
        s = LoadShedder(shed_on_burn=True, burning_fn=lambda: burning)
        assert s.check("get") == "slo_burn"
        burning.clear()
        assert s.check("get") is None

    def test_inert_without_thresholds_and_with_gate_off(self):
        s = LoadShedder(stats_fn=lambda: {"check_queue_depth": 99})
        assert s.check("list") is None
        s2 = LoadShedder(shed_queue_depth=1,
                         stats_fn=lambda: {"check_queue_depth": 99})
        GATES.set("AdmissionControl", False)
        try:
            assert s2.check("list") is None
        finally:
            GATES.set("AdmissionControl", True)
        assert s2.check("list") == "queue_depth"

    def test_metrics_and_snapshot(self):
        before = rejected_total()
        s = LoadShedder(shed_queue_depth=1,
                        stats_fn=lambda: {"check_queue_depth": 2})
        assert s.check("list") == "queue_depth"
        assert rejected_total() == before + 1
        snap = s.snapshot()
        assert snap["shed_total"] == 1
        assert snap["shedding_recently"] is True


class TestProxyChain:
    """End-to-end 429 mapping through the real handler chain."""

    def _server(self, **opt_kw):
        from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import (
            FakeKubeApiServer)
        from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
        from spicedb_kubeapi_proxy_tpu.proxy.server import (
            Options, ProxyServer)
        from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap

        kube = FakeKubeApiServer()
        kube.seed("", "v1", "pods",
                  {"metadata": {"name": "p0", "namespace": "ns"}})
        rules = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
"""
        schema = """
definition user {}
definition pod {
  relation creator: user
  permission view = creator
}
"""
        server = ProxyServer(Options(
            spicedb_endpoint="embedded://",
            bootstrap=Bootstrap(schema_text=schema),
            rules_yaml=rules,
            upstream_transport=HandlerTransport(kube),
            **opt_kw))
        server.endpoint.store.bulk_load(
            [parse_relationship("pod:ns/p0#creator@user:alice")])
        return server

    def test_admission_error_maps_to_429_with_retry_after(self):
        server = self._server()

        def reject_stream(*a, **kw):
            async def gen():
                raise AdmissionRejectedError(
                    "queue full", reason="queue_limit", retry_after_s=3.0)
                yield  # pragma: no cover — makes this an async generator

            return gen()

        async def run():
            # inject a rejection at the endpoint boundary (the prefilter
            # LR stream): the chain must surface 429 + Retry-After, not
            # 403/500/502
            server.endpoint.lookup_resources_stream = reject_stream
            client = server.get_embedded_client(user="alice")
            resp = await client.get("/api/v1/pods")
            assert resp.status == 429, resp.body
            assert resp.headers.get("Retry-After") == "3"
            assert b"TooManyRequests" in resp.body

        asyncio.run(run())

    def test_shedder_rejects_reads_keeps_writes(self):
        server = self._server(shed_queue_depth=1, shed_retry_after_s=2.0)
        # force the saturation signal
        server.shedder._stats_fn = lambda: {"check_queue_depth": 5}

        async def run():
            client = server.get_embedded_client(user="alice")
            resp = await client.get("/api/v1/pods")
            assert resp.status == 429, resp.body
            assert resp.headers.get("Retry-After") == "2"
            # /readyz reflects shedding as degraded-but-200
            ready = await client.get("/readyz")
            assert ready.status == 200
            assert b"admission control shedding" in ready.body
            # health endpoints and metrics are never shed
            assert (await client.get("/livez")).status == 200
            resp = await client.get("/metrics")
            assert resp.status == 200
            assert b"authz_admission_rejected_total" in resp.body

        asyncio.run(run())


class TestOverloadBehavior:
    """Overload turns into fast 429s + sustained goodput, never a hang:
    queues bounded, admitted work completes, rejected work fails fast."""

    def test_overload_sheds_and_keeps_goodput(self):
        ep, inner = make(max_queue_depth=4, n_docs=9)

        async def run():
            first = await hold_batch_inflight(ep, inner)
            results = []

            async def one(i):
                try:
                    r = await ep.check_permission(check(f"u{i % 3}",
                                                        f"d{i % 9}"))
                    results.append(("ok", r))
                except AdmissionRejectedError:
                    results.append(("shed", None))

            tasks = [asyncio.create_task(one(i)) for i in range(24)]
            await asyncio.sleep(0.01)
            inner.gate.set()
            # never hangs: everything resolves quickly once the gate
            # opens (rejections resolved even before it)
            await asyncio.wait_for(asyncio.gather(first, *tasks), timeout=10)
            kinds = [k for k, _ in results]
            assert kinds.count("shed") >= 1, "overload never shed"
            assert kinds.count("ok") >= 4, "no goodput under overload"
            # post-overload: the system recovers completely
            r = await ep.check_permission(check("u0"))
            assert r.allowed

        asyncio.run(run())
