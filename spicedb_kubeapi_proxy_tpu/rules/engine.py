"""Rules compiler and runtime: templates -> RunnableRules, request matching.

Mirrors the behavior of the reference rules engine (pkg/rules/rules.go):
- `compile_rule` -> RunnableRule with precompiled template expressions and
  CEL conditions (reference rules.go:719-900)
- `MapMatcher` keyed on (verb, group, version, resource)
  (reference rules.go:78-117)
- `ResolveInput` extraction and normalization (reference rules.go:231-353)
- template field compilation with `{{ expr }}` detection and literal
  wrapping (reference rules.go:1008-1029), tupleSet expressions returning
  arrays of relationship strings (reference rules.go:148-201)
- `split_name` / `split_namespace` helper functions (reference env.go:13-58).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

from ..config import proxyrule
from ..proxy.kube import RequestInfo, UserInfo
from . import blang, cel
from .relstring import ResolvedRel, UncompiledRelExpr, parse_rel_string


class RuleCompileError(ValueError):
    pass


class ResolveError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Expression environment
# ---------------------------------------------------------------------------

def _split_name(value: Any) -> Any:
    """`ns/name` -> `name`; passthrough when no separator (env.go:19-38)."""
    if not isinstance(value, str):
        raise blang.BlangEvalError("split_name expects a string argument")
    if "/" not in value:
        return value
    return value.split("/", 1)[1]


def _split_namespace(value: Any) -> Any:
    """`ns/name` -> `ns`; empty when no separator (env.go:40-58)."""
    if not isinstance(value, str):
        raise blang.BlangEvalError("split_namespace expects a string argument")
    if "/" not in value:
        return ""
    return value.split("/", 1)[0]


def default_environment() -> blang.Environment:
    env = blang.Environment()
    env.register_function("split_name", _split_name)
    env.register_function("split_namespace", _split_namespace)
    return env


_ENV = default_environment()


def compile_template_expression(expr: str) -> blang.Executor:
    """Compile a template field: `{{ expr }}` is an expression, anything else
    is a literal (reference rules.go:1008-1029, including the quirk that a
    half-delimited `{{foo` compiles as the literal with delimiters stripped).
    """
    expr = expr.strip()
    if expr == "":
        return _ENV.parse('""')
    has_prefix = expr.startswith("{{")
    if has_prefix:
        expr = expr[2:]
    has_suffix = expr.endswith("}}")
    if has_suffix:
        expr = expr[:-2]
    if not (has_prefix and has_suffix):
        if expr == "":
            return _ENV.parse('""')
        return _LiteralExecutor(expr)
    inner = expr.strip()
    if inner == "":
        return _ENV.parse('""')
    return _ENV.parse(inner)


def compile_tuple_set_expression(expr: str) -> blang.Executor:
    """tupleSet fields are always expressions; optional {{ }} wrapper is
    stripped (reference rules.go:1035-1051)."""
    expr = expr.strip()
    if expr == "":
        return _ENV.parse('""')
    if expr.startswith("{{") and expr.endswith("}}"):
        expr = expr[2:-2].strip()
        if expr == "":
            return _ENV.parse('""')
    return _ENV.parse(expr)


class _LiteralExecutor(blang.Executor):
    """An executor that returns a fixed string (literal template field)."""

    def __init__(self, value: str):
        self._value = value

    def query(self, data: Any) -> Any:
        return self._value


# ---------------------------------------------------------------------------
# ResolveInput
# ---------------------------------------------------------------------------

@dataclass
class ResolveInput:
    """The data fed into template expressions (reference rules.go:231-240)."""
    name: str = ""
    namespace: str = ""
    namespaced_name: str = ""
    request: Optional[RequestInfo] = None
    user: Optional[UserInfo] = None
    object: Optional[dict] = None  # partial object metadata: {"metadata": {...}}
    body: bytes = b""
    headers: dict = field(default_factory=dict)  # name -> list[str]

    def to_key_values(self) -> list:
        """Structured log fields (reference rules.go:242-279)."""
        out: list[Any] = [
            "name", self.name,
            "namespace", self.namespace,
            "namespacedName", self.namespaced_name,
            "object", self.object,
            "body", self.body,
        ]
        if self.request is not None:
            out += [
                "request.verb", self.request.verb,
                "request.resource", self.request.resource,
                "request.labelSelector", self.request.label_selector,
                "request.fieldSelector", self.request.field_selector,
                "request.path", self.request.path,
            ]
        if self.user is not None:
            out += [
                "user.name", self.user.name,
                "user.groups", self.user.groups,
                "user.extra", self.user.extra,
            ]
        for k, v in self.headers.items():
            out += [k, v]
        return out


def new_resolve_input(request: RequestInfo, user: UserInfo,
                      obj: Optional[dict] = None, body: bytes = b"",
                      headers: Optional[dict] = None) -> ResolveInput:
    """Normalized input construction (reference rules.go:315-353): name and
    namespace default from the object, fall back to the request; requests on
    the `namespaces` resource clear the namespace so they match other
    cluster-scoped objects."""
    name = ""
    namespace = ""
    if obj is not None:
        meta = obj.get("metadata") or {}
        name = meta.get("name") or ""
        namespace = meta.get("namespace") or ""
    if not name:
        name = request.name
    if not namespace:
        namespace = request.namespace
    if request.resource == "namespaces":
        namespace = ""
    namespaced_name = f"{namespace}/{name}" if namespace else name
    return ResolveInput(
        name=name,
        namespace=namespace,
        namespaced_name=namespaced_name,
        request=request,
        user=user,
        object=obj,
        body=body,
        headers=headers or {},
    )


def resolve_input_from_request(request: RequestInfo, user: UserInfo,
                               body: bytes, headers: dict) -> ResolveInput:
    """HTTP extraction (reference rules.go:281-312): create/update/patch
    bodies are parsed as kube objects and carried in the input."""
    obj: Optional[dict] = None
    parsed_body = b""
    if request.verb in ("create", "update", "patch"):
        parsed_body = body
        try:
            decoded = json.loads(body) if body else None
        except (ValueError, UnicodeDecodeError) as e:
            raise ResolveError(f"unable to decode request body as kube object: {e}") from e
        if not isinstance(decoded, dict):
            raise ResolveError("unable to decode request body as kube object")
        obj = {"metadata": decoded.get("metadata") or {}}
        obj["apiVersion"] = decoded.get("apiVersion", "")
        obj["kind"] = decoded.get("kind", "")
    return new_resolve_input(request, user, obj, parsed_body, headers)


def _to_template_data(inp: ResolveInput) -> dict:
    """Input conversion for template expressions (reference rules.go:524-617),
    including the `resourceId` alias and object/metadata body merge."""
    data: dict[str, Any] = {
        "name": inp.name,
        "namespace": inp.namespace,
        "namespacedName": inp.namespaced_name,
        "resourceId": inp.namespaced_name,
        "headers": {k: list(v) for k, v in inp.headers.items()},
    }
    if inp.request is not None:
        data["request"] = {
            "verb": inp.request.verb,
            "apiGroup": inp.request.api_group,
            "apiVersion": inp.request.api_version,
            "resource": inp.request.resource,
            "name": inp.request.name,
            "namespace": inp.request.namespace,
        }
    if inp.user is not None:
        data["user"] = {
            "name": inp.user.name,
            "uid": inp.user.uid,
            "groups": list(inp.user.groups),
            "extra": {k: list(v) for k, v in inp.user.extra.items()},
        }
    body_data: Optional[dict] = None
    if inp.body:
        try:
            parsed = json.loads(inp.body)
            if isinstance(parsed, dict):
                body_data = parsed
        except (ValueError, UnicodeDecodeError):
            body_data = None
    if body_data is not None:
        object_data = dict(body_data)
        if inp.object is not None and "metadata" in inp.object:
            object_data["metadata"] = inp.object["metadata"]
        data["object"] = object_data
        if "metadata" in object_data:
            data["metadata"] = object_data["metadata"]
    elif inp.object is not None:
        object_data = {"metadata": inp.object.get("metadata") or {}}
        data["object"] = object_data
        data["metadata"] = object_data["metadata"]
    if inp.body:
        data["body"] = inp.body.decode("utf-8", errors="replace")
    return data


def _to_cel_input(inp: ResolveInput) -> dict:
    """Input conversion for CEL conditions (reference rules.go:470-521)."""
    data: dict[str, Any] = {
        "name": inp.name,
        "resourceNamespace": inp.namespace,
        "namespacedName": inp.namespaced_name,
        "headers": {k: list(v) for k, v in inp.headers.items()},
    }
    if inp.body:
        data["body"] = inp.body
    if inp.request is not None:
        data["request"] = {
            "verb": inp.request.verb,
            "apiGroup": inp.request.api_group,
            "apiVersion": inp.request.api_version,
            "resource": inp.request.resource,
            "name": inp.request.name,
            "namespace": inp.request.namespace,
        }
    if inp.user is not None:
        data["user"] = {
            "name": inp.user.name,
            "uid": inp.user.uid,
            "groups": list(inp.user.groups),
            "extra": {k: list(v) for k, v in inp.user.extra.items()},
        }
    if inp.object is not None:
        data["object"] = inp.object
    return data


# ---------------------------------------------------------------------------
# Relationship expressions
# ---------------------------------------------------------------------------

@dataclass
class RelExpr:
    """A relationship template with compiled field expressions
    (reference rules.go:137-144)."""
    resource_type: blang.Executor
    resource_id: blang.Executor
    resource_relation: blang.Executor
    subject_type: blang.Executor
    subject_id: blang.Executor
    subject_relation: Optional[blang.Executor] = None

    def generate_relationships(self, inp: ResolveInput) -> list:
        return [resolve_rel(self, inp)]


@dataclass
class TupleSetExpr:
    """An expression returning an array of relationship strings
    (reference rules.go:148-201)."""
    expression: blang.Executor

    def generate_relationships(self, inp: ResolveInput) -> list:
        data = _to_template_data(inp)
        try:
            result = self.expression.query(data)
        except blang.BlangError as e:
            raise ResolveError(f"error executing tuple set expression: {e}") from e
        if not isinstance(result, list):
            raise ResolveError(
                f"tuple set expression must return an array, got {type(result).__name__}")
        rels = []
        for i, item in enumerate(result):
            if not isinstance(item, str):
                raise ResolveError(
                    f"tuple set expression item {i} must be a string, got {type(item).__name__}")
            try:
                u = parse_rel_string(item)
            except ValueError as e:
                raise ResolveError(f"error parsing relationship string {item!r}: {e}") from e
            rels.append(ResolvedRel(
                resource_type=u.resource_type,
                resource_id=u.resource_id,
                resource_relation=u.resource_relation,
                subject_type=u.subject_type,
                subject_id=u.subject_id,
                subject_relation=u.subject_relation,
            ))
        return rels


def resolve_rel(expr: RelExpr, inp: ResolveInput) -> ResolvedRel:
    """Evaluate all six field expressions (reference rules.go:355-417):
    a None result is an error; results must be strings."""
    data = _to_template_data(inp)

    def q(executor: blang.Executor, what: str) -> str:
        try:
            v = executor.query(data)
        except blang.BlangError as e:
            raise ResolveError(f"error resolving relationship: {e}") from e
        if v is None:
            raise ResolveError(f"error resolving relationship: empty {what}")
        if not isinstance(v, str):
            raise ResolveError(
                f"error resolving relationship: {what} must be a string, got {type(v).__name__}")
        return v

    rel = ResolvedRel(
        resource_type=q(expr.resource_type, "resource type"),
        resource_id=q(expr.resource_id, "resource id"),
        resource_relation=q(expr.resource_relation, "relation"),
        subject_type=q(expr.subject_type, "subject type"),
        subject_id=q(expr.subject_id, "subject id"),
    )
    if expr.subject_relation is not None:
        rel.subject_relation = q(expr.subject_relation, "subject relation")
    return rel


# ---------------------------------------------------------------------------
# Runnable rules
# ---------------------------------------------------------------------------

@dataclass
class PreFilter:
    """Compiled prefilter (reference rules.go:689-693)."""
    name_from_object_id: blang.Executor
    namespace_from_object_id: blang.Executor
    rel: RelExpr


@dataclass
class ResolvedPreFilter:
    """A prefilter whose LR template has been resolved for a request
    (reference rules.go:698-702)."""
    name_from_object_id: blang.Executor
    namespace_from_object_id: blang.Executor
    rel: ResolvedRel


@dataclass
class PostFilter:
    rel: RelExpr


@dataclass
class UpdateSet:
    must_exist: list = field(default_factory=list)
    must_not_exist: list = field(default_factory=list)
    creates: list = field(default_factory=list)
    touches: list = field(default_factory=list)
    deletes: list = field(default_factory=list)
    deletes_by_filter: list = field(default_factory=list)


@dataclass
class RunnableRule:
    """A fully compiled rule (reference rules.go:660-669)."""
    name: str = ""
    lock_mode: str = ""
    if_conditions: list = field(default_factory=list)  # cel.Program
    checks: list = field(default_factory=list)
    post_checks: list = field(default_factory=list)
    update: Optional[UpdateSet] = None
    pre_filter: list = field(default_factory=list)
    post_filter: list = field(default_factory=list)


def _compile_rel_template(t: proxyrule.StringOrTemplate) -> RelExpr:
    if t.template:
        u = parse_rel_string(t.template)
    else:
        rt = t.relationship_template
        u = UncompiledRelExpr(
            resource_type=rt.resource.type,
            resource_id=rt.resource.id,
            resource_relation=rt.resource.relation,
            subject_type=rt.subject.type,
            subject_id=rt.subject.id,
            subject_relation=rt.subject.relation,
        )
    try:
        expr = RelExpr(
            resource_type=compile_template_expression(u.resource_type),
            resource_id=compile_template_expression(u.resource_id),
            resource_relation=compile_template_expression(u.resource_relation),
            subject_type=compile_template_expression(u.subject_type),
            subject_id=compile_template_expression(u.subject_id),
        )
        if u.subject_relation:
            expr.subject_relation = compile_template_expression(u.subject_relation)
    except blang.BlangError as e:
        raise RuleCompileError(f"error compiling relationship template: {e}") from e
    return expr


def _compile_templates(tmpls: list) -> list:
    out = []
    for t in tmpls:
        if t.tuple_set:
            try:
                executor = compile_tuple_set_expression(t.tuple_set)
            except blang.BlangError as e:
                raise RuleCompileError(f"error compiling tuple set expression: {e}") from e
            out.append(TupleSetExpr(executor))
        else:
            out.append(_compile_rel_template(t))
    return out


def _compile_single_rel(t: proxyrule.StringOrTemplate, what: str) -> RelExpr:
    if t.tuple_set:
        raise RuleCompileError(
            f"{what}: tupleSet is not allowed in this context, use tpl or a"
            " relationship template instead")
    return _compile_rel_template(t)


_POSTCHECK_INCOMPATIBLE_VERBS = ("create", "update", "patch", "delete", "list", "watch")


def compile_rule(config: proxyrule.Config) -> RunnableRule:
    """Compile a parsed config into a RunnableRule (reference rules.go:719-900)."""
    spec = config.spec
    rule = RunnableRule(name=config.name, lock_mode=spec.locking)

    for i, expr in enumerate(spec.if_conditions):
        try:
            rule.if_conditions.append(cel.compile_condition(expr))
        except cel.CELCompileError as e:
            raise RuleCompileError(
                f"error compiling CEL expression {i} ({expr!r}): {e}") from e

    try:
        rule.checks = _compile_templates(spec.checks)
    except RuleCompileError as e:
        raise RuleCompileError(f"error compiling checks: {e}") from e
    try:
        rule.post_checks = _compile_templates(spec.post_checks)
    except RuleCompileError as e:
        raise RuleCompileError(f"error compiling postchecks: {e}") from e

    if spec.post_checks:
        for m in spec.matches:
            for v in m.verbs:
                if v in _POSTCHECK_INCOMPATIBLE_VERBS:
                    raise RuleCompileError(
                        f"PostCheck operations cannot be used with verb {v!r}."
                        " PostChecks only apply to read-only operations like 'get'")

    u = spec.update
    if not u.empty():
        rule.update = UpdateSet(
            must_exist=_compile_templates(u.precondition_exists),
            must_not_exist=_compile_templates(u.precondition_does_not_exist),
            creates=_compile_templates(u.creates),
            touches=_compile_templates(u.touches),
            deletes=_compile_templates(u.deletes),
            deletes_by_filter=_compile_templates(u.delete_by_filter),
        )

    for f in spec.pre_filters:
        try:
            name_exec = compile_template_expression(f.from_object_id_name_expr)
            ns_exec = compile_template_expression(f.from_object_id_namespace_expr)
        except blang.BlangError as e:
            raise RuleCompileError(f"failed to compile expression: {e}") from e
        if f.lookup_matching_resources is None:
            raise RuleCompileError("pre-filter must have LookupMatchingResources defined")
        rel = _compile_single_rel(f.lookup_matching_resources, "LookupMatchingResources")
        # The LR resourceID template must produce `$` (reference rules.go:858-877).
        try:
            processed = rel.resource_id.query({"resourceId": "$"})
        except blang.BlangError as e:
            raise RuleCompileError(
                f"error processing resource ID in LookupMatchingResources: {e}") from e
        if processed != proxyrule.MATCHING_ID_FIELD_VALUE:
            raise RuleCompileError(
                "LookupMatchingResources resourceID must be set to $ to match"
                f" all resources, got {processed!r}")
        rule.pre_filter.append(PreFilter(
            name_from_object_id=name_exec,
            namespace_from_object_id=ns_exec,
            rel=rel,
        ))

    for f in spec.post_filters:
        rel = _compile_single_rel(f.check_permission_template, "CheckPermissionTemplate")
        rule.post_filter.append(PostFilter(rel=rel))

    return rule


# ---------------------------------------------------------------------------
# Matching
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RequestMeta:
    verb: str
    api_group: str
    api_version: str
    resource: str


def _parse_group_version(gv: str) -> tuple:
    """'v1' -> ('', 'v1'); 'apps/v1' -> ('apps', 'v1')."""
    if not gv:
        return "", ""
    parts = gv.split("/")
    if len(parts) == 1:
        return "", parts[0]
    if len(parts) == 2:
        return parts[0], parts[1]
    raise RuleCompileError(f"couldn't parse gv {gv!r}: unexpected GroupVersion string")


class MapMatcher:
    """Rules keyed on (verb, group, version, resource)
    (reference rules.go:78-117)."""

    def __init__(self, configs: list):
        self._rules: dict[RequestMeta, list[RunnableRule]] = {}
        for cfg in configs:
            for m in cfg.spec.matches:
                group, version = _parse_group_version(m.group_version)
                for verb in m.verbs:
                    meta = RequestMeta(verb=verb, api_group=group,
                                       api_version=version, resource=m.resource)
                    try:
                        compiled = compile_rule(cfg)
                    except RuleCompileError as e:
                        raise RuleCompileError(
                            f"couldn't compile rule {cfg.name}: {e}") from e
                    self._rules.setdefault(meta, []).append(compiled)

    def match(self, info: RequestInfo) -> list:
        return self._rules.get(RequestMeta(
            verb=info.verb,
            api_group=info.api_group,
            api_version=info.api_version,
            resource=info.resource,
        ), [])


# ---------------------------------------------------------------------------
# CEL condition evaluation
# ---------------------------------------------------------------------------

def evaluate_cel_conditions(programs: list, inp: ResolveInput) -> bool:
    """All conditions must be true (reference rules.go:420-449)."""
    if not programs:
        return True
    cel_input = _to_cel_input(inp)
    for i, program in enumerate(programs):
        try:
            result = program.eval(cel_input)
        except cel.CELError as e:
            raise ResolveError(f"error evaluating CEL condition {i}: {e}") from e
        if not isinstance(result, bool):
            raise ResolveError(
                f"CEL condition {i} returned non-boolean value: {result!r}")
        if not result:
            return False
    return True


def filter_rules_with_cel_conditions(rules: list, inp: ResolveInput) -> list:
    """Keep rules whose conditions all pass (reference rules.go:452-467)."""
    out = []
    for rule in rules:
        if evaluate_cel_conditions(rule.if_conditions, inp):
            out.append(rule)
    return out
