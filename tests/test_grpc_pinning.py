"""Remote-endpoint certificate pinning (reference options.go:349-355).

Round-4 fixes pinned: target parsing handles bracketed/bare IPv6, the PEM
is parsed with cryptography (no private CPython API), and async callers
fetch the certificate in an executor — never blocking the event loop.
"""

import asyncio
import datetime
import threading

import pytest

# collection must degrade gracefully where cryptography is absent (the
# module is a dev requirement, requirements-dev.txt): skip, don't error
pytest.importorskip(
    "cryptography",
    reason="cryptography not installed (see requirements-dev.txt)")
from cryptography import x509  # noqa: E402
from cryptography.hazmat.primitives import hashes  # noqa: E402
from cryptography.hazmat.primitives.asymmetric import ec  # noqa: E402
from cryptography.x509.oid import NameOID  # noqa: E402

from spicedb_kubeapi_proxy_tpu.spicedb.grpc_remote import (  # noqa: E402
    RemoteEndpoint)


def self_signed_pem(cn="myserver", san_dns="alt.example"):
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime(2026, 1, 1)
    builder = (x509.CertificateBuilder()
               .subject_name(name).issuer_name(name)
               .public_key(key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now)
               .not_valid_after(now + datetime.timedelta(days=3650)))
    if san_dns:
        builder = builder.add_extension(
            x509.SubjectAlternativeName([x509.DNSName(san_dns)]),
            critical=False)
    cert = builder.sign(key, hashes.SHA256())
    from cryptography.hazmat.primitives.serialization import Encoding
    return cert.public_bytes(Encoding.PEM).decode()


class TestParseTarget:
    def test_bracketed_ipv6_with_port(self):
        assert RemoteEndpoint._parse_target("[::1]:50051") == ("::1", 50051)

    def test_bracketed_ipv6_no_port(self):
        assert RemoteEndpoint._parse_target("[fe80::1]") == ("fe80::1", 443)

    def test_bare_ipv6_no_port(self):
        assert RemoteEndpoint._parse_target("fe80::1:2") == ("fe80::1:2", 443)

    def test_host_port(self):
        assert RemoteEndpoint._parse_target("example.com:443") == (
            "example.com", 443)

    def test_host_only_defaults_443(self):
        assert RemoteEndpoint._parse_target("example.com") == (
            "example.com", 443)


class TestPinning:
    def _patched(self, monkeypatch, pem, record):
        import ssl

        def fake_get(addr, timeout=None):
            record.append((addr, threading.current_thread()))
            return pem

        monkeypatch.setattr(ssl, "get_server_certificate", fake_get)

    def test_san_name_override_without_private_api(self, monkeypatch):
        record = []
        self._patched(monkeypatch, self_signed_pem(), record)
        ep = RemoteEndpoint("[::1]:50051", skip_verify=True)
        pem, options = ep._pin_server_cert()
        # brackets stripped for the socket dial
        assert record[0][0] == ("::1", 50051)
        # SAN DNS preferred for the TLS target-name override
        assert options == [("grpc.ssl_target_name_override", "alt.example")]
        assert pem.startswith(b"-----BEGIN CERTIFICATE-----")

    def test_cn_fallback_when_no_san(self, monkeypatch):
        record = []
        self._patched(monkeypatch, self_signed_pem(san_dns=None), record)
        ep = RemoteEndpoint("10.0.0.9:443", skip_verify=True)
        _, options = ep._pin_server_cert()
        assert options == [("grpc.ssl_target_name_override", "myserver")]

    def test_ensure_pinned_runs_off_loop(self, monkeypatch):
        """The blocking fetch must run in an executor thread, not on the
        event loop thread (r3 ADVICE / VERDICT weak #6)."""
        record = []
        self._patched(monkeypatch, self_signed_pem(), record)
        ep = RemoteEndpoint("host:443", skip_verify=True)

        async def go():
            loop_thread = threading.current_thread()
            await ep._ensure_pinned()
            assert record, "certificate was not fetched"
            fetch_thread = record[0][1]
            assert fetch_thread is not loop_thread
        asyncio.run(go())
        # cached: a second call must not re-fetch
        ep._pin_server_cert()
        assert len(record) == 1

    def test_no_pin_when_ca_given(self, monkeypatch):
        record = []
        self._patched(monkeypatch, self_signed_pem(), record)
        ep = RemoteEndpoint("host:443", skip_verify=True, ca_pem=b"ca")

        async def go():
            await ep._ensure_pinned()
        asyncio.run(go())
        assert record == []  # explicit CA wins; nothing fetched
