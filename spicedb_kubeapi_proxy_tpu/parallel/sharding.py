"""Multi-chip sharded reachability: 2D (data x graph) mesh over ICI/DCN.

Replaces the reference's single-process graph-walk distribution (SpiceDB
internal dispatch, reference pkg/spicedb/spicedb.go:31-47) with a
`shard_map` program over a `jax.sharding.Mesh`:

- `data` axis  — query batch sharded (each chip owns B/n_data query
  columns): pure data parallelism for concurrent list requests, zero
  communication.
- `graph` axis — edge set sharded (each chip owns E/n_graph edges of the
  tuple graph): each chip computes a partial one-step closure over the full
  state vector, combined with a boolean all-reduce (`lax.pmax`) per
  iteration.  This is what lets tuple counts exceed single-chip HBM.

The per-iteration body is ops/spmv.make_step with the all-reduce injected
via its `combine` hook, so single-chip and sharded kernels cannot drift.
Convergence (while_loop) uses a globally all-reduced changed flag so every
shard agrees on the trip count.  On a v5e-8 both axes map onto ICI, and
`jax.distributed` extends the same program across hosts over DCN
(SURVEY.md §5 communication-backend note).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.graph_compile import GraphProgram
from ..ops.spmv import MAX_ITERATIONS, bucket, make_evaluate, pad_edges


def make_mesh(devices=None, data: Optional[int] = None,
              graph: Optional[int] = None) -> Mesh:
    """Build a 2D (data, graph) mesh.  Defaults: square-ish split of all
    local devices with the graph axis at least as large as the data axis."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if data is None or graph is None:
        # smallest factor pair with graph >= data: the graph axis is the
        # HBM-capacity axis and must get the larger share
        graph = n
        g = 1
        while g * g <= n:
            if n % g == 0:
                graph = n // g  # g = data candidate, n//g = graph >= g
            g += 1
        data = n // graph
    if data * graph != n:
        raise ValueError(f"mesh {data}x{graph} != {n} devices")
    arr = np.asarray(devices).reshape(data, graph)
    return Mesh(arr, axis_names=("data", "graph"))


def make_sharded_evaluate(prog: GraphProgram, mesh: Mesh, num_iters: int):
    """Build fn(q_idx, edge_src, edge_dst) -> x_final [N, B] where q_idx is
    sharded over `data` and the edge arrays over `graph`.  The state vector
    is replicated along `graph`."""
    shard_fn = make_evaluate(
        prog, num_iters, use_while=True, indices_sorted=False,
        combine=lambda y: jax.lax.pmax(y, "graph"),
        changed_reduce=lambda c: jax.lax.pmax(
            c.astype(jnp.int32), ("data", "graph")) > 0,
    )
    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P("data"), P("graph"), P("graph")),
        out_specs=P(None, "data"),
        check_vma=False,  # x is replicated along `graph` by construction
    )


class ShardedKernel:
    """Sharded check/lookup entry points (multi-chip counterpart of
    ops.spmv.KernelCache)."""

    def __init__(self, prog: GraphProgram, mesh: Mesh,
                 num_iters: Optional[int] = None):
        self.prog = prog
        self.mesh = mesh
        self.num_iters = num_iters or MAX_ITERATIONS
        evaluate = make_sharded_evaluate(prog, mesh, self.num_iters)

        def run_checks(q_idx, gather_idx, gather_col, edge_src, edge_dst):
            x = evaluate(q_idx, edge_src, edge_dst)
            return x[gather_idx, gather_col] > 0

        def run_lookup(slot_offset, slot_length, q_idx, edge_src, edge_dst):
            x = evaluate(q_idx, edge_src, edge_dst)
            return jax.lax.dynamic_slice_in_dim(
                x, slot_offset, slot_length, axis=0) > 0

        self._checks = jax.jit(run_checks)
        self._lookup = jax.jit(run_lookup, static_argnums=(0, 1))

    # -- shape discipline ---------------------------------------------------

    def _pad_batch(self, q_idx: np.ndarray) -> np.ndarray:
        n_data = self.mesh.shape["data"]
        b = bucket(max(len(q_idx), 1), max(8, n_data))
        if b % n_data:
            b += n_data - (b % n_data)
        out = np.full(b, self.prog.dead_index, np.int32)
        out[: len(q_idx)] = q_idx
        return out

    def pad_edges_for_mesh(self, capacity: Optional[int] = None) -> tuple:
        n_graph = self.mesh.shape["graph"]
        e = max(len(self.prog.edge_src), 1)
        cap = capacity if capacity is not None else bucket(e)
        if cap % n_graph:
            cap += n_graph - (cap % n_graph)
        return pad_edges(self.prog, cap)

    def device_edges(self, capacity: Optional[int] = None) -> tuple:
        src, dst = self.pad_edges_for_mesh(capacity)
        spec = NamedSharding(self.mesh, P("graph"))
        return (jax.device_put(src, spec), jax.device_put(dst, spec))

    # -- host-facing --------------------------------------------------------

    def lookup(self, slot_offset: int, slot_length: int, q_idx: np.ndarray,
               edge_src, edge_dst) -> np.ndarray:
        q = self._pad_batch(np.asarray(q_idx, np.int32))
        q = jax.device_put(q, NamedSharding(self.mesh, P("data")))
        return np.asarray(self._lookup(slot_offset, slot_length, q,
                                       edge_src, edge_dst))[:, : len(q_idx)]

    def checks(self, q_idx: np.ndarray, gather_idx: np.ndarray,
               gather_col: np.ndarray, edge_src, edge_dst) -> np.ndarray:
        q = self._pad_batch(np.asarray(q_idx, np.int32))
        q = jax.device_put(q, NamedSharding(self.mesh, P("data")))
        g = bucket(max(len(gather_idx), 1), 8)
        gi = np.zeros(g, np.int32)
        gc = np.zeros(g, np.int32)
        gi[: len(gather_idx)] = gather_idx
        gc[: len(gather_col)] = gather_col
        out = np.asarray(self._checks(q, jnp.asarray(gi), jnp.asarray(gc),
                                      edge_src, edge_dst))
        return out[: len(gather_idx)]
