"""Relationship-string template grammar.

Parses `type:id#relation@subjecttype:subjectid(#subjectrelation)` template
strings, where any field may be a `{{ expr }}` template.  Mirrors the
reference grammar exactly (reference: pkg/rules/rules.go:1053-1076, the
`relRegex` non-greedy grammar and its named groups).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


class RelParseError(ValueError):
    pass


# Same non-greedy structure as the reference regex (rules.go:1053-1055).
_REL_RE = re.compile(
    r"^(?P<resourceType>(.*?)):(?P<resourceID>.*?)#(?P<resourceRel>.*?)"
    r"@(?P<subjectType>(.*?)):(?P<subjectID>.*?)(#(?P<subjectRel>.*?))?$"
)


@dataclass
class UncompiledRelExpr:
    """A relationship template whose fields are still uncompiled strings."""
    resource_type: str = ""
    resource_id: str = ""
    resource_relation: str = ""
    subject_type: str = ""
    subject_id: str = ""
    subject_relation: str = ""


@dataclass
class ResolvedRel:
    """A relationship after all template expressions have been evaluated."""
    resource_type: str = ""
    resource_id: str = ""
    resource_relation: str = ""
    subject_type: str = ""
    subject_id: str = ""
    subject_relation: str = ""

    def rel_string(self) -> str:
        s = (f"{self.resource_type}:{self.resource_id}"
             f"#{self.resource_relation}"
             f"@{self.subject_type}:{self.subject_id}")
        if self.subject_relation:
            s += f"#{self.subject_relation}"
        return s

    def key(self) -> tuple:
        return (self.resource_type, self.resource_id, self.resource_relation,
                self.subject_type, self.subject_id, self.subject_relation)


def parse_rel_string(tpl: str) -> UncompiledRelExpr:
    m = _REL_RE.match(tpl)
    if m is None:
        raise RelParseError(f"invalid template: `{tpl}`")
    return UncompiledRelExpr(
        resource_type=m.group("resourceType"),
        resource_id=m.group("resourceID"),
        resource_relation=m.group("resourceRel"),
        subject_type=m.group("subjectType"),
        subject_id=m.group("subjectID"),
        subject_relation=m.group("subjectRel") or "",
    )
