"""Structured request logging (reference pkg/authz/requestlogger.go,
rules.go:242-279): the proxy log line carries user/rule/GVR context and
the authz outcome; per-verb latency lands in a histogram."""

import asyncio
import logging


from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    parse_relationship)

SCHEMA = """
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
"""
RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: ns-read}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
"""


def make_proxy():
    kube = FakeKubeApiServer()
    kube.seed("", "v1", "namespaces", {"metadata": {"name": "ns1"}})
    proxy = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
    ))
    proxy.endpoint.store.bulk_load(
        [parse_relationship("namespace:ns1#viewer@user:alice")])
    return proxy


class TestStructuredLogging:
    def test_allowed_request_logs_kv_fields(self, caplog):
        proxy = make_proxy()
        client = proxy.get_embedded_client(user="alice", groups=["devs"])
        with caplog.at_level(logging.INFO,
                             logger="spicedb_kubeapi_proxy_tpu.proxy"):
            resp = asyncio.run(client.get("/api/v1/namespaces/ns1"))
        assert resp.status == 200
        line = next(r.message for r in caplog.records
                    if "/api/v1/namespaces/ns1" in r.message)
        assert "user='alice'" in line
        assert "groups='devs'" in line
        assert "request.verb='get'" in line
        assert "request.resource='namespaces'" in line
        assert "name='ns1'" in line
        assert "rules='ns-read'" in line
        assert "authz='allowed'" in line
        assert "ms)" in line  # latency recorded

    def test_denied_request_logs_denied_outcome(self, caplog):
        proxy = make_proxy()
        client = proxy.get_embedded_client(user="mallory")
        with caplog.at_level(logging.INFO,
                             logger="spicedb_kubeapi_proxy_tpu.proxy"):
            resp = asyncio.run(client.get("/api/v1/namespaces/ns1"))
        assert resp.status == 403
        line = next(r.message for r in caplog.records
                    if "/api/v1/namespaces/ns1" in r.message)
        assert "user='mallory'" in line
        assert "authz='denied'" in line

    def test_authorization_header_redacted(self, caplog):
        proxy = make_proxy()
        client = proxy.get_embedded_client(user="alice")
        with caplog.at_level(logging.INFO,
                             logger="spicedb_kubeapi_proxy_tpu.proxy"):
            asyncio.run(client.get(
                "/api/v1/namespaces/ns1",
                headers=[("Authorization", "Bearer supersecret")]))
        line = next(r.message for r in caplog.records
                    if "/api/v1/namespaces/ns1" in r.message)
        assert "supersecret" not in line
        assert "[redacted]" in line

    def test_per_verb_latency_histogram(self):
        from spicedb_kubeapi_proxy_tpu.utils.metrics import REGISTRY
        proxy = make_proxy()
        client = proxy.get_embedded_client(user="alice")
        asyncio.run(client.get("/api/v1/namespaces/ns1"))
        rendered = REGISTRY.render()
        assert "proxy_http_request_seconds" in rendered
        assert 'verb="get"' in rendered
