"""Standalone TPU authorization service: serve a permissions endpoint over
gRPC.

The network inverse of the proxy's `--spicedb-endpoint grpc://` mode: run
the `jax://` backend (with cross-request batched dispatch) on the machine
that owns the TPU, and point any number of proxy instances at it —
concurrent RPCs from all of them fuse into device-sized kernel batches
server-side. This replaces running a remote SpiceDB (reference
options.go:331-368) with a remote TPU evaluator behind the same seven-verb
gRPC surface.

    python -m spicedb_kubeapi_proxy_tpu.permsd \\
        --listen-address 0.0.0.0:50051 \\
        --spicedb-endpoint jax:// \\
        --spicedb-bootstrap bootstrap.yaml \\
        --spicedb-token sekrit \\
        [--tls-cert-file cert.pem --tls-key-file key.pem]
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys
from typing import Optional

from .spicedb.endpoints import Bootstrap, create_endpoint
from .spicedb.grpc_remote import PermissionsGrpcServer

log = logging.getLogger("spicedb_kubeapi_proxy_tpu.permsd")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="permsd", description="TPU authorization gRPC service")
    p.add_argument("--listen-address", default="127.0.0.1:50051")
    p.add_argument("--spicedb-endpoint", default="jax://",
                   help="backend to serve: jax:// (default) or embedded://")
    p.add_argument("--spicedb-bootstrap", default="",
                   help="YAML file with bootstrap schema/relationships")
    p.add_argument("--spicedb-token", default="",
                   help="require this bearer token on every RPC")
    p.add_argument("--tls-cert-file", default="")
    p.add_argument("--tls-key-file", default="")
    p.add_argument("-v", "--verbosity", type=int, default=3)
    return p


async def run(args, ready_cb=None) -> None:
    bootstrap: Optional[Bootstrap] = None
    if args.spicedb_bootstrap:
        bootstrap = Bootstrap.from_file(args.spicedb_bootstrap)
    endpoint = create_endpoint(args.spicedb_endpoint, bootstrap=bootstrap)
    tls_cert = tls_key = None
    if args.tls_cert_file and args.tls_key_file:
        # key material loads off-loop (analyzer A001): startup shares
        # this loop with ready_cb-driven embedders, so even here sync
        # file I/O is hopped rather than excused
        loop = asyncio.get_running_loop()

        def _read_bytes(path):
            with open(path, "rb") as f:
                return f.read()

        tls_cert = await loop.run_in_executor(
            None, _read_bytes, args.tls_cert_file)
        tls_key = await loop.run_in_executor(
            None, _read_bytes, args.tls_key_file)
    server = PermissionsGrpcServer(endpoint, token=args.spicedb_token,
                                   tls_cert=tls_cert, tls_key=tls_key)
    port = await server.start(args.listen_address)
    log.info("permsd serving %s on %s (port %d)%s",
             args.spicedb_endpoint, args.listen_address, port,
             " [TLS]" if tls_cert else "")
    if ready_cb is not None:
        ready_cb(port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix
            pass
    try:
        await stop.wait()
    finally:
        await server.stop()
        close = getattr(endpoint, "close", None)
        if close is not None:
            await close()


def main(argv: Optional[list] = None) -> int:
    from .cli import _normalize_argv, _sync_jax_platforms

    _sync_jax_platforms()
    args = build_parser().parse_args(_normalize_argv(
        list(sys.argv[1:] if argv is None else argv)))
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 4 else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    try:
        asyncio.run(run(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
