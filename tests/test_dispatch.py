"""Cross-request batched dispatch tests (SURVEY.md §2 parallelism table):
concurrent callers fuse into device-sized batches, results stay correct,
failures are isolated per request, and create_endpoint wires the wrapper
for jax:// by default."""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    EmbeddedEndpoint,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""


class CountingEndpoint(EmbeddedEndpoint):
    """Embedded endpoint that records inner-call batch sizes."""

    def __init__(self, schema):
        super().__init__(schema)
        self.bulk_calls = []
        self.lr_batch_calls = []
        self.slow = False

    async def check_bulk_permissions(self, reqs):
        self.bulk_calls.append(len(reqs))
        if self.slow:
            await asyncio.sleep(0.01)
        return await super().check_bulk_permissions(reqs)

    async def lookup_resources_batch(self, resource_type, permission, subjects):
        self.lr_batch_calls.append(len(subjects))
        if self.slow:
            await asyncio.sleep(0.01)
        return await super().lookup_resources_batch(
            resource_type, permission, subjects)


def make(n_docs=4, users=("alice", "bob")):
    inner = CountingEndpoint(sch.parse_schema(SCHEMA))
    rels = []
    for i in range(n_docs):
        rels.append(RelationshipUpdate(op=UpdateOp.TOUCH, rel=parse_relationship(
            f"doc:d{i}#viewer@user:{users[i % len(users)]}")))
    inner.store.write(rels)
    return BatchingEndpoint(inner), inner


def check(user, doc="d0"):
    return CheckRequest(resource=ObjectRef("doc", doc), permission="view",
                        subject=SubjectRef("user", user))


def test_concurrent_checks_fuse_into_one_inner_call():
    ep, inner = make()
    inner.slow = True

    async def run():
        # first call occupies the drain loop; the rest accumulate
        first = asyncio.create_task(ep.check_permission(check("alice", "d0")))
        await asyncio.sleep(0.002)
        rest = [asyncio.create_task(ep.check_permission(check(u, d)))
                for u, d in [("alice", "d2"), ("bob", "d1"), ("bob", "d3"),
                             ("alice", "d1")]]
        return [await first] + [await t for t in rest]

    results = asyncio.run(run())
    assert [r.allowed for r in results] == [True, True, True, True, False]
    # call 1: the lone first check; call 2: the four accumulated checks fused
    assert inner.bulk_calls == [1, 4]
    assert ep.stats["fused_checks"] == 2
    assert ep.stats["max_fused_batch"] == 4


def test_concurrent_lookups_fuse_by_type_permission():
    ep, inner = make(n_docs=6)
    inner.slow = True

    async def run():
        first = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0.002)
        rest = [asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", u)))
            for u in ("bob", "alice", "bob")]
        return [sorted(await first)] + [sorted(await t) for t in rest]

    res = asyncio.run(run())
    assert res[0] == ["d0", "d2", "d4"]
    assert res[1] == ["d1", "d3", "d5"]
    assert res[2] == ["d0", "d2", "d4"]
    # the queued bob/alice/bob dedupe to two unique subjects
    # (singleflight): one fused call with 2 members after the leader
    assert inner.lr_batch_calls == [1, 2]
    assert ep.stats["singleflight_hits"] == 1


def test_batch_failure_isolated_per_request():
    ep, inner = make()

    async def run():
        good = ep.check_permission(check("alice", "d0"))
        # unknown definition raises inside the fused call
        bad = ep.check_permission(CheckRequest(
            resource=ObjectRef("nosuchtype", "x"), permission="view",
            subject=SubjectRef("user", "alice")))
        return await asyncio.gather(good, bad, return_exceptions=True)

    good, bad = asyncio.run(run())
    assert good.allowed
    assert isinstance(bad, Exception)


def test_bulk_api_preserves_order_and_duplicates():
    ep, _ = make()

    async def run():
        return await ep.check_bulk_permissions(
            [check("alice", "d0"), check("bob", "d0"),
             check("alice", "d0")])

    res = asyncio.run(run())
    assert [r.allowed for r in res] == [True, False, True]


def test_sequential_calls_have_no_added_latency_path():
    ep, inner = make()

    async def run():
        a = await ep.check_permission(check("alice", "d0"))
        b = await ep.check_permission(check("bob", "d0"))
        return a, b

    a, b = asyncio.run(run())
    assert a.allowed and not b.allowed
    # each sequential call drains immediately (no artificial window)
    assert inner.bulk_calls == [1, 1]


def test_writes_pass_through_and_are_visible():
    ep, inner = make(n_docs=1)

    async def run():
        before = await ep.check_permission(check("bob", "d9"))
        await ep.write_relationships([RelationshipUpdate(
            op=UpdateOp.TOUCH,
            rel=parse_relationship("doc:d9#viewer@user:bob"))])
        after = await ep.check_permission(check("bob", "d9"))
        return before, after

    before, after = asyncio.run(run())
    assert not before.allowed and after.allowed


def test_create_endpoint_wraps_jax_in_batching():
    ep = create_endpoint("jax://")
    assert isinstance(ep, BatchingEndpoint)
    direct = create_endpoint("jax://?dispatch=direct")
    assert not isinstance(direct, BatchingEndpoint)
    custom = create_endpoint("jax://?dispatch=batched&max_batch=128")
    assert isinstance(custom, BatchingEndpoint)
    assert custom.max_batch == 128
    with pytest.raises(Exception):
        create_endpoint("jax://?dispatch=bogus")


def test_stats_merge_inner_backend_counters():
    ep = create_endpoint("jax://")
    s = ep.stats
    assert "drains" in s and "rebuilds" in s


class TwoPhaseInner(EmbeddedEndpoint):
    """Inner endpoint exposing the two-phase fused-lookup pair so the
    dispatcher's double-buffer drain (and its failure paths) run in
    tests without a jax:// backend."""

    def __init__(self, schema):
        super().__init__(schema)
        self.start_calls = 0
        self.finish_calls = 0
        self.fail_start = 0   # fail the next N start calls
        self.fail_finish = 0  # fail the next N finish calls

    async def lookup_resources_batch_start(self, resource_type, permission,
                                           subjects):
        self.start_calls += 1
        if self.fail_start:
            self.fail_start -= 1
            raise RuntimeError("injected start failure")
        return {"rt": resource_type, "perm": permission,
                "subjects": subjects}

    async def lookup_resources_batch_finish(self, ctx):
        self.finish_calls += 1
        if self.fail_finish:
            self.fail_finish -= 1
            raise RuntimeError("injected finish failure")
        return [await self.lookup_resources(ctx["rt"], ctx["perm"], s)
                for s in ctx["subjects"]]


def make_two_phase(n_docs=4):
    schema = sch.parse_schema(SCHEMA)
    inner = TwoPhaseInner(schema)
    rels = [f"doc:d{i}#viewer@user:alice" for i in range(n_docs)]
    inner.store.bulk_load([parse_relationship(r) for r in rels])
    return BatchingEndpoint(inner), inner


def test_two_phase_drain_resolves_all_waiters():
    ep, inner = make_two_phase()

    async def run():
        outs = await asyncio.gather(*[
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice"))
            for _ in range(6)])
        return outs

    outs = asyncio.run(run())
    assert all(sorted(o) == ["d0", "d1", "d2", "d3"] for o in outs)
    assert inner.start_calls >= 1 and inner.finish_calls >= 1


def test_two_phase_start_failure_degrades_to_classic_fused():
    ep, inner = make_two_phase()
    inner.fail_start = 10  # every start fails; classic path must serve

    async def run():
        return await asyncio.gather(*[
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice"))
            for _ in range(4)])

    outs = asyncio.run(run())
    assert all(sorted(o) == ["d0", "d1", "d2", "d3"] for o in outs)


def test_two_phase_finish_failure_retries_individually():
    ep, inner = make_two_phase()
    inner.fail_finish = 10

    async def run():
        return await asyncio.gather(*[
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice"))
            for _ in range(4)])

    outs = asyncio.run(run())
    assert all(sorted(o) == ["d0", "d1", "d2", "d3"] for o in outs)


def test_two_phase_back_to_back_batches_pipeline():
    """Two disjoint (type, permission) buckets queued together drive the
    pipelined branch: batch N+1 starts before batch N finishes, and all
    futures still resolve with correct, bucket-matched results."""
    ep, inner = make_two_phase()

    async def run():
        a = [ep.lookup_resources("doc", "view", SubjectRef("user", "alice"))
             for _ in range(3)]
        b = [ep.lookup_resources("doc", "viewer",
                                 SubjectRef("user", "alice"))
             for _ in range(3)]
        return await asyncio.gather(*(a + b))

    outs = asyncio.run(run())
    assert all(sorted(o) == ["d0", "d1", "d2", "d3"] for o in outs)
    assert inner.start_calls >= 2


def test_cancelled_drain_fails_waiters_instead_of_hanging():
    """Regression (ADVICE round 5): a drain task cancelled mid-batch
    must fail every waiter — in-flight AND queued — not strand their
    futures forever."""
    class BlockingEndpoint(CountingEndpoint):
        gate = None

        async def check_bulk_permissions(self, reqs):
            await self.gate.wait()  # never set: simulates a hung backend
            return await super().check_bulk_permissions(reqs)

    inner = BlockingEndpoint(sch.parse_schema(SCHEMA))
    ep = BatchingEndpoint(inner)

    async def run():
        inner.gate = asyncio.Event()
        first = asyncio.create_task(ep.check_permission(check("alice")))
        await asyncio.sleep(0.01)   # drain running, blocked in the fused call
        second = asyncio.create_task(ep.check_permission(check("bob")))
        await asyncio.sleep(0.01)   # queued behind the in-flight batch
        assert not first.done() and not second.done()
        ep._drain_task.cancel()
        for waiter in (first, second):
            with pytest.raises(RuntimeError, match="drain task cancelled"):
                await asyncio.wait_for(waiter, 2)

    asyncio.run(run())


def test_dying_drain_fails_pending_two_phase_waiters():
    """A started-but-unfinished double-buffered batch (`pending`) must
    also fail when the drain dies during the NEXT batch's blocking
    phase."""
    class ExplodingTwoPhase(CountingEndpoint):
        started = 0

        async def lookup_resources_batch_start(self, rt, perm, subjects):
            self.started += 1
            return ("ctx", rt, perm, subjects)

        async def lookup_resources_batch_finish(self, ctx):
            raise asyncio.CancelledError()  # drain dies inside phase 2

        async def lookup_resources(self, rt, perm, subject):
            raise RuntimeError("retry path must not mask the drain death")

    inner = ExplodingTwoPhase(sch.parse_schema(SCHEMA))
    ep = BatchingEndpoint(inner)

    async def run():
        a = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        b = asyncio.create_task(
            ep.lookup_resources("doc", "viewer", SubjectRef("user", "alice")))
        with pytest.raises((RuntimeError, asyncio.CancelledError)):
            await asyncio.wait_for(a, 2)
        with pytest.raises((RuntimeError, asyncio.CancelledError)):
            await asyncio.wait_for(b, 2)

    asyncio.run(run())


# -- singleflight dedup + queue gauges (decision-cache PR satellites) --------

def test_singleflight_dedupes_identical_queued_lookups():
    """Concurrent IDENTICAL lookups queued behind an in-flight batch
    collapse into one waiter: the fused inner call sees ONE subject and
    every caller receives the shared result."""
    ep, inner = make(n_docs=4, users=("alice",))
    inner.slow = True

    async def run():
        first = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0.002)  # drain now busy with the first call
        rest = [asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
            for _ in range(5)]
        return [sorted(await first)] + [sorted(await t) for t in rest]

    res = asyncio.run(run())
    assert all(r == ["d0", "d1", "d2", "d3"] for r in res)
    # call 1: the lone leader; call 2: the 5 identical queued callers
    # deduped into ONE fused member
    assert inner.lr_batch_calls == [1, 1]
    assert ep.stats["singleflight_hits"] == 4


def test_singleflight_caller_cancellation_does_not_poison_others():
    ep, inner = make(n_docs=2, users=("alice",))
    inner.slow = True

    async def run():
        first = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0.002)
        a = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        b = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0)
        a.cancel()
        out = sorted(await b)
        with pytest.raises(asyncio.CancelledError):
            await a
        await first
        return out

    assert asyncio.run(run()) == ["d0", "d1"]


def test_singleflight_window_closes_at_drain_pickup():
    """An identical query arriving AFTER its twin was picked up by the
    drain must start a fresh query (the in-flight batch drained deltas
    before this arrival: joining it could miss a newer write)."""
    ep, inner = make(n_docs=2, users=("alice",))
    inner.slow = True

    async def run():
        first = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        # wait until the first call is IN FLIGHT (picked up, executing)
        await asyncio.sleep(0.005)
        second = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        await asyncio.gather(first, second)

    asyncio.run(run())
    assert inner.lr_batch_calls == [1, 1]
    assert ep.stats["singleflight_hits"] == 0


def test_stats_export_queue_depth_and_inflight_batch_gauges():
    ep, inner = make()
    s = ep.stats
    assert s["check_queue_depth"] == 0
    assert s["lr_queue_depth"] == 0
    assert s["inflight_batch"] == 0
    assert "singleflight_hits" in s

    async def run():
        inner.slow = True
        first = asyncio.create_task(ep.check_permission(check("alice", "d0")))
        await asyncio.sleep(0.002)  # first batch in flight
        queued = [asyncio.create_task(ep.check_permission(check("bob", "d1")))
                  for _ in range(3)]
        lr = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0)
        depth = ep.stats
        assert depth["inflight_batch"] == 1    # the first check executing
        assert depth["check_queue_depth"] == 3
        assert depth["lr_queue_depth"] == 1
        await asyncio.gather(first, lr, *queued)
        done = ep.stats
        assert done["check_queue_depth"] == 0
        assert done["lr_queue_depth"] == 0
        assert done["inflight_batch"] == 0

    asyncio.run(run())


def test_two_phase_finish_failure_isolates_poison_member():
    """Per-member retry under the two-phase (jax://-shaped) drain: when
    the fused finish fails, each member retries individually — the good
    member succeeds and only the poison member observes its own error."""
    class PartialRetryTwoPhase(TwoPhaseInner):
        async def lookup_resources_batch_finish(self, ctx):
            self.finish_calls += 1
            raise RuntimeError("injected fused finish failure")

        async def lookup_resources(self, resource_type, permission, subject):
            if subject.id == "poison":
                raise RuntimeError("poison member")
            return await super().lookup_resources(
                resource_type, permission, subject)

    schema = sch.parse_schema(SCHEMA)
    inner = PartialRetryTwoPhase(schema)
    inner.store.bulk_load(
        [parse_relationship(f"doc:d{i}#viewer@user:alice") for i in range(3)])
    ep = BatchingEndpoint(inner)

    async def run():
        good = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "alice")))
        bad = asyncio.create_task(
            ep.lookup_resources("doc", "view", SubjectRef("user", "poison")))
        return await asyncio.gather(good, bad, return_exceptions=True)

    good, bad = asyncio.run(run())
    assert sorted(good) == ["d0", "d1", "d2"]
    assert isinstance(bad, RuntimeError) and "poison" in str(bad)
    assert inner.finish_calls >= 1  # the fused phase 2 actually ran+failed


def test_lr_rotation_no_head_of_line_starvation():
    """Fairness regression (two competing keys under load): a hot
    (type, perm) key with a deep backlog must yield the drain to every
    other queued key between its batches — strict rotation, so the cold
    key's single waiter never sits behind the hot key's whole backlog."""
    schema_text = """
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
definition pod {
  relation viewer: user
  permission view = viewer
}
"""
    schema = sch.parse_schema(schema_text)
    inner = CountingEndpoint(schema)
    inner.store.write(
        [RelationshipUpdate(op=UpdateOp.TOUCH, rel=parse_relationship(r))
         for r in ["doc:d0#viewer@user:h0", "pod:p0#viewer@user:cold"]])
    order = []
    orig = inner.lookup_resources_batch

    async def recording(resource_type, permission, subjects):
        order.append((resource_type, len(subjects)))
        return await orig(resource_type, permission, subjects)

    inner.lookup_resources_batch = recording
    inner.slow = True
    ep = BatchingEndpoint(inner, max_batch=2)

    async def run():
        first = asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", "h0")))
        await asyncio.sleep(0.002)
        # hot key backlog: 8 distinct doc subjects = 4 batches at
        # max_batch=2; then ONE cold pod waiter arrives behind them
        hot = [asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", f"h{i}")))
            for i in range(1, 9)]
        await asyncio.sleep(0)
        cold = asyncio.create_task(ep.lookup_resources(
            "pod", "view", SubjectRef("user", "cold")))
        await asyncio.gather(first, cold, *hot)

    asyncio.run(run())
    # drop the lone leader call; the cold key must be served before the
    # hot backlog finishes (rotation), not after all 4 hot batches
    fused = order[1:]
    cold_pos = next(i for i, (t, _n) in enumerate(fused) if t == "pod")
    assert cold_pos <= 1, (
        f"cold key starved behind hot backlog: order={fused}")


def test_cobatched_member_cancellation_mid_fused_batch():
    """Regression (client disconnect): cancelling ONE waiter while its
    fused batch is mid-flight must not poison co-batched members (they
    still get results) and must not leak the singleflight leader (the
    pending map empties; an identical later query starts fresh)."""
    ep, inner = make(n_docs=4, users=("alice", "bob"))
    inner.slow = True

    async def run():
        first = asyncio.create_task(ep.check_permission(check("alice", "d0")))
        await asyncio.sleep(0.002)
        # co-batched: two checks + two lookups (distinct subjects) queue
        # for the next drain
        c_keep = asyncio.create_task(ep.check_permission(check("bob", "d1")))
        c_cancel = asyncio.create_task(
            ep.check_permission(check("alice", "d2")))
        l_keep = asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", "alice")))
        l_cancel = asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", "bob")))
        await asyncio.sleep(0)
        # wait until the co-batch is IN FLIGHT, then disconnect two
        # members mid-batch
        for _ in range(100):
            await asyncio.sleep(0.001)
            if ep.stats["inflight_batch"]:
                break
        c_cancel.cancel()
        l_cancel.cancel()
        keep_res = await c_keep
        keep_ids = sorted(await l_keep)
        with pytest.raises(asyncio.CancelledError):
            await c_cancel
        with pytest.raises(asyncio.CancelledError):
            await l_cancel
        await first
        assert keep_res.allowed
        assert keep_ids == ["d0", "d2"]
        # no singleflight leader leaked for the cancelled lookup: the
        # window closed at pickup and the maps drained with the batch
        assert ep._lr_pending == {}
        assert ep._sf_counts == {}
        # an identical re-issue of the cancelled query starts fresh and
        # completes (nothing poisoned)
        again = sorted(await ep.lookup_resources(
            "doc", "view", SubjectRef("user", "bob")))
        assert again == ["d1", "d3"]

    asyncio.run(run())


def test_cancelled_follower_before_pickup_leader_still_drains():
    """A follower cancelled BEFORE drain pickup leaves the queued leader
    intact: the leader future completes at drain, the pending map entry
    is removed at pickup, and nothing leaks."""
    ep, inner = make(n_docs=2, users=("alice",))
    inner.slow = True

    async def run():
        first = asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0.002)
        doomed = asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", "alice")))
        survivor = asyncio.create_task(ep.lookup_resources(
            "doc", "view", SubjectRef("user", "alice")))
        await asyncio.sleep(0)
        doomed.cancel()
        got = sorted(await survivor)
        with pytest.raises(asyncio.CancelledError):
            await doomed
        await first
        assert got == ["d0", "d1"]
        assert ep._lr_pending == {}

    asyncio.run(run())
