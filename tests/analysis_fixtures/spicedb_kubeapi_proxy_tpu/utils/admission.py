"""A004 true positives (fixture mirrors a gated-module path: this file
"is" utils/admission.py, an AdmissionControl-gated module)."""

_REJECTED = object()
_WINDOW = []
_LIMIT = 0


def note_rejected(reason):
    _REJECTED.inc(reason=reason)          # A004: no gate check


def remember(decision):
    _WINDOW.append(decision)              # A004: module registry append


def set_limit(n):
    global _LIMIT
    _LIMIT = n                            # A004: module global rebound


def bump():
    global _LIMIT
    _LIMIT += 1                           # A004: augmented rebind
