"""Durable relationship store: segmented WAL, columnar checkpoints, and
crash recovery for the in-memory TupleStore (docs/durability.md).

- wal.py        CRC-framed segmented append-only log
- checkpoint.py columnar checkpoint files + the atomic recovery manifest
- manager.py    PersistenceManager: recover / attach / checkpoint loop
"""

from .checkpoint import read_manifest
from .manager import (
    DEFAULT_CHECKPOINT_INTERVAL,
    PersistenceManager,
    PersistenceUnavailableError,
)
from .wal import (
    DEFAULT_SEGMENT_BYTES,
    FSYNC_ALWAYS,
    FSYNC_INTERVAL,
    FSYNC_NEVER,
    FSYNC_POLICIES,
    SegmentedWal,
    WalCorruptionError,
)

__all__ = [
    "DEFAULT_CHECKPOINT_INTERVAL",
    "DEFAULT_SEGMENT_BYTES",
    "FSYNC_ALWAYS",
    "FSYNC_INTERVAL",
    "FSYNC_NEVER",
    "FSYNC_POLICIES",
    "PersistenceManager",
    "PersistenceUnavailableError",
    "SegmentedWal",
    "WalCorruptionError",
    "read_manifest",
]
