#!/usr/bin/env python
"""Differential fuzz smoke: the fixed-seed gate x replication-role matrix.

check.sh mode (default): replays 31 FIXED seeds — 25 mapped onto the
3 gate-combos x 3 replication-roles matrix (every cell covered >= 2x
across the set; kernels alternate ell/segment), plus 2 `sharded2`
cells replaying through a router over TWO partition leaders
(spicedb/sharding, schema-derived co-location-valid map, off/full
gates), plus 2 `mesh` cells replaying on a 2x2 virtual-device mesh
endpoint differentially checked against a single-device endpoint
(parallel/sharding.py, off/full gates), plus 2 `leopard` cells
replaying a nested-groups-biased case on a Leopard-indexed endpoint
differentially checked against a gate-off endpoint (ops/leopard.py,
off/full gates) — asserting ZERO jax://-vs-oracle divergences.  Deterministic: schemas, delta
streams, clocks, and queries all derive from the seed; wall time is the
only thing that varies.  A divergence shrinks to a self-contained repro
artifact (docs/fuzzing.md) and fails the run with its path + seed line.

Cost control (the smoke time box):

- two worker processes (spawned, jax-safe) split the seed set;
- `--xla_backend_optimization_level=0` (tiny graphs need fast COMPILE,
  not fast code) via a re-exec before jax initializes;
- a persistent jax compilation cache under /tmp keyed by HLO, so
  repeat runs (the common check.sh case) skip XLA entirely;
- the smoke case profile (driver.build_case(smoke=True)): bounded
  schema size, short streams, end-state checkpoints.

Other modes:

  --budget-seconds N   open-ended random search (full-depth profile,
                       every checkpoint compared, randomized kernels)
                       starting at --budget-start, until the budget
                       expires; exits nonzero on the first divergence
                       with a shrunken artifact.
  --replay ART.json    re-run a repro artifact's exact cell; exit 1
                       while it still diverges, 0 once fixed.
  --mutation MUT       self-check: inject a deliberate compiler bug
                       (fuzz/mutations.py) and verify the fixed seed
                       set CATCHES it and shrinks it (exit 0 = caught).

Usage: python scripts/fuzz_smoke.py [--time-box 90] [--seeds N]
       [--workers 2] [--budget-seconds N] [--replay path] [--mutation m]
"""

import argparse
import concurrent.futures
import multiprocessing
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_XLA_CACHE_DIR = os.environ.get("FUZZ_XLA_CACHE",
                                "/tmp/authz_fuzz_xla_cache")

if os.environ.get("_FUZZ_SMOKE_REEXEC") != "1":
    # compile-speed flags must be in place before the interpreter (or
    # any sitecustomize) initializes a jax backend — re-exec with them
    # the forced host device count gives the `mesh` cells their 2x2
    # virtual mesh (a no-op for every other cell)
    env = dict(os.environ, _FUZZ_SMOKE_REEXEC="1", JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_backend_optimization_level=0"
                          + " --xla_force_host_platform_device_count=8"))
    os.execve(sys.executable, [sys.executable] + sys.argv, env)

ARTIFACT_DIR = os.environ.get("FUZZ_ARTIFACT_DIR", "/tmp/authz_fuzz")


def _enable_compile_cache() -> None:
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", _XLA_CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass  # older jax: cache is an optimization, not a requirement


def cell_for(seed: int) -> tuple:
    """The fixed (gates, role, kernel) cell a smoke seed lands in —
    delegated to fuzz.driver.smoke_cell_for so tests and the smoke
    agree on what 'the fixed seed set' means."""
    from spicedb_kubeapi_proxy_tpu.fuzz.driver import smoke_cell_for
    return smoke_cell_for(seed)


def _worker_init() -> None:
    _enable_compile_cache()
    import spicedb_kubeapi_proxy_tpu.fuzz  # noqa: F401  (pay import once)


def _run_cell(seed: int) -> dict:
    from spicedb_kubeapi_proxy_tpu.fuzz import build_case, run_case
    gates, role, kernel = cell_for(seed)
    t0 = time.time()
    kw = {}
    if role == "leopard":
        # leopard cells replay the nested-groups shape at the smoke
        # size cap, so membership-only fragments actually materialize
        from spicedb_kubeapi_proxy_tpu.fuzz.scenarios import (
            NESTED_GROUPS_SMOKE_BIAS)
        kw["schema_bias"] = NESTED_GROUPS_SMOKE_BIAS
    case = build_case(seed, smoke=True, kernel=kernel, **kw)
    divs = run_case(case, gates=gates, role=role, checkpoints="final")
    return {"seed": seed, "gates": gates, "role": role, "kernel": kernel,
            "elapsed": time.time() - t0,
            "divergences": [d.line() for d in divs]}


def _shrink_and_report(seed: int, smoke: bool = True,
                       checkpoints: str = "final") -> int:
    """Slow path after a failure: re-find the divergence in-process,
    shrink it, write the artifact; returns the delta count."""
    from spicedb_kubeapi_proxy_tpu.fuzz import build_case, run_case
    from spicedb_kubeapi_proxy_tpu.fuzz.shrink import (
        delta_count, shrink_case, write_artifact)
    gates, role, kernel = cell_for(seed)
    kw = {}
    if role == "leopard":
        from spicedb_kubeapi_proxy_tpu.fuzz.scenarios import (
            NESTED_GROUPS_SMOKE_BIAS)
        kw["schema_bias"] = NESTED_GROUPS_SMOKE_BIAS
    case = build_case(seed, smoke=smoke, kernel=kernel, **kw)
    divs = run_case(case, gates=gates, role=role, checkpoints=checkpoints,
                    stop_on_first=True)
    if not divs:
        print(f"seed {seed}: divergence did not reproduce in-process")
        return -1
    d = divs[0]
    print(d.line())
    small = shrink_case(case, d)
    n = delta_count(small)
    path = os.path.join(ARTIFACT_DIR, f"fuzz-seed{seed}-{gates}-{role}.json")
    write_artifact(path, small, d)
    print(f"shrunk to {n} deltas -> {path}")
    return n


def run_fixed_set(n_seeds: int, workers: int, time_box: float) -> int:
    t0 = time.time()
    seeds = list(range(n_seeds))
    cells_hit = {}
    failed = []
    ctx = multiprocessing.get_context("spawn")
    with concurrent.futures.ProcessPoolExecutor(
            max_workers=workers, mp_context=ctx,
            initializer=_worker_init) as pool:
        for res in pool.map(_run_cell, seeds):
            gates, role = res["gates"], res["role"]
            cells_hit[(gates, role)] = cells_hit.get((gates, role), 0) + 1
            status = "ok" if not res["divergences"] else "DIVERGED"
            print(f"seed {res['seed']:3d} [{gates:5s}/{role:9s}/"
                  f"{res['kernel']:7s}] {status} in "
                  f"{res['elapsed']:4.1f}s")
            if res["divergences"]:
                failed.append(res)
    elapsed = time.time() - t0
    # matrix-coverage tripwire (a real error path, not an assert: it
    # must survive python -O and scale with --seeds).  The expectation
    # is INDEPENDENT of smoke_cell_for — derived from the documented
    # walk (seeds 0..24 = classic 3x3 matrix, 25..26 = sharded2 cells
    # alternating off/full, 27..28 = mesh cells alternating off/full,
    # >= 29 = leopard cells alternating off/full) — so a regression in
    # the seed->cell map itself trips here instead of validating its
    # own output.
    n_classic = min(n_seeds, 25)
    n_sharded = min(max(0, n_seeds - 25), 2)
    n_mesh = min(max(0, n_seeds - 27), 2)
    n_leopard = max(0, n_seeds - 29)
    classic_hit = {c: v for c, v in cells_hit.items()
                   if c[1] not in ("sharded2", "mesh", "leopard")}
    sharded_hit = {c: v for c, v in cells_hit.items()
                   if c[1] == "sharded2"}
    mesh_hit = {c: v for c, v in cells_hit.items()
                if c[1] == "mesh"}
    leopard_hit = {c: v for c, v in cells_hit.items()
                   if c[1] == "leopard"}
    want_sharded = {k: v for k, v in (
        (("off", "sharded2"), (n_sharded + 1) // 2),
        (("full", "sharded2"), n_sharded // 2)) if v}
    want_mesh = {k: v for k, v in (
        (("off", "mesh"), (n_mesh + 1) // 2),
        (("full", "mesh"), n_mesh // 2)) if v}
    want_leopard = {k: v for k, v in (
        (("off", "leopard"), (n_leopard + 1) // 2),
        (("full", "leopard"), n_leopard // 2)) if v}
    if (len(classic_hit) != min(9, n_classic)
            or sum(classic_hit.values()) != n_classic
            or any(v < max(1, n_classic // 9)
                   for v in classic_hit.values())
            or sharded_hit != want_sharded
            or mesh_hit != want_mesh
            or leopard_hit != want_leopard):
        print(f"fuzz smoke: matrix coverage hole at --seeds {n_seeds}: "
              f"classic {dict(classic_hit)}, sharded {dict(sharded_hit)}, "
              f"mesh {dict(mesh_hit)}, leopard {dict(leopard_hit)} "
              f"(want {min(9, n_classic)} classic cells x >= "
              f"{max(1, n_classic // 9)}, sharded {dict(want_sharded)}, "
              f"mesh {dict(want_mesh)}, leopard {dict(want_leopard)})")
        return 1
    if failed:
        for res in failed:
            for line in res["divergences"]:
                print(line)
            _shrink_and_report(res["seed"])
        print(f"fuzz smoke: {len(failed)}/{n_seeds} seeds DIVERGED "
              f"in {elapsed:.1f}s")
        return 1
    print(f"fuzz smoke: {n_seeds} seeds x 3 gate combos x 3 replication "
          f"roles (+ {n_sharded} sharded2 router cells, + {n_mesh} mesh "
          f"cells, + {n_leopard} leopard cells) AGREE in {elapsed:.1f}s")
    if elapsed > time_box:
        print(f"fuzz smoke: exceeded the {time_box:.0f}s time box")
        return 1
    return 0


def run_budgeted(budget_s: float, start_seed: int, scenario: str = "") -> int:
    """Open-ended search: full-depth cases, every checkpoint compared,
    randomized cells — until the budget expires.  `scenario` steers the
    generators with a fuzz/scenarios.py bias profile."""
    _enable_compile_cache()
    from spicedb_kubeapi_proxy_tpu.fuzz import build_case, run_case
    from spicedb_kubeapi_proxy_tpu.fuzz.scenarios import SCENARIO_BIASES
    from spicedb_kubeapi_proxy_tpu.fuzz.shrink import (
        delta_count, shrink_case, write_artifact)
    from spicedb_kubeapi_proxy_tpu.fuzz.driver import (
        ALL_ROLES, GATE_COMBOS, SMOKE_KERNELS)
    bias_kw = {}
    if scenario:
        sb, db = SCENARIO_BIASES[scenario]
        bias_kw = {"schema_bias": sb, "delta_bias": db}
    t0 = time.time()
    seed = start_seed
    n = 0
    while time.time() - t0 < budget_s:
        gates = tuple(GATE_COMBOS)[seed % 3]
        role = ALL_ROLES[(seed // 3) % len(ALL_ROLES)]
        kernel = SMOKE_KERNELS[(seed // 9) % 2]
        if role == "mesh":
            kernel = "ell"  # the mesh path requires the ell kernel
        case = build_case(seed, kernel=kernel, **bias_kw)
        divs = run_case(case, gates=gates, role=role, checkpoints="every",
                        stop_on_first=True)
        n += 1
        print(f"seed {seed} [{gates}/{role}/{kernel}] "
              f"{'ok' if not divs else 'DIVERGED'} "
              f"({time.time() - t0:.0f}s/{budget_s:.0f}s)")
        if divs:
            d = divs[0]
            print(d.line())
            small = shrink_case(case, d)
            path = os.path.join(
                ARTIFACT_DIR, f"fuzz-seed{seed}-{gates}-{role}.json")
            write_artifact(path, small, d)
            print(f"shrunk to {delta_count(small)} deltas -> {path}")
            return 1
        seed += 1
    print(f"budgeted fuzz: {n} cells agree in {time.time() - t0:.0f}s")
    return 0


def run_replay(path: str) -> int:
    _enable_compile_cache()
    from spicedb_kubeapi_proxy_tpu.fuzz import replay_artifact
    divs = replay_artifact(path)
    if divs:
        for d in divs:
            print(d.line())
        print(f"replay {path}: still diverges")
        return 1
    print(f"replay {path}: agrees (fixed)")
    return 0


def run_mutation_check(name: str, n_seeds: int) -> int:
    """Harness self-check: with a deliberately broken device compiler,
    the fixed seed set must catch a divergence and shrink it small."""
    _enable_compile_cache()
    from spicedb_kubeapi_proxy_tpu.fuzz import build_case, run_case
    from spicedb_kubeapi_proxy_tpu.fuzz.mutations import MUTATIONS
    from spicedb_kubeapi_proxy_tpu.fuzz.shrink import (
        delta_count, shrink_case, write_artifact)
    with MUTATIONS[name]():
        for seed in range(n_seeds):
            gates, role, kernel = cell_for(seed)
            case = build_case(seed, smoke=True, kernel=kernel)
            divs = run_case(case, gates=gates, role=role,
                            checkpoints="final", stop_on_first=True)
            print(f"seed {seed} [{gates}/{role}/{kernel}] "
                  f"{'ok' if not divs else 'CAUGHT'}")
            if not divs:
                continue
            d = divs[0]
            print(d.line())
            small = shrink_case(case, d)
            n = delta_count(small)
            path = os.path.join(ARTIFACT_DIR, f"mutation-{name}.json")
            write_artifact(path, small, d)
            print(f"mutation {name!r}: caught at seed {seed}, shrunk to "
                  f"{n} deltas -> {path}")
            return 0
    print(f"mutation {name!r}: NOT caught by the fixed seed set")
    return 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=31,
                    help="seeds 0..24 walk the classic 3x3 gate x role "
                         "matrix; seeds 25..26 are the appended sharded2 "
                         "(2-partition-leader router) cells; seeds 27..28 "
                         "are the mesh (2x2 virtual-device) cells; seeds "
                         "29+ are the leopard (indexed vs gate-off) cells")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--time-box", type=float, default=90.0,
                    help="hard wall-clock bound for the fixed set "
                         "(generous vs the ~15s warm-cache typical run: "
                         "cold XLA caches + CI contention headroom)")
    ap.add_argument("--budget-seconds", type=float, default=0.0)
    ap.add_argument("--budget-start", type=int, default=1000)
    ap.add_argument("--scenario", default="", choices=(
        "", "caveat-heavy", "wildcard-public", "ephemeral-grants",
        "nested-groups"),
        help="steer the budgeted search with a scenario bias profile")
    ap.add_argument("--replay", default="")
    ap.add_argument("--mutation", default="",
                    help="inject a named mutation (fuzz/mutations.py) "
                         "and require the fixed set to catch it")
    args = ap.parse_args()
    if args.replay:
        return run_replay(args.replay)
    if args.mutation:
        return run_mutation_check(args.mutation, args.seeds)
    if args.budget_seconds > 0:
        return run_budgeted(args.budget_seconds, args.budget_start,
                            scenario=args.scenario)
    return run_fixed_set(args.seeds, args.workers, args.time_box)


if __name__ == "__main__":
    sys.exit(main())
