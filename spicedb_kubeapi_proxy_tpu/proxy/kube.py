"""Kubernetes request metadata types and URL parsing.

Python equivalents of the k8s.io/apiserver types the reference leans on:
`request.RequestInfo` (populated by the RequestInfo filter in the handler
chain, reference pkg/proxy/server.go:157) and `user.DefaultInfo`.  The parser
follows the upstream RequestInfoFactory conventions for API paths:

  /api/v1[/namespaces/{ns}]/{resource}[/{name}[/{subresource}]]
  /apis/{group}/{version}[/namespaces/{ns}]/{resource}[/{name}[/{subresource}]]

with verb derivation: GET -> get/list/watch (list when no name, watch when
`watch=true`), POST -> create, PUT -> update, PATCH -> patch,
DELETE -> delete/deletecollection.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from urllib.parse import parse_qs, urlsplit


@dataclass
class RequestInfo:
    is_resource_request: bool = False
    path: str = ""
    verb: str = ""
    api_prefix: str = ""
    api_group: str = ""
    api_version: str = ""
    namespace: str = ""
    resource: str = ""
    subresource: str = ""
    name: str = ""
    parts: list = field(default_factory=list)
    label_selector: str = ""
    field_selector: str = ""


@dataclass
class UserInfo:
    name: str = ""
    uid: str = ""
    groups: list = field(default_factory=list)
    extra: dict = field(default_factory=dict)


# Subresources of the namespace object itself (upstream RequestInfoFactory's
# namespaceSubresources set): /namespaces/{ns}/status addresses the namespace,
# while /namespaces/{ns}/{resource} addresses resources within it.
_NAMESPACE_SUBRESOURCES = {"status", "finalize"}


def parse_request_info(method: str, url: str) -> RequestInfo:
    """Derive RequestInfo from an HTTP method + URL (path and query)."""
    split = urlsplit(url)
    path = split.path
    query = parse_qs(split.query)

    info = RequestInfo(path=path)
    info.label_selector = (query.get("labelSelector") or [""])[0]
    info.field_selector = (query.get("fieldSelector") or [""])[0]

    parts = [p for p in path.split("/") if p]
    if not parts or parts[0] not in ("api", "apis"):
        info.verb = _nonresource_verb(method)
        return info

    info.api_prefix = parts[0]
    rest: list[str]
    if parts[0] == "api":
        # core group: /api/v1/...
        if len(parts) < 2:
            info.verb = _nonresource_verb(method)
            return info
        info.api_group = ""
        info.api_version = parts[1]
        rest = parts[2:]
    else:
        # /apis/{group}/{version}/...
        if len(parts) < 3:
            info.verb = _nonresource_verb(method)
            return info
        info.api_group = parts[1]
        info.api_version = parts[2]
        rest = parts[3:]

    if not rest:
        info.verb = _nonresource_verb(method)
        return info

    info.is_resource_request = True

    # Upstream's "watch" path prefix (legacy /watch/...) also exists; handle
    # the common modern form (watch=true query) plus the legacy prefix.
    legacy_watch = False
    if rest and rest[0] == "watch":
        legacy_watch = True
        rest = rest[1:]

    # Upstream convention: /namespaces/{ns}/{resource}/... addresses resources
    # inside the namespace; /namespaces/{ns}[/status|/finalize] addresses the
    # namespace object itself (namespace stays set to {ns} in both cases).
    if rest and rest[0] == "namespaces":
        if len(rest) > 1:
            info.namespace = rest[1]
            if len(rest) > 2 and rest[2] not in _NAMESPACE_SUBRESOURCES:
                rest = rest[2:]
    if rest:
        info.resource = rest[0]
        if len(rest) >= 2:
            info.name = rest[1]
        if len(rest) >= 3:
            info.subresource = rest[2]
    info.parts = rest

    watching = legacy_watch or (query.get("watch") or ["false"])[0] in ("true", "1")
    method = method.upper()
    if method == "GET":
        if watching:
            info.verb = "watch"
        elif info.name:
            info.verb = "get"
        else:
            info.verb = "list"
    elif method == "POST":
        info.verb = "create"
    elif method == "PUT":
        info.verb = "update"
    elif method == "PATCH":
        info.verb = "patch"
    elif method == "DELETE":
        info.verb = "delete" if info.name else "deletecollection"
    else:
        info.verb = ""
    return info


def _nonresource_verb(method: str) -> str:
    return {
        "GET": "get", "HEAD": "get", "POST": "post",
        "PUT": "put", "PATCH": "patch", "DELETE": "delete",
    }.get(method.upper(), "")
