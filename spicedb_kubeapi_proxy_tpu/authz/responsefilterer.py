"""Response filtering (reference pkg/authz/responsefilterer.go).

- StandardResponseFilterer: waits (≤10s) for the concurrently-running
  prefilter LookupResources, then filters list/object/Table response bodies
  against the allowed NamespacedName set.  Filter-denied single objects
  surface as 401 Unauthorized with a kube Status body; an empty filtered
  body becomes 404 (reference responsefilterer.go:716-735).
- WatchResponseFilterer: wraps the upstream watch stream; raw frames are
  replayed byte-exactly when allowed, buffered per NamespacedName until
  allowed, and dropped + unbuffered on revocation; Status events pass
  through (reference responsefilterer.go:423-714).
- EmptyResponseFilterer: pass-through for alwaysAllow requests.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from ..proxy.httpcore import Request, Response
from ..proxy.kube import RequestInfo
from ..proxy.restmapper import CachingRESTMapper, NoKindMatchError
from ..rules.engine import (
    ResolveInput,
    ResolvedPreFilter,
    RunnableRule,
    resolve_rel,
)
from ..spicedb.endpoints import PermissionsEndpoint
from ..utils.tracing import span
from .lookups import PrefilterResult, run_lookup_resources
from .rulesel import single_pre_filter_rule
from .watch import WatchTracker, run_watch

PREFILTER_TIMEOUT = 10.0
# max not-yet-authorized frames buffered per watch (overflow drops oldest)
WATCH_BUFFER_CAP = 1024


class FilterError(Exception):
    pass


def _unauthorized_status(message: str) -> dict:
    return {
        "kind": "Status", "apiVersion": "v1", "metadata": {},
        "status": "Failure", "message": message, "reason": "Unauthorized",
        "code": 401,
    }


class ResponseFilterer:
    async def filter_resp(self, resp: Response, req: Request) -> None:
        raise NotImplementedError


class EmptyResponseFilterer(ResponseFilterer):
    async def filter_resp(self, resp: Response, req: Request) -> None:
        return None


class StandardResponseFilterer(ResponseFilterer):
    def __init__(self, rest_mapper: CachingRESTMapper, input: ResolveInput,
                 filtered_rules: list, endpoint: Optional[PermissionsEndpoint]):
        self.rest_mapper = rest_mapper
        self.input = input
        self.filtered_rules = filtered_rules
        self.endpoint = endpoint
        self._prefilter_started = False
        self._prefilter_future: Optional[asyncio.Future] = None

    def run_pre_filters(self) -> None:
        """Start the LR concurrently with the upstream request
        (reference responsefilterer.go:120-185)."""
        if self._prefilter_started:
            raise FilterError("pre-filters already started, cannot run again")
        self._prefilter_started = True

        rule = single_pre_filter_rule(self.filtered_rules)
        loop = asyncio.get_event_loop()
        self._prefilter_future = loop.create_future()
        if rule is None:
            self._prefilter_future.set_result(PrefilterResult(all_allowed=True))
            return
        if len(rule.pre_filter) != 1:
            raise FilterError(
                "pre-filter rule must have exactly one filter defined")
        f = rule.pre_filter[0]
        rel = resolve_rel(f.rel, self.input)
        resolved = ResolvedPreFilter(
            name_from_object_id=f.name_from_object_id,
            namespace_from_object_id=f.namespace_from_object_id,
            rel=rel,
        )

        async def runner():
            try:
                # the LR runs concurrently with the upstream request; the
                # task inherits the request's trace context, so the
                # kernel spans it triggers land in the request trace even
                # though respfilter only WAITS for it.  NOT a phase span:
                # it overlaps the `upstream` phase in wall time, and the
                # phase set must tile the request without double-counting
                with span("prefilter"):
                    result = await run_lookup_resources(self.endpoint,
                                                        resolved, self.input)
                if not self._prefilter_future.done():
                    self._prefilter_future.set_result(result)
            except Exception as e:
                if not self._prefilter_future.done():
                    self._prefilter_future.set_exception(e)

        asyncio.ensure_future(runner())

    async def filter_resp(self, resp: Response, req: Request) -> None:
        if not self._prefilter_started:
            raise FilterError("pre-filters were not started, cannot filter response")
        try:
            # the wait is NOT the respfilter phase: its wall time is the
            # concurrent prefilter's (already attributed) — folding it in
            # would double-count kernel time against filtering
            with span("respfilter.wait"):
                result = await asyncio.wait_for(
                    asyncio.shield(self._prefilter_future), PREFILTER_TIMEOUT)
        except asyncio.TimeoutError:
            raise FilterError("timed out waiting for pre-filter") from None
        except FilterError:
            raise
        except Exception as e:
            raise FilterError(f"pre-filter error: {e}") from e

        with span("respfilter", phase=True):
            await self._apply_filters(resp, req, result)

    async def _apply_filters(self, resp: Response, req: Request,
                             result: PrefilterResult) -> None:
        info: RequestInfo = req.context["request_info"]
        # error responses pass through unfiltered (responsefilterer.go:229-234)
        if 400 <= resp.status <= 599:
            return

        from ..proxy import k8sproto

        # a Table request short-circuits GVK handling
        if "as=Table" in req.headers.get("Accept", ""):
            if k8sproto.is_k8s_proto(resp.body):
                try:
                    body = self._filter_table_proto(resp.body, result)
                except k8sproto.K8sProtoError as e:
                    raise FilterError(
                        f"error decoding protobuf table: {e}") from e
                self._write_resp(resp, body, None)
                return
            try:
                body, err = self._filter_table(resp.body, result)
            except ValueError as e:
                raise FilterError(f"error decoding table: {e}") from e
            self._write_resp(resp, body, err)
            return

        content_type = resp.headers.get("Content-Type", "application/json")
        media = content_type.split(";")[0].strip()
        if "json" not in media:
            if k8sproto.is_k8s_proto(resp.body):
                # negotiated protobuf body: filter at the wire level
                # (reference responsefilterer.go:241-301; unparseable
                # bodies reject like unrecognized-GVK proto at 278-280)
                await self._filter_proto(resp, info, result)
                return
            gvk = await self._gvk(info)
            raise FilterError(
                f"unsupported media type {media} for gvk {gvk}")

        try:
            decoded = json.loads(resp.body) if resp.body else {}
        except ValueError as e:
            raise FilterError(f"failed to decode response body: {e}") from e

        if len(info.parts) == 1:
            # list response
            err = self._filter_list(decoded, result)
            body = b"" if err else json.dumps(decoded).encode()
            self._write_resp(resp, body, err)
        else:
            err = self._filter_object(decoded, result)
            self._write_resp(resp, resp.body if not err else b"", err)

    async def _gvk(self, info: RequestInfo):
        try:
            return await self.rest_mapper.kind_for(
                info.api_group, info.api_version, info.resource)
        except NoKindMatchError as e:
            raise FilterError(str(e)) from e

    async def _filter_proto(self, resp: Response, info: RequestInfo,
                            result: PrefilterResult) -> None:
        """Filter a `k8s\\x00`-enveloped protobuf list/object body by
        wire-level splicing (proxy/k8sproto.py)."""
        from ..proxy import k8sproto

        try:
            api_version, kind, raw, ct = k8sproto.decode_unknown(resp.body)
            if len(info.parts) == 1 and kind.endswith("List"):
                filtered = k8sproto.filter_list_raw(raw, result.is_allowed)
                body = k8sproto.encode_unknown(api_version, kind, filtered, ct)
                self._write_resp(resp, body, None)
            else:
                namespace, name = k8sproto.object_meta(raw)
                if result.is_allowed(namespace, name):
                    self._write_resp(resp, resp.body, None)
                else:
                    self._write_resp(resp, b"", FilterError("unauthorized"))
        except k8sproto.K8sProtoError as e:
            raise FilterError(
                f"unable to filter protobuf body for gvk "
                f"{await self._gvk(info)}: {e}") from e

    def _filter_table_proto(self, body: bytes, result: PrefilterResult) -> bytes:
        from ..proxy import k8sproto

        api_version, kind, raw, ct = k8sproto.decode_unknown(body)
        filtered = k8sproto.filter_table_raw(raw, result.is_allowed)
        return k8sproto.encode_unknown(api_version, kind, filtered, ct)

    def _filter_table(self, body: bytes, result: PrefilterResult) -> tuple:
        table = json.loads(body)
        rows = table.get("rows") or []
        allowed_rows = []
        for r in rows:
            pom = (r.get("object") or {}).get("metadata") or {}
            if result.is_allowed(pom.get("namespace", "") or "",
                                 pom.get("name", "") or ""):
                allowed_rows.append(r)
        table["rows"] = allowed_rows
        return json.dumps(table).encode(), None

    def _filter_list(self, decoded: dict, result: PrefilterResult):
        items = decoded.get("items")
        if not isinstance(items, list):
            return None
        allowed = []
        for item in items:
            meta = (item.get("metadata") or {}) if isinstance(item, dict) else {}
            if result.is_allowed(meta.get("namespace", "") or "",
                                 meta.get("name", "") or ""):
                allowed.append(item)
        decoded["items"] = allowed
        return None

    def _filter_object(self, decoded: dict, result: PrefilterResult):
        meta = decoded.get("metadata") or {}
        if result.is_allowed(meta.get("namespace", "") or "",
                             meta.get("name", "") or ""):
            return None
        return FilterError("unauthorized")

    @staticmethod
    def _write_resp(resp: Response, body: bytes, err) -> None:
        """401-on-error / 404-on-empty (reference responsefilterer.go:716-735)."""
        if err is not None:
            body = json.dumps(_unauthorized_status(str(err))).encode()
            resp.status = 401
        resp.body = body
        resp.headers.set("Content-Length", str(len(body)))
        if len(body) == 0:
            resp.status = 404


def new_empty_response_filterer(rest_mapper, input) -> EmptyResponseFilterer:
    return EmptyResponseFilterer()


class WatchResponseFilterer(ResponseFilterer):
    def __init__(self, rest_mapper: CachingRESTMapper, input: ResolveInput,
                 watch_rule: RunnableRule, endpoint: PermissionsEndpoint):
        self.rest_mapper = rest_mapper
        self.input = input
        self.watch_rule = watch_rule
        self.endpoint = endpoint
        self._tracker: Optional[WatchTracker] = None
        self._watch_task: Optional[asyncio.Task] = None

    def run_watcher(self) -> None:
        """Start the SpiceDB-side watch (reference responsefilterer.go:434-460)."""
        if self._tracker is not None:
            raise FilterError("watcher already started, cannot run again")
        if len(self.watch_rule.pre_filter) != 1:
            raise FilterError("watch rule must have exactly one pre-filter defined")
        f = self.watch_rule.pre_filter[0]
        rel = resolve_rel(f.rel, self.input)
        resolved = ResolvedPreFilter(
            name_from_object_id=f.name_from_object_id,
            namespace_from_object_id=f.namespace_from_object_id,
            rel=rel,
        )
        self._tracker = WatchTracker()
        # subscribe synchronously: tuple writes racing the watch setup must
        # not be lost before the watch task first runs
        watcher = self.endpoint.watch([resolved.rel.resource_type])
        self._watch_task = asyncio.ensure_future(
            run_watch(self.endpoint, self._tracker, resolved, self.input,
                      watcher=watcher))

    async def filter_resp(self, resp: Response, req: Request) -> None:
        if self._tracker is None:
            raise FilterError("watcher was not started, cannot filter response")
        if resp.stream is None:
            return  # error responses pass through
        with span("respfilter", phase=True):
            self._wrap_stream(resp)

    def _wrap_stream(self, resp: Response) -> None:
        upstream = resp.stream
        # the upstream Content-Type decides the stream framing/codec, the
        # analog of the reference's negotiated streaming serializer
        # (responsefilterer.go:500-506)
        content_type = resp.headers.get("Content-Type", "")
        proto = "protobuf" in content_type
        resp.stream = self._filtered_stream(upstream, proto=proto)

    @staticmethod
    def _decode_frame(raw: bytes, proto: bool) -> tuple:
        """(event_type, namespace, name, is_status) for one raw frame.
        Raises ValueError when the frame cannot be decoded — the caller
        must DROP such frames (fail closed), never relay them."""
        if proto:
            from ..proxy import k8sproto

            try:
                ev, api_version, kind, obj_raw = k8sproto.decode_watch_event(
                    raw[4:])
                if ev == "ERROR" or kind == "Status":
                    return ev, "", "", True
                # Table event unwrapping (responsefilterer.go:667-677)
                if kind == "Table" and "meta.k8s.io" in api_version:
                    namespace, name = k8sproto.table_first_row_meta(obj_raw)
                else:
                    namespace, name = k8sproto.object_meta(obj_raw)
            except k8sproto.K8sProtoError as e:
                raise ValueError(str(e)) from e
            return ev, namespace, name, False
        event = json.loads(raw)  # ValueError propagates to the caller
        if not isinstance(event, dict):
            raise ValueError("watch frame is not a JSON object")
        obj = event.get("object") or {}
        ev = event.get("type", "")
        if ev == "ERROR" or obj.get("kind") == "Status":
            return ev, "", "", True
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        namespace = meta.get("namespace", "")
        # Table event unwrapping (responsefilterer.go:667-677)
        if (obj.get("kind") == "Table"
                and "meta.k8s.io" in obj.get("apiVersion", "")):
            for r in obj.get("rows") or []:
                rmeta = (r.get("object") or {}).get("metadata") or {}
                name = rmeta.get("name", "")
                namespace = rmeta.get("namespace", "")
                break
        return ev, namespace, name, False

    async def _filtered_stream(self, upstream, proto: bool = False):
        """Replay / buffer / revoke raw frames
        (reference responsefilterer.go:487-714)."""
        from .frames import frame_length_delimited, frame_lines

        framer = frame_length_delimited if proto else frame_lines
        merged: asyncio.Queue = asyncio.Queue()

        async def pump_upstream():
            try:
                async for raw in framer(upstream):
                    await merged.put(("frame", raw))
            finally:
                await merged.put(("eof", None))

        async def pump_changes():
            while True:
                change = await self._tracker.changes.get()
                await merged.put(("change", change))

        pump1 = asyncio.ensure_future(pump_upstream())
        pump2 = asyncio.ensure_future(pump_changes())
        allowed: set = set()
        # bounded not-yet-authorized frame buffer: a watch on a resource
        # the subject will never be granted must not grow memory without
        # limit — overflow drops the OLDEST buffered frame (the client
        # re-lists on resume, matching kube watch semantics)
        buffered: dict = {}
        try:
            while True:
                kind, payload = await merged.get()
                if kind == "eof":
                    return
                if kind == "change":
                    nn = (payload.namespace, payload.name)
                    if payload.allowed:
                        allowed.add(nn)
                        if nn in buffered:
                            raw = buffered.pop(nn)
                            yield raw
                    else:
                        allowed.discard(nn)
                        buffered.pop(nn, None)
                    continue
                raw = payload
                try:
                    ev, namespace, name, is_status = self._decode_frame(
                        raw, proto)
                except ValueError as e:
                    # FAIL CLOSED: an undecodable frame may carry an object
                    # we cannot authorize — drop it with an error, never
                    # relay it (this path previously passed frames through
                    # unfiltered, an authorization bypass)
                    import logging
                    logging.getLogger(__name__).error(
                        "dropping undecodable watch frame (%d bytes, "
                        "proto=%s): %s", len(raw), proto, e)
                    continue
                if is_status:
                    # status events pass through and the stream CONTINUES
                    # (reference responsefilterer.go:645-651 writes the
                    # chunk and keeps reading)
                    yield raw
                    continue
                if ev in ("ADDED", "MODIFIED"):
                    nn = (namespace or "", name)
                    if nn in allowed:
                        yield raw
                    else:
                        buffered[nn] = raw
                        if len(buffered) > WATCH_BUFFER_CAP:
                            victim = next(iter(buffered))
                            buffered.pop(victim)
                            import logging
                            logging.getLogger(__name__).warning(
                                "watch buffer cap %d exceeded; dropped "
                                "buffered frame for %s", WATCH_BUFFER_CAP,
                                victim)
                # DELETED / BOOKMARK events: the reference neither replays nor
                # buffers them (only ADDED/MODIFIED are handled)
        finally:
            pump1.cancel()
            pump2.cancel()
            if self._watch_task is not None:
                self._watch_task.cancel()
